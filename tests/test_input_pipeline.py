"""Streaming double-buffered input pipeline (workflow/input_pipeline):
chunk-boundary correctness (pipelined model bit-identical to the
single-shot path on CPU), worker-exception propagation, backpressure /
bounded-buffer behavior, and clean shutdown mid-stream."""

import threading
import time

import numpy as np
import pytest

from incubator_predictionio_tpu.workflow.input_pipeline import (
    PipelineConfig,
    PipelineStats,
    PipelineWorkerError,
    chunk_ranges,
    host_parallel,
    prefetch,
    run_pipeline,
)

OFF = PipelineConfig(mode="off")


def _on(**kw):
    kw.setdefault("mode", "on")
    return PipelineConfig(**kw)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_chunk_ranges_cover_exactly():
    assert chunk_ranges(0, 10) == []
    assert chunk_ranges(5, 10) == [(0, 5)]
    assert chunk_ranges(10, 10) == [(0, 10)]
    assert chunk_ranges(25, 10) == [(0, 10), (10, 20), (20, 25)]


def test_prefetch_preserves_order():
    out = list(prefetch(range(50), lambda v: v * v, workers=4, lookahead=3))
    assert out == [v * v for v in range(50)]


def test_prefetch_backpressure_bounds_lookahead():
    """Workers must stall on a slow consumer: at any time at most
    ``lookahead`` items are started-but-not-consumed (bounded host
    memory), never the whole input."""
    lookahead = 3
    started, consumed = [], []
    lock = threading.Lock()
    max_ahead = 0

    def fn(v):
        with lock:
            started.append(v)
        return v

    gen = prefetch(range(40), fn, workers=4, lookahead=lookahead)
    for v in gen:
        time.sleep(0.002)  # slow consumer
        with lock:
            consumed.append(v)
            max_ahead = max(max_ahead, len(started) - len(consumed))
    assert consumed == list(range(40))
    assert max_ahead <= lookahead + 1  # +1: the item being yielded


def test_prefetch_worker_exception_propagates():
    def fn(v):
        if v == 7:
            raise ValueError("boom at 7")
        return v

    gen = prefetch(range(20), fn, workers=2, lookahead=2)
    got = []
    with pytest.raises(PipelineWorkerError) as e:
        for v in gen:
            got.append(v)
    assert got == list(range(7))
    assert isinstance(e.value.__cause__, ValueError)
    assert "boom at 7" in str(e.value)


def test_prefetch_clean_shutdown_midstream():
    """Breaking out of the consumer loop (generator close) must stop
    the workers — no runaway featurize of the remaining input, no
    leaked threads."""
    processed = []
    lock = threading.Lock()

    def fn(v):
        with lock:
            processed.append(v)
        return v

    before = threading.active_count()
    gen = prefetch(range(10_000), fn, workers=2, lookahead=2)
    for v in gen:
        if v >= 2:
            break
    gen.close()  # explicit close; a dropped generator does the same
    # pool joined: only items already submitted before the close ran
    assert len(processed) <= 2 + 2 + 2 + 1  # consumed + lookahead margin
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_run_pipeline_bounds_inflight_ring():
    uploads, consumed = [], []

    def upload(c):
        uploads.append(c)
        return np.asarray([c])

    def consume(dev):
        consumed.append(int(dev[0]))
        return dev  # token: numpy passes block_until_ready untouched

    stats = PipelineStats()
    n = run_pipeline(iter(range(9)), upload, consume, depth=2, stats=stats)
    assert n == 9
    assert consumed == list(range(9))
    assert stats.max_inflight <= 2
    assert stats.n_chunks == 9


def test_run_pipeline_closes_source_on_consume_error():
    closed = []

    def chunks():
        try:
            for v in range(100):
                yield v
        finally:
            closed.append(True)

    def consume(dev):
        if dev >= 3:
            raise RuntimeError("device exploded")
        return None

    with pytest.raises(RuntimeError, match="device exploded"):
        run_pipeline(chunks(), lambda c: c, consume, depth=2)
    assert closed == [True]


def test_host_parallel_results_and_errors():
    assert host_parallel(lambda: 1, lambda: 2) == [1, 2]
    with pytest.raises(KeyError):
        host_parallel(lambda: 1, lambda: (_ for _ in ()).throw(KeyError("x")))


def test_config_auto_threshold_and_env(monkeypatch):
    import jax

    cfg = PipelineConfig(mode="auto", chunk_rows=100)
    # auto only streams on an accelerator backend (no transfer to
    # overlap on CPU); forced 'on' streams anywhere (guard tests)
    assert not cfg.enabled_for(10**9)
    assert _on(chunk_rows=100).enabled_for(1)
    assert not OFF.enabled_for(10**9)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert not cfg.enabled_for(150)
    assert cfg.enabled_for(200)
    monkeypatch.setenv("PIO_PIPELINE", "on")
    monkeypatch.setenv("PIO_PIPELINE_CHUNK", "12345")
    monkeypatch.setenv("PIO_PIPELINE_DEPTH", "5")
    cfg = PipelineConfig.from_env()
    assert (cfg.mode, cfg.chunk_rows, cfg.depth) == ("on", 12345, 5)


# ---------------------------------------------------------------------------
# trainer identity: pipelined == single-shot, bit for bit (CPU)
# ---------------------------------------------------------------------------


def _cls_data(n, d=4, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.poisson(2.0, (n, d)).astype(np.float32)
    y = rng.integers(0, c, n).astype(np.int32)
    return x, y, c


@pytest.mark.parametrize("n,chunk", [
    (10_000, 1024),   # uneven final chunk
    (4_096, 1024),    # exact chunk multiple
    (700, 1024),      # single short chunk (mode=on forces streaming)
])
def test_nb_dense_stream_bit_identical(n, chunk):
    from incubator_predictionio_tpu.ops.linear import train_naive_bayes

    x, y, c = _cls_data(n)
    m0 = train_naive_bayes(x, y, c, pipeline=OFF)
    stats = PipelineStats()
    m1 = train_naive_bayes(x, y, c, pipeline=_on(chunk_rows=chunk),
                           pipeline_stats=stats)
    assert np.array_equal(m0.log_prior, m1.log_prior)
    assert np.array_equal(m0.log_likelihood, m1.log_likelihood)
    assert stats.n_chunks == len(chunk_ranges(n, max(chunk, 1)))


def test_nb_coo_stream_bit_identical():
    from incubator_predictionio_tpu.ops.linear import train_naive_bayes_coo
    from incubator_predictionio_tpu.ops.tfidf import TfIdfVectorizer

    rng = np.random.default_rng(1)
    docs = [" ".join(f"w{int(v)}" for v in rng.integers(0, 60, 25))
            for _ in range(2_000)]
    y = rng.integers(0, 7, len(docs)).astype(np.int32)
    vec = TfIdfVectorizer(n_features=256)
    dp, ft, cnt = vec.fit_tf_coo(docs, use_native=False)
    m0 = train_naive_bayes_coo(dp, ft, cnt, y, 7, 256, pipeline=OFF)
    m1 = train_naive_bayes_coo(dp, ft, cnt, y, 7, 256,
                               pipeline=_on(chunk_rows=4_000))
    assert np.array_equal(m0.log_prior, m1.log_prior)
    assert np.array_equal(m0.log_likelihood, m1.log_likelihood)


def test_lr_stream_bit_identical():
    from incubator_predictionio_tpu.ops.linear import train_logistic_regression

    x, y, c = _cls_data(3_000, seed=2)
    m0 = train_logistic_regression(x, y, c, reg=0.01, max_iters=12,
                                   pipeline=OFF)
    m1 = train_logistic_regression(x, y, c, reg=0.01, max_iters=12,
                                   pipeline=_on(chunk_rows=700))
    assert np.array_equal(m0.weights, m1.weights)
    assert np.array_equal(m0.intercept, m1.intercept)


def test_rebatch_entries_preserves_stream():
    from incubator_predictionio_tpu.ops.linear import rebatch_entries

    rng = np.random.default_rng(3)
    blocks = []
    for ln in (0, 5, 17, 1, 0, 40, 3):
        blocks.append((rng.integers(0, 9, ln).astype(np.int32),
                       rng.integers(0, 99, ln).astype(np.int32),
                       rng.random(ln).astype(np.float32)))
    out = list(rebatch_entries(iter(blocks), 16))
    assert all(len(ch[0]) == 16 for ch in out[:-1])
    assert sum(len(ch[0]) for ch in out) == sum(len(b[0]) for b in blocks)
    for j in range(3):
        got = np.concatenate([ch[j] for ch in out])
        want = np.concatenate([b[j] for b in blocks])
        assert np.array_equal(got, want)


def test_nb_coo_stream_propagates_source_error():
    from incubator_predictionio_tpu.ops.linear import (
        train_naive_bayes_coo_stream,
    )

    def blocks():
        yield (np.zeros(10, np.int32), np.zeros(10, np.int32),
               np.ones(10, np.float32))
        raise OSError("event store died mid-scan")

    with pytest.raises(OSError, match="died mid-scan"):
        train_naive_bayes_coo_stream(
            blocks(), np.zeros(4, np.int32), 3, 16,
            pipeline=_on(chunk_rows=8))


# ---------------------------------------------------------------------------
# template-level identity (the product path: Preparator → Algorithm)
# ---------------------------------------------------------------------------


def _text_corpus(n_docs=600, n_classes=5, vocab=80, seed=4):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, n_docs).astype(np.int32)
    texts = [" ".join(f"w{(int(v) + int(y[j]) * 13) % vocab}"
                      for v in rng.integers(0, vocab, 30))
             for j in range(n_docs)]
    return texts, y, n_classes


def test_text_template_stream_identity():
    """The full text path — deferred TF-IDF featurize streamed through
    tokenizer workers into the device scatter — must produce the same
    model (stats, idf, priors) as the one-shot prepare+train."""
    from incubator_predictionio_tpu.models.text_classification import (
        TextNBAlgorithm, TextPreparator, TrainingData,
    )
    from incubator_predictionio_tpu.workflow.context import WorkflowContext

    texts, y, c = _text_corpus()
    td = TrainingData(texts, y, np.arange(c).astype(str))

    def run(cfg):
        ctx = WorkflowContext(app_name="t")
        ctx.input_pipeline = cfg
        prep = TextPreparator(TextPreparator.params_cls(n_features=512))
        pd = prep.prepare(ctx, td)
        algo = TextNBAlgorithm(TextNBAlgorithm.params_cls())
        return pd, algo.train(ctx, pd)

    pd0, m0 = run(OFF)
    pd1, m1 = run(_on(chunk_rows=2_048, chunk_docs=128, workers=2))
    assert pd0.coo is not None          # one-shot prepared eagerly
    assert pd1.coo is None and pd1.texts is not None  # streaming deferred
    assert np.array_equal(m0.inner.log_prior, m1.inner.log_prior)
    assert np.array_equal(m0.inner.log_likelihood, m1.inner.log_likelihood)
    assert np.array_equal(m0.vectorizer.idf, m1.vectorizer.idf)


def test_classification_template_stream_identity():
    from incubator_predictionio_tpu.models.classification import (
        NaiveBayesAlgorithm, TrainingData,
    )
    from incubator_predictionio_tpu.workflow.context import WorkflowContext

    x, y, c = _cls_data(5_000, seed=5)
    td = TrainingData(x, y, tuple(f"a{j}" for j in range(4)),
                      np.arange(c).astype(np.float64))

    def run(cfg):
        ctx = WorkflowContext(app_name="t")
        ctx.input_pipeline = cfg
        algo = NaiveBayesAlgorithm(NaiveBayesAlgorithm.params_cls())
        return algo.train(ctx, td)

    m0, m1 = run(OFF), run(_on(chunk_rows=512))
    assert np.array_equal(m0.inner.log_prior, m1.inner.log_prior)
    assert np.array_equal(m0.inner.log_likelihood, m1.inner.log_likelihood)


def test_workflow_params_override_env(monkeypatch):
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.workflow_params import (
        WorkflowParams,
    )

    monkeypatch.setenv("PIO_PIPELINE", "off")
    monkeypatch.setenv("PIO_PIPELINE_CHUNK", "111")
    ctx = WorkflowContext(workflow_params=WorkflowParams(
        pipeline="on", pipeline_chunk=222, pipeline_depth=3))
    cfg = ctx.get_input_pipeline()
    assert (cfg.mode, cfg.chunk_rows, cfg.depth) == ("on", 222, 3)
    # resolved once: a later env flip doesn't change this run
    monkeypatch.setenv("PIO_PIPELINE", "auto")
    assert ctx.get_input_pipeline() is cfg


# ---------------------------------------------------------------------------
# event-store batch iterator
# ---------------------------------------------------------------------------


def test_find_batches_concat_equals_find_batch():
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import AccessKey, App
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.data.store.p_event_store import (
        PEventStore,
    )

    s = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
    })
    try:
        app_id = s.get_meta_data_apps().insert(App(0, "chunks", None))
        s.get_l_events().init(app_id)
        s.get_meta_data_access_keys().insert(AccessKey("K", app_id, ()))
        events = [Event.from_json({
            "event": "view", "entityType": "user", "entityId": f"u{j}",
            "targetEntityType": "item", "targetEntityId": f"i{j % 7}",
            "properties": {"rating": float(j % 5)},
            "eventTime": "2024-02-%02dT00:00:00Z" % (1 + j % 28),
        }) for j in range(55)]
        s.get_l_events().insert_batch(events, app_id)

        whole = PEventStore.find_batch("chunks", storage=s)
        chunks = list(PEventStore.find_batches("chunks", storage=s,
                                               chunk_size=10))
        assert len(whole) == 55
        assert [len(b) for b in chunks] == [10, 10, 10, 10, 10, 5]
        assert sum((b.event for b in chunks), []) == whole.event
        assert sum((b.entity_id for b in chunks), []) == whole.entity_id
        assert sum((b.properties for b in chunks), []) == whole.properties
        assert np.array_equal(
            np.concatenate([b.event_time_us for b in chunks]),
            whole.event_time_us)
    finally:
        s.close()
