"""Unified telemetry subsystem: registry semantics, Prometheus
exposition, server /metrics endpoints, sampled tracing, and the
disabled-path cost guarantee.

- exposition golden test (exact text format output)
- histogram log2 bucket-boundary math (bit_length indexing, exact
  powers, +Inf overflow)
- multi-threaded lock-sharded counter correctness
- GET /metrics e2e on the event server AND the engine server (valid
  Prometheus text covering ingest / query / storage families)
- X-Pio-Trace-Id propagation through a live query (stage spans in the
  sink, header echoed)
- guard: the disabled path (PIO_METRICS=0) adds no per-request
  allocations on the hot ingest instrumentation
- guard: no new ad-hoc module-level counter dicts under data/api/ and
  workflow/ — metrics go through the registry
"""

import gc
import json
import os
import re
import sys
import threading

import pytest
import requests

import incubator_predictionio_tpu
from incubator_predictionio_tpu.common import telemetry
from incubator_predictionio_tpu.data.api.event_server import EventServer
from incubator_predictionio_tpu.data.api.stats import Stats
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.data.storage.base import AccessKey, App

from server_utils import ServerThread

pytestmark = pytest.mark.telemetry


# ---------------------------------------------------------------------------
# registry + exposition
# ---------------------------------------------------------------------------

def test_exposition_golden():
    """Byte-exact Prometheus text format: HELP/TYPE comments, label
    escaping, histogram cumulative buckets + _sum/_count."""
    r = telemetry.Registry()
    c = r.counter("t_requests_total", "Requests served", ("method",))
    c.labels("GET").inc()
    c.labels("GET").inc(2)
    c.labels('we"ird\\path').inc()
    g = r.gauge("t_temperature", "A gauge")
    g.labels().set(2.5)
    h = r.histogram("t_sizes", "Sizes", lo_exp=0, n_buckets=2, scale=1)
    h.labels().observe_raw(1)
    h.labels().observe_raw(2)
    h.labels().observe_raw(9)  # past the top bucket -> +Inf
    assert r.render() == (
        "# HELP t_requests_total Requests served\n"
        "# TYPE t_requests_total counter\n"
        't_requests_total{method="GET"} 3\n'
        't_requests_total{method="we\\"ird\\\\path"} 1\n'
        "# HELP t_sizes Sizes\n"
        "# TYPE t_sizes histogram\n"
        't_sizes_bucket{le="1"} 1\n'
        't_sizes_bucket{le="2"} 2\n'
        't_sizes_bucket{le="+Inf"} 3\n'
        "t_sizes_sum 12\n"
        "t_sizes_count 3\n"
        "# HELP t_temperature A gauge\n"
        "# TYPE t_temperature gauge\n"
        "t_temperature 2.5\n"
    )


def test_histogram_bucket_boundary_math():
    """Bucket index = smallest power-of-two bound >= value, computed
    with bit_length — exact at the powers themselves."""
    h = telemetry.Histogram(lo_exp=0, n_buckets=16, scale=1)
    # bound of bucket j is 2**j: value 2**j must land IN bucket j,
    # value 2**j + 1 in bucket j+1
    for j in range(1, 15):
        assert h.bucket_index(2 ** j) == j
        assert h.bucket_index(2 ** j + 1) == j + 1
    assert h.bucket_index(1) == 0
    assert h.bucket_index(0) == 0
    assert h.bucket_index(2 ** 16) == 16      # == top bound -> last bucket
    assert h.bucket_index(2 ** 16 + 1) == 16  # past it -> +Inf slot
    assert h.upper_bound(3) == 8.0

    # ns->seconds latency shape: 1024 ns lands in the first bucket
    # (le=2**10 ns), 1025 ns in the second
    lat = telemetry.Histogram(
        lo_exp=10, n_buckets=26, scale=1e-9)
    assert lat.bucket_index(1024) == 0
    assert lat.bucket_index(1025) == 1
    assert lat.upper_bound(0) == pytest.approx(1.024e-6)

    lat.observe_raw(1024)
    lat.observe_raw(10 ** 9)  # 1 s
    counts, total, sum_raw = lat.snapshot()
    assert total == 2 and sum_raw == 1024 + 10 ** 9
    assert counts[0] == 1


def test_counter_multithreaded_exact():
    """Lock-sharded counters lose no increments under contention."""
    fam = telemetry.CounterFamily("t_mt_total", "mt", ("who",))
    child = fam.labels("x")
    n_threads, per_thread = 8, 20_000

    def work():
        for _ in range(per_thread):
            child.inc()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert child.value() == n_threads * per_thread


def test_registry_get_or_create_and_conflicts():
    r = telemetry.Registry()
    a = r.counter("t_x_total", "x", ("k",))
    assert r.counter("t_x_total", "x", ("k",)) is a
    with pytest.raises(ValueError):
        r.gauge("t_x_total", "x", ("k",))
    with pytest.raises(ValueError):
        r.counter("t_x_total", "x", ("other",))
    with pytest.raises(ValueError):
        a.labels("a", "b")  # label arity enforced
    # histograms: the bucket shape is part of the identity — a second
    # registrant with a different lo_exp/n_buckets/scale must error,
    # not silently adopt the first shape (its observations would render
    # with the wrong scale)
    h = r.histogram("t_h_seconds", "h", lo_exp=0, n_buckets=4, scale=1)
    assert r.histogram("t_h_seconds", "h",
                       lo_exp=0, n_buckets=4, scale=1) is h
    with pytest.raises(ValueError):
        r.histogram("t_h_seconds", "h")  # default latency shape differs


def test_stats_json_view_is_registry_backed():
    """Stats keeps its /stats.json shape, served from a telemetry
    CounterFamily rather than an ad-hoc dict."""
    s = Stats()
    s.record(7, "rate", "user", 201)
    s.record_many({(7, "rate", "user", 201): 2, (8, "buy", "user", 400): 1})
    out = s.to_json()
    assert {(c["appId"], c["event"], c["status"]): c["count"]
            for c in out["counts"]} == {(7, "rate", 201): 3,
                                        (8, "buy", 400): 1}
    assert s.to_json(8)["counts"] == [
        {"appId": 8, "event": "buy", "entityType": "user", "status": 400,
         "count": 1}]
    assert isinstance(s.family, telemetry.CounterFamily)


# ---------------------------------------------------------------------------
# /metrics e2e
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})?'
    r" -?[0-9.e+\-]+$")


def _assert_valid_exposition(text: str) -> dict:
    """Every line is a HELP/TYPE comment or a sample; returns
    {metric_name: value} for non-comment lines."""
    samples = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE_RE.match(line), f"invalid exposition line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        samples[name] = line.rsplit(" ", 1)[1]
    return samples


def _setup_event_storage():
    env = {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        "PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY",
    }
    storage = Storage(env)
    app_id = storage.get_meta_data_apps().insert(App(0, "telemapp"))
    key = storage.get_meta_data_access_keys().insert(
        AccessKey("", app_id, ()))
    return storage, app_id, key


def test_event_server_metrics_e2e():
    """GET /metrics on the event server: valid text format covering the
    ingest histogram families and (with --stats) per-app counters."""
    storage, _app_id, key = _setup_event_storage()
    server = EventServer(storage, enable_stats=True)
    with ServerThread(server.app) as st:
        for i in range(3):
            r = requests.post(
                f"{st.base}/events.json?accessKey={key}",
                json={"event": "view", "entityType": "user",
                      "entityId": f"u{i}"})
            assert r.status_code == 201
        r = requests.get(f"{st.base}/metrics")
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.text
    samples = _assert_valid_exposition(body)
    # ingest family: three committed events through the group buffer
    assert "pio_ingest_group_size_count" in samples
    assert "pio_ingest_commit_seconds_count" in samples
    assert "pio_ingest_queue_wait_seconds_bucket" in samples
    # per-app stats counters from the live server's collector
    assert 'pio_ingest_events_total{app_id=' in body
    assert 'event="view"' in body
    # storage breaker gauge family is registered (resilience collector)
    assert "# TYPE pio_storage_breaker_state gauge" in body
    # histograms expose cumulative buckets ending in +Inf
    assert 'pio_ingest_group_size_bucket{le="+Inf"}' in body


def _trained_engine_server(memory_storage):
    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine)
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.create_server import EngineServer

    from test_dase_train_e2e import ENGINE_PARAMS, _seed_ratings

    _seed_ratings(memory_storage)
    engine = RecommendationEngine()()
    ctx = WorkflowContext(app_name="testapp", storage=memory_storage)
    run_train(engine, ENGINE_PARAMS, ctx, engine_factory_name="rec")
    return EngineServer(engine, engine_factory_name="rec",
                        storage=memory_storage)


def test_engine_server_metrics_e2e(memory_storage):
    """GET /metrics on the engine server: query stage histograms
    accumulate per query; compile gauges cover the warmed algorithms."""
    server = _trained_engine_server(memory_storage)
    with ServerThread(server.app) as st:
        for u in ("1", "2"):
            r = requests.post(st.base + "/queries.json",
                              json={"user": u, "num": 2})
            assert r.status_code == 200, r.text
        body = requests.get(st.base + "/metrics").text
    samples = _assert_valid_exposition(body)
    assert "# TYPE pio_query_stage_seconds histogram" in body
    for stage in ("featurize", "predict", "serve"):
        m = re.search(
            r'pio_query_stage_seconds_count\{stage="%s",batched="0"\} (\d+)'
            % stage, body)
        assert m and int(m.group(1)) >= 2, f"missing stage {stage}"
    assert "# TYPE pio_engine_compile_seconds gauge" in body
    assert 'pio_engine_compile_count{algorithm=' in body
    assert "pio_engine_query_count" in samples


def test_dashboard_metrics_pages():
    """The dashboard serves the registry raw at /metrics and as a
    readable table at /metrics/html, linked from the index."""
    from incubator_predictionio_tpu.tools.dashboard import Dashboard

    storage, _app_id, _key = _setup_event_storage()
    d = Dashboard(storage)
    with ServerThread(d.app) as st:
        raw = requests.get(st.base + "/metrics")
        assert raw.status_code == 200
        _assert_valid_exposition(raw.text)
        page = requests.get(st.base + "/metrics/html")
        assert page.status_code == 200 and "Telemetry" in page.text
        assert "/metrics/html" in requests.get(st.base + "/").text


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

def test_trace_id_propagation_through_query(memory_storage, tmp_path):
    """A query carrying X-Pio-Trace-Id is traced end to end: the id is
    echoed on the response, and the sink receives the http root span
    plus the featurize/predict/serve stage spans — proving the trace
    context crossed asyncio.to_thread into Deployment.query."""
    sink = tmp_path / "spans.jsonl"
    telemetry.configure_tracer(rate=1.0, sink=str(sink))
    try:
        server = _trained_engine_server(memory_storage)
        with ServerThread(server.app) as st:
            r = requests.post(st.base + "/queries.json",
                              json={"user": "1", "num": 2},
                              headers={"X-Pio-Trace-Id": "deadbeef01"})
            assert r.status_code == 200
            assert r.headers["X-Pio-Trace-Id"] == "deadbeef01"
            # untraced request: no header, no extra spans
            r2 = requests.post(st.base + "/queries.json",
                               json={"user": "2", "num": 2})
            assert r2.status_code == 200
    finally:
        telemetry.configure_tracer(rate=0.0)
    spans = [json.loads(line) for line in
             sink.read_text().splitlines()]
    mine = [s for s in spans if s["traceId"] == "deadbeef01"]
    names = {s["span"] for s in mine}
    assert {"query.featurize", "query.predict", "query.serve"} <= names
    root = [s for s in mine if s["span"].startswith("http POST")]
    assert root and root[0]["tags"]["status"] == 200
    assert all(s["durUs"] >= 0 for s in mine)
    # rate=0 after the finally: nothing is sampled
    assert telemetry.sample_trace(None) is None


def test_trace_sampling_rules(tmp_path):
    rec = telemetry.TraceRecorder(rate=0.0, sink=str(tmp_path / "t"))
    assert rec.sample(None) is None
    assert rec.sample("upstream-id") is None  # off means off
    rec = telemetry.TraceRecorder(rate=1.0, sink=str(tmp_path / "t"))
    assert rec.sample(None) is not None
    assert rec.sample("upstream-id").trace_id == "upstream-id"


def test_event_server_trace_header_echo(tmp_path):
    """Ingest POSTs propagate the trace id too (one id follows a
    request across tiers)."""
    sink = tmp_path / "ingest_spans.jsonl"
    telemetry.configure_tracer(rate=1.0, sink=str(sink))
    try:
        storage, _app_id, key = _setup_event_storage()
        server = EventServer(storage)
        with ServerThread(server.app) as st:
            r = requests.post(
                f"{st.base}/events.json?accessKey={key}",
                json={"event": "view", "entityType": "user",
                      "entityId": "u1"},
                headers={"X-Pio-Trace-Id": "ingest-trace-7"})
            assert r.status_code == 201
            assert r.headers["X-Pio-Trace-Id"] == "ingest-trace-7"
    finally:
        telemetry.configure_tracer(rate=0.0)
    spans = [json.loads(line) for line in sink.read_text().splitlines()]
    assert any(s["traceId"] == "ingest-trace-7"
               and s["span"].startswith("http POST /events.json")
               for s in spans)


# ---------------------------------------------------------------------------
# disabled-path guarantees
# ---------------------------------------------------------------------------

def test_disabled_path_no_allocations():
    """With PIO_METRICS off, the exact telemetry calls on the hot
    ingest path — timer_start, Counter.inc, Histogram.observe_since —
    must allocate nothing per request (timer_start returns the cached
    small int 0, the others return before touching state)."""
    fam_c = telemetry.CounterFamily("t_noalloc_total", "x")
    fam_h = telemetry.HistogramFamily("t_noalloc_seconds", "x")
    c = fam_c.labels()
    h = fam_h.labels()

    def hot_request():
        t0 = telemetry.timer_start()
        c.inc()
        h.observe_since(t0)

    telemetry.set_metrics_enabled(False)
    try:
        for _ in range(100):   # warm frames, caches, freelists
            hot_request()
        gc.collect()
        before = sys.getallocatedblocks()
        for _ in range(10_000):
            hot_request()
        gc.collect()
        grown = sys.getallocatedblocks() - before
    finally:
        telemetry.set_metrics_enabled(True)
    # zero in practice; tiny slack for unrelated interpreter churn
    assert grown <= 10, f"disabled telemetry path allocated ({grown} blocks)"
    assert c.value() == 0
    _counts, total, _sum = h.snapshot()
    assert total == 0

    # and the enabled path actually records
    hot_request()
    assert c.value() == 1


def test_disabled_metrics_skip_recording():
    telemetry.set_metrics_enabled(False)
    try:
        assert telemetry.timer_start() == 0
        h = telemetry.Histogram(0, 4, 1)
        h.observe_raw(3)
        h.observe_since(0)
        assert h.snapshot()[1] == 0
    finally:
        telemetry.set_metrics_enabled(True)


# ---------------------------------------------------------------------------
# AST guard: metrics go through the registry
# ---------------------------------------------------------------------------

def test_no_adhoc_module_counter_dicts():
    """No NEW module-level counter dicts under data/api/ and workflow/:
    a counter-ish name assigned a dict/Counter literal at module scope
    is ad-hoc state the registry should own (this is exactly what
    stats.py and the ingest counters migrated away from). Enforced by
    the shared `pio lint` engine."""
    from incubator_predictionio_tpu.tools.lint import assert_rule_clean

    assert_rule_clean("no-adhoc-counters")
