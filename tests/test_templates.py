"""End-to-end tests for the four non-quickstart template families
(SURVEY.md §2.8 rows 2-5): classification, text, similar-product,
e-commerce, universal recommender — each through the full train →
persist → reload → query workflow."""

import datetime as dt

import numpy as np
import pytest

from incubator_predictionio_tpu.controller import EngineParams
from incubator_predictionio_tpu.data.storage import App, DataMap, Event
from incubator_predictionio_tpu.workflow.context import WorkflowContext
from incubator_predictionio_tpu.workflow.core_workflow import (
    load_deployment,
    run_train,
)

T0 = dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc)


def _mk_app(storage, name):
    app_id = storage.get_meta_data_apps().insert(App(0, name))
    storage.get_l_events().init(app_id)
    return app_id


def _ts(i):
    return T0 + dt.timedelta(seconds=i)


# -- classification --------------------------------------------------------


def test_classification_template(memory_storage):
    from incubator_predictionio_tpu.models.classification import (
        ClassificationEngine,
    )

    app_id = _mk_app(memory_storage, "clsapp")
    le = memory_storage.get_l_events()
    rng = np.random.default_rng(0)
    events = []
    for n in range(200):
        attrs = rng.integers(0, 5, 3)
        plan = int(attrs[0] >= 2) + int(attrs[0] >= 4)  # label from attr0
        events.append(
            Event("$set", "user", str(n),
                  properties=DataMap({"attr0": int(attrs[0]), "attr1": int(attrs[1]),
                                      "attr2": int(attrs[2]), "plan": plan}),
                  event_time=_ts(n))
        )
    le.insert_batch(events, app_id)

    engine = ClassificationEngine()()
    ctx = WorkflowContext(app_name="clsapp", storage=memory_storage)
    for algo in ("naive", "lr"):
        ep = EngineParams.from_json({
            "datasource": {"params": {"appName": "clsapp"}},
            "algorithms": [{"name": algo, "params": {}}],
        })
        iid = run_train(engine, ep, ctx, engine_factory_name=f"cls-{algo}")
        dep, _, _ = load_deployment(
            engine, iid, WorkflowContext(storage=memory_storage),
            engine_factory_name=f"cls-{algo}",
        )
        assert dep.query({"attr0": 0, "attr1": 1, "attr2": 0})["label"] == 0.0
        assert dep.query({"attr0": 4, "attr1": 1, "attr2": 0})["label"] == 2.0


# -- text classification ---------------------------------------------------


def test_text_classification_template(memory_storage):
    from incubator_predictionio_tpu.models.text_classification import (
        TextClassificationEngine,
    )

    app_id = _mk_app(memory_storage, "txtapp")
    le = memory_storage.get_l_events()
    docs = [
        ("fast motorcycles ride highway speed engine", "motorcycles"),
        ("engine throttle motorcycles helmet speed", "motorcycles"),
        ("ride motorcycles fast wheels", "motorcycles"),
        ("graphics screen computer keyboard software", "computers"),
        ("software computer cpu keyboard code", "computers"),
        ("computer code screen programming", "computers"),
    ] * 5
    events = [
        Event("documents", "content", str(j),
              properties=DataMap({"text": t, "label": lab}), event_time=_ts(j))
        for j, (t, lab) in enumerate(docs)
    ]
    le.insert_batch(events, app_id)

    engine = TextClassificationEngine()()
    ctx = WorkflowContext(app_name="txtapp", storage=memory_storage)
    for algo in ("nb", "lr"):
        ep = EngineParams.from_json({
            "datasource": {"params": {"appName": "txtapp"}},
            "preparator": {"params": {"numFeatures": 512}},
            "algorithms": [{"name": algo, "params": {}}],
        })
        iid = run_train(engine, ep, ctx, engine_factory_name=f"txt-{algo}")
        dep, _, _ = load_deployment(
            engine, iid, WorkflowContext(storage=memory_storage),
            engine_factory_name=f"txt-{algo}",
        )
        r = dep.query({"text": "I like speed and fast motorcycles"})
        assert r["category"] == "motorcycles", r
        assert 0 < r["confidence"] <= 1
        r = dep.query({"text": "my computer software and keyboard"})
        assert r["category"] == "computers", r


# -- similar product -------------------------------------------------------


def _seed_views(storage, app_name, groups=((0, 10), (10, 20)), n_users=40):
    """Users view items only within their own group → within-group
    similarity dominates."""
    app_id = _mk_app(storage, app_name)
    le = storage.get_l_events()
    rng = np.random.default_rng(3)
    events = []
    for u in range(n_users):
        lo, hi = groups[u % len(groups)]
        for _ in range(12):
            i = rng.integers(lo, hi)
            events.append(
                Event("view", "user", str(u), "item", f"i{i}",
                      event_time=_ts(len(events)))
            )
    # item categories: group 0 items = "red", group 1 = "blue"
    for i in range(groups[-1][1]):
        cat = "red" if i < groups[0][1] else "blue"
        events.append(
            Event("$set", "item", f"i{i}",
                  properties=DataMap({"categories": [cat]}),
                  event_time=_ts(len(events)))
        )
    le.insert_batch(events, app_id)
    return app_id


def test_similar_product_template(memory_storage):
    from incubator_predictionio_tpu.models.similar_product import (
        SimilarProductEngine,
    )

    _seed_views(memory_storage, "simapp")
    engine = SimilarProductEngine()()
    ctx = WorkflowContext(app_name="simapp", storage=memory_storage)
    ep = EngineParams.from_json({
        "datasource": {"params": {"appName": "simapp"}},
        "algorithms": [{"name": "als",
                        "params": {"rank": 8, "numIterations": 10}}],
    })
    iid = run_train(engine, ep, ctx, engine_factory_name="sim")
    dep, _, _ = load_deployment(
        engine, iid, WorkflowContext(storage=memory_storage),
        engine_factory_name="sim",
    )
    r = dep.query({"items": ["i0"], "num": 5})
    items = [s["item"] for s in r["itemScores"]]
    assert "i0" not in items  # query item excluded
    in_group = sum(1 for it in items if int(it[1:]) < 10)
    assert in_group >= 4, f"similar items leak across groups: {items}"

    # category filter: only "blue" items
    r = dep.query({"items": ["i0"], "num": 5, "categories": ["blue"]})
    assert all(int(s["item"][1:]) >= 10 for s in r["itemScores"])

    # whitelist/blacklist
    r = dep.query({"items": ["i0"], "num": 5, "whiteList": ["i3", "i4"]})
    assert set(s["item"] for s in r["itemScores"]) <= {"i3", "i4"}
    r = dep.query({"items": ["i0"], "num": 5, "blackList": ["i1"]})
    assert "i1" not in [s["item"] for s in r["itemScores"]]

    # unknown query item → empty
    assert dep.query({"items": ["nope"], "num": 3}) == {"itemScores": []}


# -- e-commerce ------------------------------------------------------------


def test_ecommerce_template(memory_storage):
    from incubator_predictionio_tpu.models.ecommerce import ECommerceEngine

    app_id = _seed_views(memory_storage, "ecapp")
    le = memory_storage.get_l_events()
    engine = ECommerceEngine()()
    ctx = WorkflowContext(app_name="ecapp", storage=memory_storage)
    ep = EngineParams.from_json({
        "datasource": {"params": {"appName": "ecapp"}},
        "algorithms": [{"name": "ecomm",
                        "params": {"appName": "ecapp", "rank": 8,
                                   "numIterations": 10}}],
    })
    iid = run_train(engine, ep, ctx, engine_factory_name="ec")
    dep, _, _ = load_deployment(
        engine, iid, WorkflowContext(storage=memory_storage),
        engine_factory_name="ec",
    )
    # user 0 (group 0) has seen several items; unseenOnly filters them
    seen = {
        e.target_entity_id
        for e in le.find(app_id, entity_type="user", entity_id="0",
                         event_names=["view"])
    }
    r = dep.query({"user": "0", "num": 5})
    rec_items = [s["item"] for s in r["itemScores"]]
    assert not (set(rec_items) & seen), "seen items not filtered"

    # mark an item unavailable via the constraint entity → excluded
    candidate = rec_items[0]
    le.insert(
        Event("$set", "constraint", "unavailableItems",
              properties=DataMap({"items": [candidate]}), event_time=_ts(99999)),
        app_id,
    )
    r2 = dep.query({"user": "0", "num": 5})
    assert candidate not in [s["item"] for s in r2["itemScores"]]

    # unseenOnly=false returns seen items too
    r3 = dep.query({"user": "0", "num": 10, "unseenOnly": False})
    assert set(s["item"] for s in r3["itemScores"]) & seen


# -- universal recommender -------------------------------------------------


def test_universal_recommender_template(memory_storage):
    from incubator_predictionio_tpu.models.universal_recommender import (
        UniversalRecommenderEngine,
    )

    app_id = _mk_app(memory_storage, "urapp")
    le = memory_storage.get_l_events()
    rng = np.random.default_rng(7)
    events = []
    # two taste groups of 12 items; buys concentrated in-group (few per
    # user so the exclude-purchased rule leaves in-group candidates),
    # views noisier
    for u in range(40):
        group = u % 2
        lo, hi = (0, 12) if group == 0 else (12, 24)
        for _ in range(4):
            events.append(Event("buy", "user", str(u), "item",
                                f"i{rng.integers(lo, hi)}",
                                event_time=_ts(len(events))))
        for _ in range(10):
            # views mostly in-group with some cross-noise
            if rng.random() < 0.85:
                i = rng.integers(lo, hi)
            else:
                i = rng.integers(0, 24)
            events.append(Event("view", "user", str(u), "item", f"i{i}",
                                event_time=_ts(len(events))))
    le.insert_batch(events, app_id)

    engine = UniversalRecommenderEngine()()
    ctx = WorkflowContext(app_name="urapp", storage=memory_storage)
    ep = EngineParams.from_json({
        "datasource": {"params": {"appName": "urapp",
                                   "eventNames": ["buy", "view"]}},
        "algorithms": [{"name": "ur",
                        "params": {"appName": "urapp",
                                   "maxCorrelatorsPerItem": 8,
                                   "user_chunk": 64}}],
    })
    iid = run_train(engine, ep, ctx, engine_factory_name="ur")
    dep, _, _ = load_deployment(
        engine, iid, WorkflowContext(storage=memory_storage),
        engine_factory_name="ur",
    )
    r = dep.query({"user": "0", "num": 4})  # group 0 user
    assert r["itemScores"], "no recommendations"
    items = [s["item"] for s in r["itemScores"]]
    in_group = sum(1 for it in items if int(it[1:]) < 12)
    assert in_group >= 3, f"CCO recommendations leak across groups: {items}"
    # already-bought items excluded
    bought = {
        e.target_entity_id
        for e in le.find(app_id, entity_type="user", entity_id="0",
                         event_names=["buy"])
    }
    assert not (set(items) & bought)

    # unknown user → popularity backfill (UR popModel; detailed coverage
    # in tests/test_ur_completeness.py)
    cold = dep.query({"user": "zzz", "num": 3})
    assert len(cold["itemScores"]) == 3

    # blacklist honoured
    r2 = dep.query({"user": "0", "num": 4, "blacklistItems": [items[0]]})
    assert items[0] not in [s["item"] for s in r2["itemScores"]]
