"""Packed slab transfers + the device-resident slab cache.

Single-device meshes upload the layout's per-bucket slabs as 2-3
dtype-grouped buffers and unpack them as static slices inside the
jitted loop (ops/als.py _pack_flat — the remote-PJRT tunnel pays a
per-transfer cost that made the upload, not the device math, dominate
warm implicit-ALS trains). These tests pin:

- numerical identity: the packed single-device path solves the same
  problem as the per-slab multi-device path (same factors within
  reduction-order tolerance);
- the content-hash device cache: repeat trains over identical data
  reuse device buffers (no re-upload), changed data misses, and a
  changed regularization re-uploads only the (tiny) lam slab while the
  big index slabs still hit.
"""

import numpy as np
import jax
import pytest

from incubator_predictionio_tpu.ops import als as als_mod
from incubator_predictionio_tpu.ops.als import ALSParams, train_als
from incubator_predictionio_tpu.parallel.mesh import (
    default_mesh, mesh_from_devices,
)


def _data(nnz=20_000, n_users=500, n_items=200, seed=0, binary=False):
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    r = (np.ones(nnz, np.float32) if binary
         else (rng.random(nnz).astype(np.float32) * 4 + 1))
    return u, i, r


@pytest.mark.parametrize("binary", [False, True])
def test_packed_single_device_matches_multi_device(binary):
    u, i, r = _data(binary=binary)
    params = ALSParams(rank=8, num_iterations=3, reg=0.1, seed=1,
                       implicit_prefs=binary, alpha=1.0,
                       compute_dtype="float32")
    m1 = mesh_from_devices(devices=[jax.devices()[0]])
    assert m1.devices.size == 1  # the packed path
    f1 = train_als(u, i, r, n_users=500, n_items=200, params=params,
                   mesh=m1)
    f8 = train_als(u, i, r, n_users=500, n_items=200, params=params,
                   mesh=default_mesh())
    np.testing.assert_allclose(f1.user_factors, f8.user_factors,
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(f1.item_factors, f8.item_factors,
                               rtol=2e-3, atol=2e-4)


def test_device_slab_cache_hits_and_misses(monkeypatch):
    als_mod._dev_buf_cache.clear()
    als_mod._dev_buf_cache_order.clear()
    puts = []
    real_put = jax.device_put

    def counting_put(x, target=None):
        puts.append(np.asarray(x).nbytes if hasattr(x, "nbytes") else 0)
        return real_put(x, target)

    monkeypatch.setattr(als_mod.jax, "device_put", counting_put)
    u, i, r = _data()
    params = ALSParams(rank=8, num_iterations=2, reg=0.1, seed=1,
                       compute_dtype="float32")
    m1 = mesh_from_devices(devices=[jax.devices()[0]])

    train_als(u, i, r, n_users=500, n_items=200, params=params, mesh=m1)
    n_first = len(puts)
    assert n_first > 0

    # identical data + params: every slab hits; only x0/y0 re-put
    puts.clear()
    train_als(u, i, r, n_users=500, n_items=200, params=params, mesh=m1)
    assert len(puts) == 2  # the factor inits (x0, y0), nothing else

    # changed reg: the lam slab (small f4) misses, index slabs hit
    puts.clear()
    params2 = ALSParams(rank=8, num_iterations=2, reg=0.5, seed=1,
                        compute_dtype="float32")
    train_als(u, i, r, n_users=500, n_items=200, params=params2, mesh=m1)
    assert len(puts) == 3  # x0, y0, and the re-hashed f4 buffer

    # changed ratings: the value-carrying buffer misses too
    puts.clear()
    r2 = r.copy()
    r2[0] += 1.0
    train_als(u, i, r2, n_users=500, n_items=200, params=params, mesh=m1)
    assert len(puts) >= 3

    # PIO_ALS_DEVICE_CACHE=0 disables caching entirely
    als_mod._dev_buf_cache.clear()
    als_mod._dev_buf_cache_order.clear()
    monkeypatch.setenv("PIO_ALS_DEVICE_CACHE", "0")
    puts.clear()
    train_als(u, i, r, n_users=500, n_items=200, params=params, mesh=m1)
    first = len(puts)
    puts.clear()
    train_als(u, i, r, n_users=500, n_items=200, params=params, mesh=m1)
    assert len(puts) == first  # no reuse
    assert not als_mod._dev_buf_cache


def test_device_slab_cache_evicts_over_budget(monkeypatch):
    als_mod._dev_buf_cache.clear()
    als_mod._dev_buf_cache_order.clear()
    monkeypatch.setattr(als_mod, "_DEV_BUF_CACHE_BYTES", 1024)
    dev = jax.devices()[0]
    a = np.arange(200, dtype=np.int32)      # 800 B
    b = np.arange(100, dtype=np.int32)      # 400 B
    als_mod._cached_dev_put(a, dev)
    als_mod._cached_dev_put(b, dev)         # 1200 B > 1024 → evict a
    assert len(als_mod._dev_buf_cache) == 1
    # the survivor is b
    ((key, _arr),) = als_mod._dev_buf_cache.items()
    assert key[2] == b.shape


def test_executable_cache_survives_candidate_sweeps():
    """Eval sweeps vary reg / iterations / seed per candidate; none of
    those shape the compiled program (reg flows in as the lam data,
    n_iters is a traced operand, seed is host init), so the train-fn
    cache must serve ONE entry across the sweep — recompiling per
    candidate was a multi-second tax per eval point."""
    als_mod._train_fn_cache.clear()
    u, i, r = _data()
    m1 = mesh_from_devices(devices=[jax.devices()[0]])
    base = dict(rank=8, compute_dtype="float32")
    for reg, iters, seed in [(0.1, 2, 1), (0.5, 2, 1), (0.1, 3, 2),
                             (0.9, 1, 7)]:
        train_als(u, i, r, n_users=500, n_items=200,
                  params=ALSParams(reg=reg, num_iterations=iters,
                                   seed=seed, **base), mesh=m1)
    assert len(als_mod._train_fn_cache) == 1
    # a shaping field (rank) DOES key a new executable
    train_als(u, i, r, n_users=500, n_items=200,
              params=ALSParams(rank=16, num_iterations=1,
                               compute_dtype="float32"), mesh=m1)
    assert len(als_mod._train_fn_cache) == 2
    # and regularization actually took effect across the sweep
    f_lo = train_als(u, i, r, n_users=500, n_items=200,
                     params=ALSParams(reg=0.001, num_iterations=3,
                                      **base), mesh=m1)
    f_hi = train_als(u, i, r, n_users=500, n_items=200,
                     params=ALSParams(reg=50.0, num_iterations=3,
                                      **base), mesh=m1)
    assert (np.linalg.norm(f_hi.user_factors)
            < 0.5 * np.linalg.norm(f_lo.user_factors))


def test_device_slab_cache_is_per_device():
    """--parallel-candidates gives each worker its own single-device
    mesh; the content-hash cache keys on the DEVICE too, so candidate
    A's slabs on device 0 are never handed to candidate B training on
    device 1 (a cross-device hit would either crash placement or
    silently move the train). Both devices end up with their own
    cached copies and identical results."""
    als_mod._dev_buf_cache.clear()
    als_mod._dev_buf_cache_order.clear()
    devs = jax.devices()
    if len(devs) < 2:
        import pytest as _pytest

        _pytest.skip("needs >=2 devices (conftest provides 8 virtual)")
    u, i, r = _data()
    params = ALSParams(rank=8, num_iterations=2, reg=0.1, seed=1,
                       compute_dtype="float32")
    f0 = train_als(u, i, r, n_users=500, n_items=200, params=params,
                   mesh=mesh_from_devices(devices=[devs[0]]))
    n_after_first = len(als_mod._dev_buf_cache)
    assert n_after_first > 0
    f1 = train_als(u, i, r, n_users=500, n_items=200, params=params,
                   mesh=mesh_from_devices(devices=[devs[1]]))
    # device 1 missed device 0's entries: the cache grew by the same
    # slab count again, keyed to the second device
    assert len(als_mod._dev_buf_cache) == 2 * n_after_first
    dev_ids = {k[3] for k in als_mod._dev_buf_cache}
    assert dev_ids == {devs[0].id, devs[1].id}
    np.testing.assert_array_equal(f0.user_factors, f1.user_factors)
