"""Binary-ratings upload elision: when every rating is 1.0 (implicit
view/buy streams — similar-product, e-commerce, UR), train_als skips
building/uploading the value slabs and synthesizes exact ones on device
(padding safety comes from the zero factor rows the sentinel gathers).
These tests pin that the elided path matches the explicit-value path on
the same data (up to f32 reassociation: XLA eliminates the *1.0 multiply,
which changes fusion/contraction order — observed ~2e-4 relative)."""

import numpy as np
import pytest

import jax

from incubator_predictionio_tpu.ops.als import ALSParams, train_als
from incubator_predictionio_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    mesh_from_devices,
)


def _views(n_users=80, n_items=50, nnz=1200, seed=2, heavy=False):
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    if heavy:
        extra = rng.permutation(n_users)[: min(n_users, 3000)]
        u = np.concatenate([u, extra.astype(np.int32)])
        i = np.concatenate([i, np.zeros(len(extra), np.int32)])
    r = np.ones(len(u), np.float32)
    return u, i, r


def _mesh_1d(n=4):
    return mesh_from_devices(devices=jax.devices("cpu")[:n])


@pytest.mark.parametrize("implicit", [False, True])
def test_binary_elision_matches_explicit_path(implicit):
    u, i, r = _views()
    base = dict(rank=8, num_iterations=3, reg=0.05, block_len=8,
                implicit_prefs=implicit, alpha=3.0)
    mesh = _mesh_1d()
    out_b = train_als(u, i, r, 80, 50,
                      ALSParams(**base), mesh=mesh)  # auto → binary
    out_e = train_als(u, i, r, 80, 50,
                      ALSParams(**base, binary_ratings=False), mesh=mesh)
    np.testing.assert_allclose(
        out_b.user_factors, out_e.user_factors, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(
        out_b.item_factors, out_e.item_factors, rtol=5e-4, atol=5e-5)


def test_binary_elision_with_overflow_rows():
    """The virtual-row (overflow) slabs are elided too."""
    u, i, r = _views(n_users=3100, n_items=40, nnz=4000, heavy=True)
    counts_i = np.bincount(i, minlength=40)
    assert counts_i[0] > 2048  # overflow engaged
    base = dict(rank=6, num_iterations=2, reg=0.1, block_len=8)
    mesh = _mesh_1d()
    out_b = train_als(u, i, r, 3100, 40, ALSParams(**base), mesh=mesh)
    out_e = train_als(u, i, r, 3100, 40,
                      ALSParams(**base, binary_ratings=False), mesh=mesh)
    np.testing.assert_allclose(
        out_b.item_factors, out_e.item_factors, rtol=5e-4, atol=5e-5)


def test_binary_elision_on_2d_mesh():
    u, i, r = _views(seed=5)
    base = dict(rank=8, num_iterations=2, reg=0.05, block_len=8,
                implicit_prefs=True, alpha=2.0)
    mesh = mesh_from_devices(
        shape=(2, 2), axis_names=(DATA_AXIS, MODEL_AXIS),
        devices=jax.devices("cpu")[:4])
    out_b = train_als(u, i, r, 80, 50, ALSParams(**base), mesh=mesh)
    out_e = train_als(u, i, r, 80, 50,
                      ALSParams(**base, binary_ratings=False), mesh=mesh)
    np.testing.assert_allclose(
        out_b.user_factors, out_e.user_factors, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(
        out_b.item_factors, out_e.item_factors, rtol=5e-4, atol=5e-5)


def test_wide_catalog_keeps_int32_cols():
    """Counterpart slot spaces past uint16 must keep int32 col slabs
    (every small CPU test now exercises the uint16 narrow path, so this
    pins the wide one): 70k users means the ITEM side's cols index a
    >65535 slot space."""
    rng = np.random.default_rng(3)
    n_users, n_items = 70_000, 25
    u = rng.integers(0, n_users, 3000).astype(np.int32)
    i = rng.integers(0, n_items, 3000).astype(np.int32)
    r = np.ones(3000, np.float32)
    params = ALSParams(rank=4, num_iterations=1, reg=0.1, block_len=8)
    out = train_als(u, i, r, n_users, n_items, params, mesh=_mesh_1d(2))
    # spot-check one solved item against the dense normal equations
    sel = i == 0
    yy = out.user_factors[u[sel]].astype(np.float64)
    ref = np.linalg.solve(yy.T @ yy + 0.1 * np.eye(4), yy.T @ r[sel])
    np.testing.assert_allclose(out.item_factors[0], ref, rtol=2e-3,
                               atol=2e-4)


def test_non_binary_ratings_keep_explicit_path():
    """Ratings with any non-1.0 value must auto-select the explicit
    path: auto must agree exactly with binary_ratings=False forced."""
    rng = np.random.default_rng(9)
    u = rng.integers(0, 30, 400).astype(np.int32)
    i = rng.integers(0, 20, 400).astype(np.int32)
    r = (rng.random(400) * 4 + 1).astype(np.float32)
    params = ALSParams(rank=4, num_iterations=2, block_len=4)
    out_auto = train_als(u, i, r, 30, 20, params, mesh=_mesh_1d(2))
    out_forced = train_als(
        u, i, r, 30, 20,
        ALSParams(rank=4, num_iterations=2, block_len=4,
                  binary_ratings=False), mesh=_mesh_1d(2))
    # same jitted program (auto resolves to the explicit path) → bitwise
    assert np.array_equal(out_auto.user_factors, out_forced.user_factors)
    assert np.array_equal(out_auto.item_factors, out_forced.item_factors)
