"""Bounds the chunked-vs-unchunked precision divergence in bf16 mode.

The chunked scan path casts the f32 per-tile grams back to bf16 before
the one-hot tile→row MXU reduction (ops/als.py _half_step_local), while
the unchunked path segment-sums the f32 grams directly — a deliberate
trade (the reduction dominates the chunked path's FLOPs). This test pins
the consequence: factors from the two paths agree to bf16-commensurate
tolerance, and both fit the ratings equally well.
"""

import numpy as np

from incubator_predictionio_tpu.ops.als import (
    ALSParams,
    predict_rmse,
    train_als,
)
from incubator_predictionio_tpu.parallel.mesh import default_mesh


def _ratings(n_users=96, n_items=64, density=0.4, seed=7):
    rng = np.random.default_rng(seed)
    xu = rng.standard_normal((n_users, 4))
    xi = rng.standard_normal((n_items, 4))
    full = xu @ xi.T + 0.01 * rng.standard_normal((n_users, n_items))
    mask = rng.random((n_users, n_items)) < density
    u, i = np.nonzero(mask)
    return u.astype(np.int32), i.astype(np.int32), full[u, i].astype(np.float32)


def test_chunked_bf16_matches_unchunked_bf16_within_bound():
    u, i, r = _ratings()
    mesh = default_mesh()
    base = dict(rank=8, num_iterations=6, reg=0.1, seed=11, block_len=8,
                compute_dtype="bfloat16")
    f_unchunked = train_als(u, i, r, 96, 64,
                            ALSParams(**base, chunk_tiles=0), mesh=mesh)
    f_chunked = train_als(u, i, r, 96, 64,
                          ALSParams(**base, chunk_tiles=4), mesh=mesh)

    # Per-entry gram rounding is one bf16 ulp (rel ~2^-8) before an f32
    # accumulation, but the drift compounds through the alternating
    # solves (each half-step consumes the other side's factors), so raw
    # factors can differ by a few percent. Bound that compounded drift...
    for a, b in ((f_unchunked.user_factors, f_chunked.user_factors),
                 (f_unchunked.item_factors, f_chunked.item_factors)):
        rms = float(np.sqrt(np.mean((a - b) ** 2)))
        scale = float(np.sqrt(np.mean(a**2)))
        assert rms / scale < 0.1, (rms, scale)

    # ...and pin the invariant that matters: predictions agree and both
    # variants FIT equally well — the divergence is rounding, not a
    # quality regression.
    pu = np.sum(f_unchunked.user_factors[u] * f_unchunked.item_factors[i],
                axis=1)
    pc = np.sum(f_chunked.user_factors[u] * f_chunked.item_factors[i],
                axis=1)
    pred_rms = float(np.sqrt(np.mean((pu - pc) ** 2)))
    assert pred_rms / float(np.sqrt(np.mean(pu**2))) < 3e-2, pred_rms

    rmse_u = predict_rmse(f_unchunked, u, i, r)
    rmse_c = predict_rmse(f_chunked, u, i, r)
    assert abs(rmse_u - rmse_c) < 5e-3, (rmse_u, rmse_c)
    assert rmse_c < 0.2
