"""A tiny jax-free DASE engine for the model-lifecycle chaos harness
(tests/test_model_lifecycle.py + tests/lifecycle_server.py).

The algorithm's params select what kind of model a train produces:

- ``mode=good``    — answers every query
- ``mode=poison``  — passes the swap validation gate (the golden query
  "golden" still works, arrays are finite) but raises on every OTHER
  user: the canary regime the post-swap error-rate watch must catch
- ``mode=nan``     — carries a NaN weight array: the nan_guard leg of
  the validation gate must refuse it before it ever serves

Both the test process and the subprocess server import this module by
name, so pickled models round-trip across processes."""

from __future__ import annotations

import dataclasses

import numpy as np

from incubator_predictionio_tpu.controller.algorithm import Algorithm
from incubator_predictionio_tpu.controller.datasource import DataSource
from incubator_predictionio_tpu.controller.engine import Engine


@dataclasses.dataclass
class LifecycleModel:
    tag: str
    mode: str
    weights: np.ndarray

    def example_query(self):
        # the warm-up / probe / swap-gate golden query protocol
        return {"user": "golden"}


class LifecycleDataSource(DataSource):
    def read_training(self, ctx):
        return None


class LifecycleAlgorithm(Algorithm):
    def _params(self) -> dict:
        return self.params if isinstance(self.params, dict) else {}

    def train(self, ctx, prepared_data):
        p = self._params()
        mode = str(p.get("mode", "good"))
        weights = (np.array([1.0, float("nan")]) if mode == "nan"
                   else np.ones(3))
        return LifecycleModel(tag=str(p.get("tag", "")), mode=mode,
                              weights=weights)

    def predict(self, model, query):
        user = query["user"]
        if model.mode == "poison" and user != "golden":
            raise RuntimeError("poisoned model: predict exploded")
        # per-query latency knob for the watch/hedge race tests (a
        # poison model raises BEFORE sleeping, so a canary failure
        # spends none of the budget while a hedge can spend all of it)
        delay = float(query.get("sleepS", 0) or 0)
        if delay:
            import time

            time.sleep(delay)
        return {"user": user, "tag": model.tag,
                "score": float(model.weights[0])}

    # no jax: the pickled payload is the model itself
    def prepare_model_for_persistence(self, model):
        return model

    def restore_model(self, stored, ctx):
        return stored


def engine_factory() -> Engine:
    return Engine(LifecycleDataSource, None, {"": LifecycleAlgorithm}, None)


def engine_params(tag: str, mode: str = "good"):
    from incubator_predictionio_tpu.controller.engine import EngineParams

    return EngineParams(algorithm_params_list=[
        ("", {"tag": tag, "mode": mode})])
