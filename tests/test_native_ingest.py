"""Native batch-ingest fast path (reference ★ hot path: EventServer →
validate → store Put; here one C pass over the raw /batch/events.json
body). Parity contract: through the JSONL store the native path must be
indistinguishable from the Python path — same stored semantics, same
per-item responses — and every anomaly must fall back to Python for
exact error messages."""

import datetime as dt
import json

import pytest
import requests

from incubator_predictionio_tpu.data.api.event_server import EventServer
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.data.storage.base import AccessKey, App

from server_utils import ServerThread


@pytest.fixture()
def jsonl_storage(tmp_path):
    s = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_EV_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path / "events"),
    })
    s.get_meta_data_apps().insert(App(0, "napp"))
    s.get_meta_data_access_keys().insert(AccessKey("nk", 1, ()))
    s.get_l_events().init(1)
    yield s
    s.close()


BATCH = [
    {"event": "view", "entityType": "user", "entityId": "u1",
     "targetEntityType": "item", "targetEntityId": 42,
     "properties": {"rating": 4.5, "nested": {"a": [1, "ü\"x"]}},
     "eventTime": "2024-03-05T06:07:08.123456+05:30",
     "tags": ["a", "b\"q"], "prId": "p1"},
    {"event": "$set", "entityType": "item", "entityId": "i1",
     "properties": {"categories": ["x"]}},
    {"event": "buy", "entityType": "user", "entityId": 7},
]


def test_kill_switch_covers_resident_hot_path(monkeypatch):
    """PIO_DISABLE_NATIVE must disable the batch fast path PER CALL
    even after the codec is resident: ingest_batch reads the cached
    library through loaded(), and loaded() re-checks the flag exactly
    like the lazy loader — flipping the switch with a warm library
    must not leave /batch running the supposedly-disabled codec."""
    from incubator_predictionio_tpu import native

    monkeypatch.delenv("PIO_DISABLE_NATIVE", raising=False)
    if not native.available():
        pytest.skip("no native toolchain in this environment")
    assert native.loaded() is not None
    monkeypatch.setenv("PIO_DISABLE_NATIVE", "1")
    assert native.loaded() is None
    with pytest.raises(native.NativeUnavailable):
        native.ingest_batch(b"[]", 50, "2026-01-01T00:00:00.000Z")
    monkeypatch.delenv("PIO_DISABLE_NATIVE")
    assert native.loaded() is not None


def _ingest(storage, body, monkeypatch=None, disable_native=False):
    if monkeypatch is not None:
        if disable_native:
            monkeypatch.setenv("PIO_DISABLE_NATIVE", "1")
        else:
            monkeypatch.delenv("PIO_DISABLE_NATIVE", raising=False)
    with ServerThread(EventServer(storage).app) as st:
        return requests.post(
            st.base + "/batch/events.json?accessKey=nk", json=body)


def _normalized(storage):
    """Stored events minus server-assigned fields, for cross-path diff."""
    out = []
    for e in storage.get_l_events().find(1):
        d = e.to_json()
        d.pop("eventId")
        if d["eventTime"] == d["creationTime"]:
            d.pop("eventTime")  # server-assigned wall clock, run-dependent
        d.pop("creationTime")
        out.append(d)
    return sorted(out, key=lambda d: (d["event"], str(d["entityId"])))


def test_native_path_matches_python_path(jsonl_storage, tmp_path, monkeypatch):
    r = _ingest(jsonl_storage, BATCH, monkeypatch, disable_native=False)
    assert r.status_code == 200
    assert all(x["status"] == 201 and len(x["eventId"]) == 32
               for x in r.json())
    native_stored = _normalized(jsonl_storage)
    assert len(native_stored) == 3

    py = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_EV_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path / "py_events"),
    })
    py.get_meta_data_apps().insert(App(0, "napp"))
    py.get_meta_data_access_keys().insert(AccessKey("nk", 1, ()))
    py.get_l_events().init(1)
    r = _ingest(py, BATCH, monkeypatch, disable_native=True)
    assert r.status_code == 200
    assert _normalized(py) == native_stored
    py.close()


def test_mixed_validity_batch_falls_back_with_exact_errors(jsonl_storage):
    body = [BATCH[0],
            {"event": "view", "entityType": "user"},  # missing entityId
            {"event": "$nope", "entityType": "x", "entityId": "1"},
            BATCH[2]]
    r = _ingest(jsonl_storage, body)
    assert r.status_code == 200
    out = r.json()
    assert out[0]["status"] == 201 and out[3]["status"] == 201
    assert out[1]["status"] == 400 and "entityId" in out[1]["message"]
    assert out[2]["status"] == 400 and "reserved" in out[2]["message"]
    assert len(_normalized(jsonl_storage)) == 2


def test_client_event_id_and_whitelist_fall_back(jsonl_storage):
    # client-supplied eventId → upsert semantics only the python path has
    eid = "a" * 32
    body = [dict(BATCH[2], eventId=eid)]
    r = _ingest(jsonl_storage, body)
    assert r.json()[0]["eventId"] == eid
    assert jsonl_storage.get_l_events().get(eid, 1) is not None

    # per-key whitelist → python path enforces it
    jsonl_storage.get_meta_data_access_keys().insert(
        AccessKey("wl", 1, ("view",)))
    with ServerThread(EventServer(jsonl_storage).app) as st:
        r = requests.post(st.base + "/batch/events.json?accessKey=wl",
                          json=[BATCH[0], BATCH[2]])
    out = r.json()
    assert out[0]["status"] == 201
    assert out[1]["status"] == 400  # "buy" not whitelisted


def test_over_cap_and_malformed_bodies(jsonl_storage):
    r = _ingest(jsonl_storage, [BATCH[2]] * 51)
    assert r.status_code == 400
    assert "50" in r.json()["message"]
    with ServerThread(EventServer(jsonl_storage).app) as st:
        r = requests.post(st.base + "/batch/events.json?accessKey=nk",
                          data="}{",
                          headers={"Content-Type": "application/json"})
        assert r.status_code == 400
        r = requests.post(st.base + "/batch/events.json?accessKey=nk",
                          json={"not": "a list"})
        assert r.status_code == 400


def test_native_events_round_trip_through_training_scan(jsonl_storage, monkeypatch):
    """Events written by the C path must be scannable by the native
    columnar reader AND the Python row reader (they feed training)."""
    r = _ingest(jsonl_storage, BATCH, monkeypatch, disable_native=False)
    assert r.status_code == 200
    le = jsonl_storage.get_l_events()
    events = list(le.find(1, event_names=["view"]))
    assert len(events) == 1
    e = events[0]
    assert e.target_entity_id == "42"  # int id stringified, python parity
    assert e.event_time == dt.datetime(
        2024, 3, 5, 0, 37, 8, 123000, tzinfo=dt.timezone.utc)
    assert e.properties.get("nested") == {"a": [1, "ü\"x"]}
    assert e.tags == ("a", 'b"q')


def test_strict_json_never_wider_than_python(jsonl_storage):
    """Bytes Python's json.loads rejects must NEVER take the native fast
    path into the log (poisoned records would break every later scan):
    they fall back and 400 like before."""
    le = jsonl_storage.get_l_events()
    base = ('{"event": "view", "entityType": "user", "entityId": "u",'
            ' "properties": %s}')
    with ServerThread(EventServer(jsonl_storage).app) as st:
        url = st.base + "/batch/events.json?accessKey=nk"
        hdr = {"Content-Type": "application/json"}
        for props in ('{"a": +1}', '{"a": 007}', '{"a": .5}', '{"a": 1.}',
                      '{"a": "ctrl\x01char"}'):
            r = requests.post(url, data=("[" + base % props + "]").encode(),
                              headers=hdr)
            assert r.status_code == 400, props
        # invalid UTF-8 body
        r = requests.post(url, data=b'[{"event": "\xff\xfe"}]', headers=hdr)
        assert r.status_code == 400
        # out-of-range times Python rejects → per-item 400, nothing stored
        for t in ("2026-02-31T10:00:00Z", "2026-01-01T99:00:00Z",
                  "0000-01-01T00:00:00Z"):
            r = requests.post(url, json=[
                {"event": "view", "entityType": "user", "entityId": "u",
                 "eventTime": t}])
            assert r.status_code == 200
            assert r.json()[0]["status"] == 400, t
    # the log stayed clean: full scan parses
    assert list(le.find(1)) == []


def test_strict_but_valid_edge_cases_stored_readably(jsonl_storage):
    """Exotic-but-valid payloads: whichever path takes them, every stored
    record must read back through the scan."""
    body = [
        {"event": "view", "entityType": "user", "entityId": "u1",
         "properties": {"f": -0.5e3, "z": 0, "neg": -0, "s": "tab\tok",
                        "uni": "é中"},
         "eventTime": "2024-12-31T23:59:59.999999Z"},
        {"event": "view", "entityType": "user", "entityId": "u2",
         "eventTime": "2024-06-01T12:00:00+14:00"},  # valid extreme offset
    ]
    with ServerThread(EventServer(jsonl_storage).app) as st:
        r = requests.post(st.base + "/batch/events.json?accessKey=nk",
                          json=body)
    assert r.status_code == 200
    assert all(x["status"] == 201 for x in r.json())
    got = {e.entity_id: e for e in jsonl_storage.get_l_events().find(1)}
    assert got["u1"].properties.get("f") == -500.0
    assert got["u1"].properties.get("s") == "tab\tok"
    assert got["u1"].event_time.microsecond == 999000  # ms truncation
    assert got["u2"].event_time == dt.datetime(
        2024, 5, 31, 22, 0, tzinfo=dt.timezone.utc)


def test_duplicate_json_keys_match_json_loads(jsonl_storage, tmp_path,
                                              monkeypatch):
    """Duplicate keys in one event object: json.loads (the Python path)
    is last-wins; the native parser's single-pass field state is not
    safely overwritable (a second null targetEntityType would leave the
    first value's state behind), so any duplicate known key must force
    the Python fallback — stored semantics identical either way."""
    raw = ('[{"event": "view", "entityType": "user", "entityId": "u1", '
           '"targetEntityType": "item", "targetEntityId": "i9", '
           '"targetEntityType": null, "targetEntityId": null, '
           '"properties": {"rating": 1}, "properties": {"rating": 9}, '
           '"eventTime": "2024-01-01T00:00:00Z", '
           '"eventTime": "2024-02-02T00:00:00Z"}]')

    def post(storage):
        with ServerThread(EventServer(storage).app) as st:
            return requests.post(
                st.base + "/batch/events.json?accessKey=nk", data=raw,
                headers={"Content-Type": "application/json"})

    monkeypatch.delenv("PIO_DISABLE_NATIVE", raising=False)
    r = post(jsonl_storage)
    assert r.status_code == 200 and r.json()[0]["status"] == 201
    native_stored = _normalized(jsonl_storage)
    assert len(native_stored) == 1
    e = native_stored[0]
    # last-wins semantics, exactly like json.loads:
    assert e.get("targetEntityType") is None
    assert e.get("targetEntityId") is None
    assert e["properties"] == {"rating": 9}
    assert e["eventTime"].startswith("2024-02-02")

    py = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_EV_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path / "py_events_dup"),
    })
    py.get_meta_data_apps().insert(App(0, "napp"))
    py.get_meta_data_access_keys().insert(AccessKey("nk", 1, ()))
    py.get_l_events().init(1)
    monkeypatch.setenv("PIO_DISABLE_NATIVE", "1")
    r = post(py)
    assert r.status_code == 200 and r.json()[0]["status"] == 201
    assert _normalized(py) == native_stored
    py.close()
