"""Golden HTTP request-sequence tests for the REST-family backends.

Completes the recorded-fixture guard across every network protocol
(VERDICT r3 missing #1): where the SQL/HBase clients pin raw socket
bytes, the REST-family clients (Elasticsearch, WebHDFS, S3) pin the
ordered HTTP request sequence — method, path+query, the protocol-
relevant headers, and the exact body — rendered with the ephemeral
mock port normalized.  S3 additionally pins the FULL SigV4 signature
chain by fixing the signing clock and binding the mock to a fixed
port (the signature covers host and x-amz-date).

Regenerate after an INTENTIONAL protocol change:
    PIO_REGEN_GOLDEN=1 python -m pytest tests/test_http_golden.py
"""

import datetime as dt
import os
import urllib.request

import pytest

from server_utils import ServerThread

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

#: headers that carry protocol semantics; everything else (user-agent,
#: content-length auto-fill, connection) is transport noise
_KEEP_HEADERS = {"content-type", "accept", "x-amz-date",
                 "x-amz-content-sha256", "authorization", "host"}


def _record_requests(monkeypatch, conversation, port: int) -> str:
    lines = []
    real_urlopen = urllib.request.urlopen

    def recording_urlopen(req, timeout=None, **kw):
        if isinstance(req, urllib.request.Request):
            method = req.get_method()
            url = req.full_url
            headers = {k.lower(): v for k, v in req.header_items()}
            body = req.data or b""
        else:   # plain URL string
            method, url, headers, body = "GET", req, {}, b""
        url = url.replace(f"127.0.0.1:{port}", "HOST")
        kept = sorted(f"{k}: {v.replace(f'127.0.0.1:{port}', 'HOST')}"
                      for k, v in headers.items() if k in _KEEP_HEADERS)
        lines.append(f"{method} {url}\n" + "\n".join(kept)
                     + f"\nbody: {body.hex()}\n")
        return real_urlopen(req, timeout=timeout, **kw)

    monkeypatch.setattr(urllib.request, "urlopen", recording_urlopen)
    conversation()
    return "\n".join(lines)


def _check_golden(name: str, rendered: str):
    assert rendered, "no requests recorded"
    path = os.path.join(FIXTURES, name)
    if os.environ.get("PIO_REGEN_GOLDEN") == "1":
        os.makedirs(FIXTURES, exist_ok=True)
        with open(path, "w") as f:
            f.write(rendered)
        pytest.skip(f"golden regenerated at {path}")
    assert os.path.exists(path), (
        f"golden fixture missing; generate with PIO_REGEN_GOLDEN=1 ({path})")
    with open(path) as f:
        expected = f.read()
    assert rendered == expected, (
        f"{name}: HTTP request sequence changed. Intentional protocol "
        "change => regenerate with PIO_REGEN_GOLDEN=1 and review the "
        "diff; otherwise a refactor silently altered the client protocol."
    )


def test_es_http_golden(monkeypatch):
    from es_mock import build_es_app

    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.datamap import DataMap
    from incubator_predictionio_tpu.data.storage.event import Event

    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    with ServerThread(build_es_app()) as srv:
        env = {
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "ES",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "S",
            "PIO_STORAGE_SOURCES_S_TYPE": "MEMORY",
            "PIO_STORAGE_SOURCES_ES_TYPE": "ELASTICSEARCH",
            "PIO_STORAGE_SOURCES_ES_HOSTS": "127.0.0.1",
            "PIO_STORAGE_SOURCES_ES_PORTS": str(srv.port),
        }

        def conversation():
            s = Storage(env)
            le = s.get_l_events()
            le.insert(Event("view", "user", "u1", "item", "i1", DataMap(),
                            t0, event_id="ev-golden-1",
                            creation_time=t0), 1)
            le.insert_batch([
                Event("buy", "user", "u2", "item", "i2",
                      DataMap({"q": 2}), t0 + dt.timedelta(seconds=1),
                      event_id="ev-golden-2", creation_time=t0),
                Event("$set", "item", "i3",
                      properties=DataMap({"cat": "a"}),
                      event_time=t0 + dt.timedelta(seconds=2),
                      event_id="ev-golden-3", creation_time=t0),
            ], 1)
            list(le.find(1, event_names=["buy"]))
            le.get("ev-golden-1", 1)
            le.delete("ev-golden-3", 1)
            s.close()

        rendered = _record_requests(monkeypatch, conversation, srv.port)
    _check_golden("es_http_golden.txt", rendered)


def test_hdfs_http_golden(monkeypatch):
    from hdfs_mock import build_hdfs_app

    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import Model

    with ServerThread(build_hdfs_app()) as srv:
        env = {
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DFS",
            "PIO_STORAGE_SOURCES_S_TYPE": "MEMORY",
            "PIO_STORAGE_SOURCES_DFS_TYPE": "HDFS",
            "PIO_STORAGE_SOURCES_DFS_HOSTS": "127.0.0.1",
            "PIO_STORAGE_SOURCES_DFS_PORTS": str(srv.port),
            "PIO_STORAGE_SOURCES_DFS_PATH": "/pio/models",
        }

        def conversation():
            s = Storage(env)
            models = s.get_model_data_models()
            models.insert(Model("m-golden", b"\x00\x01blob"))
            models.get("m-golden")
            models.delete("m-golden")
            s.close()

        rendered = _record_requests(monkeypatch, conversation, srv.port)
    _check_golden("hdfs_http_golden.txt", rendered)


S3_GOLDEN_PORT = 39553


def test_s3_http_golden(monkeypatch):
    """Fixed port + fixed clock: the SigV4 Authorization header covers
    host and x-amz-date, so the full signature chain is pinned."""
    from s3_mock import build_s3_app

    from incubator_predictionio_tpu.data.storage import Storage, s3 as s3_mod
    from incubator_predictionio_tpu.data.storage.base import Model

    class FixedDateTime(dt.datetime):
        @classmethod
        def now(cls, tz=None):
            return cls(2026, 1, 2, 3, 4, 5, tzinfo=tz)

    monkeypatch.setattr(s3_mod._dt, "datetime", FixedDateTime)
    # the mock re-derives the signature from the request's own
    # x-amz-date header, so a fixed client clock stays verifiable
    try:
        server = ServerThread(build_s3_app("AKGOLDEN", "s3cr3t"),
                              port=S3_GOLDEN_PORT)
    except OSError:
        pytest.skip(f"port {S3_GOLDEN_PORT} unavailable")
    with server as srv:
        env = {
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "S",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "OBJ",
            "PIO_STORAGE_SOURCES_S_TYPE": "MEMORY",
            "PIO_STORAGE_SOURCES_OBJ_TYPE": "S3",
            "PIO_STORAGE_SOURCES_OBJ_ENDPOINT":
                f"http://127.0.0.1:{srv.port}",
            "PIO_STORAGE_SOURCES_OBJ_BUCKET": "pio-models",
            "PIO_STORAGE_SOURCES_OBJ_ACCESS_KEY": "AKGOLDEN",
            "PIO_STORAGE_SOURCES_OBJ_SECRET_KEY": "s3cr3t",
        }

        def conversation():
            s = Storage(env)
            models = s.get_model_data_models()
            models.insert(Model("m-golden", b"\x00\x01blob"))
            models.get("m-golden")
            models.delete("m-golden")
            s.close()

        rendered = _record_requests(monkeypatch, conversation, srv.port)
    _check_golden("s3_http_golden.txt", rendered)
