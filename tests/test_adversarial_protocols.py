"""Adversarial-server tests for the network storage backends.

The r3 verdict's honest caveat: a protocol implemented and tested only
against its own well-behaved mock can agree with itself and still
diverge from real servers. These tests teach each mock the awkward-but-
legal (and the broken-but-observed) server behaviors and pin the client
contract: correct results where the protocol allows, clean TYPED errors
where it doesn't — never silent corruption.

Covered (VERDICT r3 next-round #5):
- PG: NoticeResponse/ParameterStatus interleaved mid-query, legacy
  ``bytea_output='escape'`` servers, SASL mechanism lists led by the
  channel-binding variant.
- ES: partial-failure ``_bulk`` 200s, shard-failure 200s, server-side
  search timeouts.
- WebHDFS: direct-write gateways that answer CREATE without the 307
  redirect (payload would silently vanish), 307s without Location.
- S3: clock-skew 403s (RequestTimeTooSkewed).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from server_utils import ServerThread  # noqa: E402


# -- PostgreSQL ---------------------------------------------------------------

def _pg_conn(srv):
    from incubator_predictionio_tpu.data.storage.pgwire import PGConnection

    return PGConnection("127.0.0.1", srv.port, "pio", "piosecret", "pio")


def test_pg_async_messages_mid_query():
    """Notice/ParameterStatus may arrive at any point — before the row
    description AND between data rows; rows must come back intact."""
    from pg_mock import MockPGServer

    with MockPGServer(mode="noisy") as srv:
        c = _pg_conn(srv)
        c.query("CREATE TABLE n (a BIGINT, b TEXT)")
        for i in range(3):
            c.query("INSERT INTO n VALUES ($1,$2)", (i, f"v{i}"))
        cols, rows = c.query("SELECT a, b FROM n ORDER BY a")
        assert rows == [["0", "v0"], ["1", "v1"], ["2", "v2"]]
        c.close()


def test_pg_bytea_escape_server_roundtrips_blobs():
    """A server stuck on bytea_output='escape' (SET ignored by an old
    server or pooler) must still round-trip byte-exact blobs — the
    escape format is decoded, not returned as corrupt text."""
    from pg_mock import MockPGServer

    with MockPGServer(mode="bytea_escape") as srv:
        c = _pg_conn(srv)
        c.query("CREATE TABLE m (id TEXT PRIMARY KEY, blob BYTEA)")
        payload = bytes(range(256)) + b"\\x5c\\" + b"tricky\\\\path"
        c.query("INSERT INTO m VALUES ($1,$2)", ("k", payload))
        _, rows = c.query("SELECT blob FROM m WHERE id=$1", ("k",))
        assert rows[0][0] == payload
        c.close()


def test_pg_scram_mechanism_list_with_channel_binding():
    """Server advertises SCRAM-SHA-256-PLUS first (TLS-capable); a
    non-TLS client must select plain SCRAM-SHA-256 and authenticate."""
    from pg_mock import MockPGServer

    with MockPGServer(mode="scram_plus") as srv:
        c = _pg_conn(srv)
        _, rows = c.query("SELECT 1")
        assert rows == [["1"]]
        c.close()


# -- Elasticsearch ------------------------------------------------------------

def _es_events(srv):
    from incubator_predictionio_tpu.data.storage.base import (
        StorageClientConfig,
    )
    from incubator_predictionio_tpu.data.storage.elasticsearch import (
        ESClient,
    )

    return ESClient(StorageClientConfig(properties={
        "HOSTS": "127.0.0.1", "PORTS": str(srv.port)})).l_events()


def test_es_bulk_partial_failure_raises():
    """_bulk can return HTTP 200 with errors=true and per-item failures
    (queue rejection): the batch must fail loudly, not half-succeed in
    silence."""
    import datetime as dt

    from es_mock import build_es_app

    from incubator_predictionio_tpu.data.storage.elasticsearch import (
        ESStorageError,
    )
    from incubator_predictionio_tpu.data.storage.event import Event

    with ServerThread(build_es_app(mode="bulk_partial_failure")) as srv:
        le = _es_events(srv)
        evs = [Event("view", "user", str(i),
                     event_time=dt.datetime(2026, 1, 1,
                                            tzinfo=dt.timezone.utc))
               for i in range(5)]
        with pytest.raises(ESStorageError, match="bulk insert"):
            le.insert_batch(evs, 1)


def test_es_shard_failure_200_refused():
    """A 200 _search with failed shards is PARTIAL data — for an event
    store that's silent data loss; the client must refuse it."""
    import datetime as dt

    from es_mock import build_es_app

    from incubator_predictionio_tpu.data.storage.elasticsearch import (
        ESStorageError,
    )
    from incubator_predictionio_tpu.data.storage.event import Event

    with ServerThread(build_es_app(mode="shard_failure")) as srv:
        le = _es_events(srv)
        le.insert(Event("view", "user", "1",
                        event_time=dt.datetime(2026, 1, 1,
                                               tzinfo=dt.timezone.utc)), 1)
        with pytest.raises(ESStorageError, match="shards failed"):
            list(le.find(1))


def test_es_search_timeout_refused():
    import datetime as dt

    from es_mock import build_es_app

    from incubator_predictionio_tpu.data.storage.elasticsearch import (
        ESStorageError,
    )
    from incubator_predictionio_tpu.data.storage.event import Event

    with ServerThread(build_es_app(mode="search_timeout")) as srv:
        le = _es_events(srv)
        le.insert(Event("view", "user", "1",
                        event_time=dt.datetime(2026, 1, 1,
                                               tzinfo=dt.timezone.utc)), 1)
        with pytest.raises(ESStorageError, match="timeout"):
            list(le.find(1))


# -- WebHDFS ------------------------------------------------------------------

def _hdfs_models(srv):
    from incubator_predictionio_tpu.data.storage.base import (
        StorageClientConfig,
    )
    from incubator_predictionio_tpu.data.storage.hdfs import HDFSClient

    return HDFSClient(StorageClientConfig(properties={
        "HOSTS": "127.0.0.1", "PORTS": str(srv.port),
        "PATH": "/pio/models"})).models()


def test_hdfs_direct_write_gateway_does_not_lose_payload():
    """HttpFS-style gateways answer the CREATE NameNode leg directly
    (no 307). The naive two-step would 'succeed' having sent an EMPTY
    body; the client must detect the missing redirect and re-send the
    payload so the stored blob is byte-exact."""
    from hdfs_mock import build_hdfs_app

    from incubator_predictionio_tpu.data.storage.base import Model

    with ServerThread(build_hdfs_app(mode="no_redirect")) as srv:
        models = _hdfs_models(srv)
        payload = os.urandom(2048)
        models.insert(Model("m1", payload))
        got = models.get("m1")
        assert got is not None and got.models == payload


def test_hdfs_redirect_without_location_is_typed_error():
    from hdfs_mock import build_hdfs_app

    from incubator_predictionio_tpu.data.storage.base import Model
    from incubator_predictionio_tpu.data.storage.hdfs import (
        HDFSStorageError,
    )

    with ServerThread(build_hdfs_app(mode="redirect_no_location")) as srv:
        models = _hdfs_models(srv)
        with pytest.raises(HDFSStorageError, match="without a Location"):
            models.insert(Model("m1", b"payload"))


# -- S3 -----------------------------------------------------------------------

def test_s3_clock_skew_403_is_actionable_typed_error():
    from s3_mock import build_s3_app

    from incubator_predictionio_tpu.data.storage.base import (
        Model, StorageClientConfig,
    )
    from incubator_predictionio_tpu.data.storage.s3 import (
        S3Client, S3StorageError,
    )

    with ServerThread(build_s3_app("AK", "sk", mode="clock_skew")) as srv:
        models = S3Client(StorageClientConfig(properties={
            "ENDPOINT": f"http://127.0.0.1:{srv.port}",
            "BUCKET": "b", "ACCESS_KEY": "AK", "SECRET_KEY": "sk",
        })).models()
        with pytest.raises(S3StorageError, match="clock"):
            models.insert(Model("m1", b"x"))
        with pytest.raises(S3StorageError, match="RequestTimeTooSkewed"):
            models.get("m1")
