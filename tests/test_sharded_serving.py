"""Serve-time sharded models (the PAlgorithm serving analog).

Reference: core/.../controller/PAlgorithm.scala — batchPredict: models
that stay distributed at serve time. Here: item-factor catalogs sharded
over every device of the 8-CPU virtual mesh, queried via per-shard top-k
+ k-candidate all_gather merge (ops/sharded_topk.py). The invariant under
test is bit-identity with the single-device kernels for the matvec and
similarity paths, and identical indices/ordering (scores ≤2 ULP — gemm
output-shape blocking, documented in the module) for the batched path.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from incubator_predictionio_tpu.ops.sharded_topk import (  # noqa: E402
    put_sharded_catalog,
    sharded_batch_top_k,
    sharded_similar_items,
    sharded_top_k_items,
    should_shard_serving,
)
from incubator_predictionio_tpu.ops.topk import (  # noqa: E402
    batch_top_k,
    similar_items,
    top_k_items,
)
from incubator_predictionio_tpu.parallel.mesh import (  # noqa: E402
    mesh_from_devices,
)


@pytest.fixture(scope="module")
def catalog():
    rng = np.random.default_rng(7)
    n_items, rank = 1003, 16  # deliberately not a multiple of 8 (padding)
    items = rng.normal(size=(n_items, rank)).astype(np.float32)
    return items


@pytest.fixture(scope="module")
def mesh8():
    return mesh_from_devices()  # 1-D over the 8 virtual CPU devices


# -- kernel-level identity --------------------------------------------------


def test_single_query_bit_identical(catalog, mesh8):
    cat = put_sharded_catalog(catalog, mesh8)
    rng = np.random.default_rng(1)
    for _ in range(3):
        uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
        s0, i0 = top_k_items(uv, catalog, 10)
        s1, i1 = sharded_top_k_items(uv, cat, 10)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(s0, s1)  # bitwise


def test_single_query_with_exclude_bit_identical(catalog, mesh8):
    cat = put_sharded_catalog(catalog, mesh8)
    rng = np.random.default_rng(2)
    uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
    excl = np.zeros(catalog.shape[0], bool)
    excl[rng.integers(0, catalog.shape[0], 300)] = True
    s0, i0 = top_k_items(uv, catalog, 25, exclude=excl)
    s1, i1 = sharded_top_k_items(uv, cat, 25, exclude=excl)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


def test_similarity_bit_identical(catalog, mesh8):
    from incubator_predictionio_tpu.ops.topk import normalize_rows

    normed = normalize_rows(catalog)
    cat = put_sharded_catalog(normed, mesh8)
    qv = catalog[[3, 77, 500]]
    excl = np.zeros(catalog.shape[0], bool)
    excl[[3, 77, 500]] = True
    s0, i0 = similar_items(qv, normed, 9, exclude=excl)
    s1, i1 = sharded_similar_items(qv, cat, 9, exclude=excl)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


def test_batch_identical_selection(catalog, mesh8):
    cat = put_sharded_catalog(catalog, mesh8)
    rng = np.random.default_rng(3)
    uvs = rng.normal(size=(13, catalog.shape[1])).astype(np.float32)
    s0, i0 = batch_top_k(uvs, catalog, 7)
    s1, i1 = sharded_batch_top_k(uvs, cat, 7)
    np.testing.assert_array_equal(i0, i1)  # same items, same order
    np.testing.assert_allclose(s0, s1, rtol=0, atol=4e-6)


def test_2d_mesh_matches_1d(catalog):
    """The (d, m)=(4, 2) ALX mesh serves the same answers as the 1-D
    mesh and as a single device — sharding layout is invisible."""
    mesh2 = mesh_from_devices(shape=(4, 2), axis_names=("d", "m"))
    cat = put_sharded_catalog(catalog, mesh2)
    rng = np.random.default_rng(4)
    uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
    s0, i0 = top_k_items(uv, catalog, 12)
    s1, i1 = sharded_top_k_items(uv, cat, 12)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


def test_k_larger_than_shard_rows(mesh8):
    """k greater than a shard's local row count: every shard contributes
    all of its rows and the merge is still exact."""
    rng = np.random.default_rng(5)
    items = rng.normal(size=(40, 4)).astype(np.float32)  # 5 rows/shard
    cat = put_sharded_catalog(items, mesh8)
    uv = rng.normal(size=(4,)).astype(np.float32)
    s0, i0 = top_k_items(uv, items, 20)
    s1, i1 = sharded_top_k_items(uv, cat, 20)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


def test_tie_break_matches_lax_top_k(mesh8):
    """Duplicate scores across shards: the merge must pick the lowest
    global index first, exactly like lax.top_k on the unsharded row."""
    items = np.zeros((64, 2), np.float32)
    items[:, 0] = np.repeat([5.0, 4.0, 3.0, 2.0], 16)  # many exact ties
    cat = put_sharded_catalog(items, mesh8)
    uv = np.array([1.0, 0.0], np.float32)
    s0, i0 = top_k_items(uv, items, 24)
    s1, i1 = sharded_top_k_items(uv, cat, 24)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


# -- sharding policy --------------------------------------------------------


def test_should_shard_policy(mesh8, monkeypatch):
    assert not should_shard_serving(10**6, 64, None, "always")
    assert not should_shard_serving(10**6, 64, mesh8, "never")
    assert should_shard_serving(100, 4, mesh8, "always")
    monkeypatch.setenv("PIO_SHARDED_SERVING_BYTES", "1000000")
    assert should_shard_serving(10**6, 64, mesh8, "auto")
    assert not should_shard_serving(100, 4, mesh8, "auto")
    single = mesh_from_devices(devices=jax.devices()[:1])
    assert not should_shard_serving(10**9, 128, single, "always")
    with pytest.raises(ValueError):
        should_shard_serving(1, 1, mesh8, "sometimes")


# -- template-level: sharded deployment answers like a single chip ----------


def _train_recommendation(memory_storage, sharded: str):
    import datetime as dt

    from incubator_predictionio_tpu.controller import EngineParams
    from incubator_predictionio_tpu.data.storage import App, DataMap, Event
    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import (
        load_deployment,
        run_train,
    )

    name = f"shardapp-{sharded}"
    app_id = memory_storage.get_meta_data_apps().insert(App(0, name))
    le = memory_storage.get_l_events()
    le.init(app_id)
    rng = np.random.default_rng(11)
    t0 = dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc)
    events = []
    for n in range(600):
        u, i = int(rng.integers(0, 40)), int(rng.integers(0, 60))
        events.append(
            Event("rate", "user", str(u), "item", str(i),
                  properties=DataMap({"rating": float(1 + (u * i) % 5)}),
                  event_time=t0 + dt.timedelta(seconds=n)))
    le.insert_batch(events, app_id)

    engine = RecommendationEngine()()
    ctx = WorkflowContext(app_name=name, storage=memory_storage)
    ep = EngineParams.from_json({
        "datasource": {"params": {"appName": name}},
        "algorithms": [{"name": "als", "params": {
            "rank": 8, "numIterations": 3, "computeDtype": "float32",
            "shardedServing": sharded}}],
    })
    iid = run_train(engine, ep, ctx, engine_factory_name=f"rec-{sharded}")
    dep, _, _ = load_deployment(
        engine, iid, WorkflowContext(storage=memory_storage),
        engine_factory_name=f"rec-{sharded}")
    return dep


def test_recommendation_template_sharded_matches_single(memory_storage):
    dep_plain = _train_recommendation(memory_storage, "never")
    dep_shard = _train_recommendation(memory_storage, "always")
    model = dep_shard.models[0]
    assert model.serving_mesh is not None, "always → sharded deployment"
    for user in ("1", "7", "23", "unknown-user"):
        q = {"user": user, "num": 5}
        assert dep_shard.query(q) == dep_plain.query(q)
    # batched path (the serving micro-batch / pio batchpredict surface)
    qs = [{"user": str(u), "num": 4} for u in (0, 3, 9, 31, 39)]
    out_s = dep_shard.batch_query(qs)
    out_p = dep_plain.batch_query(qs)
    for a, b in zip(out_s, out_p):
        assert [x["item"] for x in a["itemScores"]] == [
            x["item"] for x in b["itemScores"]]
        np.testing.assert_allclose(
            [x["score"] for x in a["itemScores"]],
            [x["score"] for x in b["itemScores"]], rtol=0, atol=4e-6)


def test_similar_product_template_sharded_matches_single(memory_storage):
    import datetime as dt

    from incubator_predictionio_tpu.controller import EngineParams
    from incubator_predictionio_tpu.data.storage import App, DataMap, Event
    from incubator_predictionio_tpu.models.similar_product import (
        SimilarProductEngine,
    )
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import (
        load_deployment,
        run_train,
    )

    name = "spshard"
    app_id = memory_storage.get_meta_data_apps().insert(App(0, name))
    le = memory_storage.get_l_events()
    le.init(app_id)
    rng = np.random.default_rng(13)
    t0 = dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc)
    events = [
        Event("view", "user", str(int(rng.integers(0, 30))),
              "item", str(int(rng.integers(0, 50))),
              event_time=t0 + dt.timedelta(seconds=n))
        for n in range(400)
    ]
    le.insert_batch(events, app_id)

    engine = SimilarProductEngine()()
    ctx = WorkflowContext(app_name=name, storage=memory_storage)
    deps = {}
    for mode in ("never", "always"):
        ep = EngineParams.from_json({
            "datasource": {"params": {"appName": name}},
            "algorithms": [{"name": "als", "params": {
                "rank": 8, "numIterations": 3, "computeDtype": "float32",
                "shardedServing": mode}}],
        })
        iid = run_train(engine, ep, ctx, engine_factory_name=f"sp-{mode}")
        deps[mode], _, _ = load_deployment(
            engine, iid, WorkflowContext(storage=memory_storage),
            engine_factory_name=f"sp-{mode}")
    assert deps["always"].models[0].serving_mesh is not None
    for q in ({"items": ["1"], "num": 5},
              {"items": ["2", "9"], "num": 7},
              {"items": ["3"], "num": 5, "blackList": ["4", "5"]}):
        assert deps["always"].query(q) == deps["never"].query(q)


def test_identity_bimap_semantics():
    """IdentityBiMap (huge-catalog serving) must behave exactly like a
    materialized str(i)->i BiMap on every surface models touch."""
    from incubator_predictionio_tpu.data.storage.bimap import (
        BiMap, IdentityBiMap,
    )

    real = BiMap({str(j): j for j in range(10)})
    lazy = IdentityBiMap(10)
    assert len(lazy) == len(real)
    for k in ("0", "7", "9", "10", "-1", "07", "+3", " 5", "x", None):
        assert lazy.get(k) == real.get(k), k
        assert (k in lazy) == (k in real), k
    for v in range(10):
        assert lazy.inverse(v) == real.inverse(v)
    assert lazy.inverse_get(10) is None
    assert list(lazy.keys()) == list(real.keys())
    assert lazy.to_dict() == real.to_dict()
    np = __import__("numpy")
    assert np.array_equal(lazy.map_array(["3", "1"]),
                          real.map_array(["3", "1"]))
    assert lazy.inverse_array([2, 5]) == real.inverse_array([2, 5])


def test_identity_bimap_persistence_round_trip(memory_storage):
    """An IdentityBiMap-backed model persists as a compact marker and
    restores as IdentityBiMap — never materializing the huge dict."""
    from incubator_predictionio_tpu.controller.base import doer
    from incubator_predictionio_tpu.data.storage.bimap import (
        BiMap, IdentityBiMap,
    )
    from incubator_predictionio_tpu.models.recommendation import (
        ALSAlgorithm, ALSModel,
    )
    from incubator_predictionio_tpu.ops.als import ALSFactors

    rng = np.random.default_rng(0)
    model = ALSModel(
        factors=ALSFactors(rng.random((4, 3)).astype(np.float32),
                           rng.random((6, 3)).astype(np.float32), 4, 6),
        users=BiMap({str(j): j for j in range(4)}),
        items=IdentityBiMap(6),
    )
    algo = doer(ALSAlgorithm, {})
    stored = algo.prepare_model_for_persistence(model)
    assert stored["items"] == {"__identity_n__": 6}  # compact, not 6 entries
    restored = algo.restore_model(stored, None)
    assert isinstance(restored.items, IdentityBiMap)
    assert restored.items.inverse(5) == "5"
    assert isinstance(restored.users, BiMap)
    assert restored.users("2") == 2


def test_identity_bimap_rejects_non_str_keys_like_dict_bimap():
    from incubator_predictionio_tpu.data.storage.bimap import (
        BiMap, IdentityBiMap,
    )

    real = BiMap({str(j): j for j in range(10)})
    lazy = IdentityBiMap(10)
    for k in (4, np.int32(4), 4.0, True):
        assert lazy.get(k) == real.get(k) is None, k
    ks = lazy.keys()
    assert len(ks) == 10
    assert list(ks) == list(ks)  # re-iterable, unlike a generator
    assert "7" in ks and "10" not in ks


def test_big_catalog_demo_smoke(monkeypatch):
    """tools/big_catalog_demo.py at toy scale: the capability script must
    keep running end to end (its recorded 17.2 GiB run is only credible
    while the script works)."""
    import importlib.util
    import os

    monkeypatch.setenv("PIO_DEMO_ITEMS", "8000")
    monkeypatch.setenv("PIO_DEMO_RANK", "8")
    spec = importlib.util.spec_from_file_location(
        "big_catalog_demo",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "big_catalog_demo.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


# -- host-sharded (stacked-scan) kernel identity ----------------------------
# ISSUE 17: PIO_SERVE_SHARD_ITEMS stacks the catalog [S, rows, rank] on
# ONE device and scans a per-shard partial top-k; exactness contract is
# the same as the mesh path — bitwise identical on the matvec/similarity
# paths, identical indices (scores ≤2 ULP) on the batched gemm path.

from incubator_predictionio_tpu.models._sharded_serving import (  # noqa: E402
    ShardedCatalog,
    ShardedIndicators,
)
from incubator_predictionio_tpu.ops.llr import (  # noqa: E402
    Indicators,
    score_user,
)
from incubator_predictionio_tpu.ops.sharded_topk import (  # noqa: E402
    host_sharded_batch_top_k,
    host_sharded_score_user,
    host_sharded_similar_items,
    host_sharded_top_k_items,
    put_host_sharded_catalog,
    put_host_sharded_indicators,
)
from incubator_predictionio_tpu.ops.topk import normalize_rows  # noqa: E402


def _rows_for(n_items: int, shards: int) -> int:
    return -(-n_items // shards)


@pytest.mark.parametrize("shards", [1, 2, 4])
def test_host_sharded_single_query_bit_identical(catalog, shards):
    cat = put_host_sharded_catalog(catalog, _rows_for(len(catalog), shards))
    assert cat.n_shards == shards
    rng = np.random.default_rng(11)
    for k in (1, 10, 37):
        uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
        s0, i0 = top_k_items(uv, catalog, k)
        s1, i1 = host_sharded_top_k_items(uv, cat, k)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(s0, s1)  # bitwise


@pytest.mark.parametrize("shards", [2, 4])
def test_host_sharded_exclude_bit_identical(catalog, shards):
    cat = put_host_sharded_catalog(catalog, _rows_for(len(catalog), shards))
    rng = np.random.default_rng(12)
    uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
    exclude = rng.random(len(catalog)) < 0.5
    s0, i0 = top_k_items(uv, catalog, 10, exclude=exclude)
    s1, i1 = host_sharded_top_k_items(uv, cat, 10, exclude=exclude)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)
    assert not exclude[np.asarray(i1)].any()


def test_host_sharded_all_filtered_shard(catalog):
    """An entirely business-rule-excluded shard contributes only -inf
    fillers and the merge still reproduces the unsharded answer."""
    rows = _rows_for(len(catalog), 4)
    cat = put_host_sharded_catalog(catalog, rows)
    rng = np.random.default_rng(13)
    uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
    exclude = np.zeros(len(catalog), bool)
    exclude[rows:2 * rows] = True  # shard 1 fully suppressed
    s0, i0 = top_k_items(uv, catalog, 10, exclude=exclude)
    s1, i1 = host_sharded_top_k_items(uv, cat, 10, exclude=exclude)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


def test_host_sharded_k_larger_than_shard_rows(catalog):
    """k > rows-per-shard: per-shard partials are clamped to the shard
    and the merge still assembles the exact global top-k."""
    cat = put_host_sharded_catalog(catalog, 7)  # 144 shards of 7 rows
    rng = np.random.default_rng(14)
    uv = rng.normal(size=(catalog.shape[1],)).astype(np.float32)
    s0, i0 = top_k_items(uv, catalog, 50)
    s1, i1 = host_sharded_top_k_items(uv, cat, 50)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


def test_host_sharded_duplicate_scores_tie_break(catalog):
    """Duplicate scores across shard boundaries: the two-key merge sort
    must reproduce lax.top_k's tie order (lowest global index first)."""
    items = np.ones((64, 4), np.float32)  # every item scores identically
    uv = np.ones(4, np.float32)
    for shards in (2, 4):
        cat = put_host_sharded_catalog(items, _rows_for(64, shards))
        s0, i0 = top_k_items(uv, items, 9)
        s1, i1 = host_sharded_top_k_items(uv, cat, 9)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(s0, s1)


@pytest.mark.parametrize("shards", [2, 4])
def test_host_sharded_similarity_bit_identical(catalog, shards):
    normed = normalize_rows(catalog)
    cat = put_host_sharded_catalog(normed, _rows_for(len(catalog), shards))
    rng = np.random.default_rng(15)
    qvecs = catalog[rng.integers(0, len(catalog), size=3)]
    exclude = np.zeros(len(catalog), bool)
    exclude[:5] = True
    s0, i0 = similar_items(qvecs, normed, 10, exclude=exclude)
    s1, i1 = host_sharded_similar_items(qvecs, cat, 10, exclude=exclude)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)


@pytest.mark.parametrize("shards", [2, 4])
def test_host_sharded_batch_identical_selection(catalog, shards):
    cat = put_host_sharded_catalog(catalog, _rows_for(len(catalog), shards))
    rng = np.random.default_rng(16)
    uvecs = rng.normal(size=(5, catalog.shape[1])).astype(np.float32)
    s0, i0 = batch_top_k(uvecs, catalog, 10)
    s1, i1 = host_sharded_batch_top_k(uvecs, cat, 10)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_allclose(s0, s1, rtol=0, atol=4e-6)  # gemm ULPs


def _toy_indicators(rng, n_items: int, kc: int = 6) -> Indicators:
    idx = rng.integers(-1, n_items, size=(n_items, kc)).astype(np.int32)
    score = rng.random((n_items, kc)).astype(np.float32)
    return Indicators(idx=idx, score=score)


@pytest.mark.parametrize("shards", [1, 2, 4])
def test_host_sharded_ur_score_user_bit_identical(shards):
    """Universal-recommender scoring: the per-type correlator tables
    shard the same way and the merged answer is bitwise identical
    (row-wise einsum reduction is row-count-invariant)."""
    rng = np.random.default_rng(17)
    n_items = 101
    rows = _rows_for(n_items, shards)
    inds = {"view": _toy_indicators(rng, n_items),
            "buy": _toy_indicators(rng, n_items, kc=3)}
    membership = {n: (rng.random(n_items) < 0.3).astype(np.float32)
                  for n in inds}
    boost = np.where(rng.random(n_items) < 0.1, 2.0, 1.0).astype(np.float32)
    exclude = rng.random(n_items) < 0.2
    plain = [(inds[n], membership[n], b)
             for n, b in (("view", 1.0), ("buy", 2.0))]
    s0, i0 = score_user(plain, 10, exclude=exclude, item_boost=boost)
    sharded = [(put_host_sharded_indicators(inds[n], rows), membership[n], b)
               for n, b in (("view", 1.0), ("buy", 2.0))]
    s1, i1 = host_sharded_score_user(sharded, 10, n_items,
                                     exclude, boost)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))


def test_sharded_catalog_facade_layout_selection(catalog, monkeypatch):
    """ShardedCatalog picks flat with the knob unset, host when the
    knob is smaller than the vocabulary, flat when it is not."""
    monkeypatch.delenv("PIO_SERVE_SHARD_ITEMS", raising=False)
    assert ShardedCatalog(catalog).layout == "flat"
    monkeypatch.setenv("PIO_SERVE_SHARD_ITEMS", "100")
    cat = ShardedCatalog(catalog)
    assert cat.layout == "host" and cat.n_shards == 11
    s0, i0 = top_k_items(np.ones(catalog.shape[1], np.float32), catalog, 10)
    s1, i1 = cat.top_k(np.ones(catalog.shape[1], np.float32), 10)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_array_equal(s0, s1)
    monkeypatch.setenv("PIO_SERVE_SHARD_ITEMS", str(len(catalog) + 1))
    assert ShardedCatalog(catalog).layout == "flat"


def test_sharded_indicators_facade_layout_selection(monkeypatch):
    rng = np.random.default_rng(18)
    inds = {"view": _toy_indicators(rng, 40)}
    monkeypatch.delenv("PIO_SERVE_SHARD_ITEMS", raising=False)
    assert ShardedIndicators(inds, 40).layout == "flat"
    monkeypatch.setenv("PIO_SERVE_SHARD_ITEMS", "16")
    si = ShardedIndicators(inds, 40)
    assert si.layout == "host"
    m = (rng.random(40) < 0.4).astype(np.float32)
    s0, i0 = score_user([(inds["view"], m, 1.0)], 5,
                        exclude=None, item_boost=None)
    s1, i1 = si.score_user([("view", m, 1.0)], 5,
                           exclude=None, item_boost=None)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))
