"""Template-trio parity tests (ROADMAP item 1 rider, PR 16): the
formerly under-tested templates — e-commerce, complementary-purchase,
and the vanilla scaffold — reach tier-1 + eval parity with the big
five, with the continuous-quality machinery (ops/eval.py) as the
acceptance harness: each template's ranking is graded with the SAME
kernel the shadow scorer uses live, against a degraded (reversed)
variant, and the canary-vs-last-good verdict must separate them.

Three layers:
- vanilla in-process workflow (train → persist → reload → query),
  closing the gap where the scaffold only had a subprocess checkout
  test (test_standalone_template.py);
- quality-harness acceptance per template: MetricWindow + quality_verdict
  say "no breach" for template-vs-itself and "breach" for a
  rank-reversed canary over the same queries/labels;
- `pio eval` end-to-end for the three new Evaluation classes
  (models/template_evals.py + the vanilla template's own).
"""

import datetime as dt
import sys
from pathlib import Path

import numpy as np
import pytest

from incubator_predictionio_tpu.controller import EngineParams
from incubator_predictionio_tpu.data.storage import App, DataMap, Event
from incubator_predictionio_tpu.ops.eval import (
    MetricWindow,
    quality_verdict,
    ranking_metrics,
)
from incubator_predictionio_tpu.workflow.context import WorkflowContext
from incubator_predictionio_tpu.workflow.core_workflow import (
    load_deployment,
    run_train,
)
from incubator_predictionio_tpu.workflow.evaluation_workflow import (
    run_evaluation,
)

pytestmark = pytest.mark.quality

T0 = dt.datetime(2024, 1, 1, tzinfo=dt.timezone.utc)
_VANILLA_DIR = str(Path(__file__).resolve().parent.parent
                   / "templates" / "vanilla")


def _vanilla():
    if _VANILLA_DIR not in sys.path:
        sys.path.insert(0, _VANILLA_DIR)
    import vanilla_engine
    return vanilla_engine


def _mk_app(storage, name):
    app_id = storage.get_meta_data_apps().insert(App(0, name))
    storage.get_l_events().init(app_id)
    return app_id


def _ts(i):
    return T0 + dt.timedelta(seconds=i)


def _seed_grouped_views(storage, app_name, n_users=40):
    """Users view items only inside their own half of the catalog →
    the other group's items are known-irrelevant labels."""
    app_id = _mk_app(storage, app_name)
    le = storage.get_l_events()
    rng = np.random.default_rng(3)
    events = []
    for u in range(n_users):
        lo, hi = (0, 10) if u % 2 == 0 else (10, 20)
        for _ in range(12):
            events.append(
                Event("view", "user", str(u), "item",
                      f"i{rng.integers(lo, hi)}", event_time=_ts(len(events))))
    le.insert_batch(events, app_id)
    return app_id


def _seed_baskets(storage, app_name, n_sessions=60):
    """Alternating fixed combos + one noise item per session: the combo
    partners are each other's complements."""
    app_id = _mk_app(storage, app_name)
    le = storage.get_l_events()
    rng = np.random.default_rng(4)
    events = []
    for s in range(n_sessions):
        base = T0 + dt.timedelta(hours=3 * s)
        combo = ["burger", "bun", "ketchup"] if s % 2 else ["pasta", "sauce"]
        for j, item in enumerate(combo + [f"n{rng.integers(20)}"]):
            events.append(Event("buy", "user", f"s{s}", "item", item,
                                DataMap(), base + dt.timedelta(minutes=j)))
    le.insert_batch(events, app_id)
    return app_id


def _seed_popularity(storage, app_name, n_items=12):
    """Item j rated by (n_items - j) distinct users → strictly
    decreasing popularity i0 > i1 > ..."""
    app_id = _mk_app(storage, app_name)
    le = storage.get_l_events()
    events = []
    for j in range(n_items):
        for u in range(n_items - j):
            events.append(Event("view", "user", f"u{u}", "item", f"i{j}",
                                event_time=_ts(len(events))))
    le.insert_batch(events, app_id)
    return app_id


def _train(engine, params_json, ctx, name):
    ep = EngineParams.from_json(params_json)
    iid = run_train(engine, ep, ctx, engine_factory_name=name)
    dep, _, _ = load_deployment(
        engine, iid, WorkflowContext(storage=ctx.get_storage()),
        engine_factory_name=name)
    return dep


def _assert_quality_harness_separates(samples, k=10, min_samples=3):
    """The shadow scorer's verdict machinery over (ranked, labels)
    pairs: identical windows never breach; a rank-reversed canary over
    the same labels does."""
    good, bad = MetricWindow(), MetricWindow()
    for ranked, labels in samples:
        good.add(ranking_metrics([ranked], [labels], k))
        bad.add(ranking_metrics([list(reversed(ranked))], [labels], k))
    assert good.means()["n"] >= min_samples, "harness needs graded samples"

    breach, deltas = quality_verdict(
        good.means(), good.means(), min_samples=min_samples, max_drop=0.05)
    assert not breach and deltas["ndcg"] == 0.0

    breach, deltas = quality_verdict(
        bad.means(), good.means(), min_samples=min_samples, max_drop=0.05)
    assert breach, f"reversed ranking not flagged: {deltas}"
    assert deltas["ndcg"] > 0.05


# -- vanilla: in-process workflow parity -----------------------------------


def test_vanilla_template_workflow(memory_storage):
    ve = _vanilla()
    _seed_popularity(memory_storage, "vanapp")
    ctx = WorkflowContext(app_name="vanapp", storage=memory_storage)
    dep = _train(ve.VanillaEngine()(), {
        "datasource": {"params": {"appName": "vanapp"}},
        "algorithms": [{"name": "popularity", "params": {"ratingWeight": 1.0}}],
    }, ctx, "vanilla")
    r = dep.query({"num": 5})
    items = [s["item"] for s in r["itemScores"]]
    assert items == ["i0", "i1", "i2", "i3", "i4"], items
    scores = [s["score"] for s in r["itemScores"]]
    assert scores == sorted(scores, reverse=True)
    # wire-format parity with the recommendation quickstart
    assert set(r) == {"itemScores"}
    assert set(r["itemScores"][0]) == {"item", "score"}


# -- quality harness as template acceptance --------------------------------


def test_ecommerce_quality_harness(memory_storage):
    from incubator_predictionio_tpu.models.ecommerce import ECommerceEngine

    _seed_grouped_views(memory_storage, "ecqapp")
    ctx = WorkflowContext(app_name="ecqapp", storage=memory_storage)
    dep = _train(ECommerceEngine()(), {
        "datasource": {"params": {"appName": "ecqapp"}},
        "algorithms": [{"name": "ecomm",
                        "params": {"appName": "ecqapp", "rank": 8,
                                   "numIterations": 10}}],
    }, ctx, "ecq")
    samples = []
    for u in ("0", "2", "4", "1", "3", "5"):
        labels = ({f"i{j}" for j in range(10)} if int(u) % 2 == 0
                  else {f"i{j}" for j in range(10, 20)})
        r = dep.query({"user": u, "num": 10, "unseenOnly": False})
        ranked = [s["item"] for s in r["itemScores"]]
        assert ranked
        samples.append((ranked, labels))
    _assert_quality_harness_separates(samples)


def test_complementary_quality_harness(memory_storage):
    from incubator_predictionio_tpu.models.complementary_purchase import (
        ComplementaryPurchaseEngine,
    )

    _seed_baskets(memory_storage, "cpqapp")
    ctx = WorkflowContext(app_name="cpqapp", storage=memory_storage)
    dep = _train(ComplementaryPurchaseEngine()(), {
        "datasource": {"params": {"appName": "cpqapp"}},
        "algorithms": [{"name": "cooccurrence", "params": {"minLLR": 0.0}}],
    }, ctx, "cpq")
    cases = [
        (["burger"], {"bun", "ketchup"}),
        (["bun"], {"burger", "ketchup"}),
        (["pasta"], {"sauce"}),
        (["burger", "bun"], {"ketchup"}),
        (["sauce"], {"pasta"}),
    ]
    samples = []
    for basket, labels in cases:
        r = dep.query({"items": basket, "num": 6})
        ranked = [s["item"] for s in r["itemScores"]]
        assert ranked, f"no complements for {basket}"
        assert ranked[0] in labels, (basket, ranked)
        samples.append((ranked, labels))
    _assert_quality_harness_separates(samples, k=6)


def test_vanilla_quality_harness(memory_storage):
    ve = _vanilla()
    _seed_popularity(memory_storage, "vanqapp")
    ctx = WorkflowContext(app_name="vanqapp", storage=memory_storage)
    dep = _train(ve.VanillaEngine()(), {
        "datasource": {"params": {"appName": "vanqapp"}},
        "algorithms": [{"name": "popularity", "params": {}}],
    }, ctx, "vanq")
    ranked = [s["item"] for s in dep.query({"num": 8})["itemScores"]]
    # every "user" holds out the head of the popularity order
    samples = [(ranked, {"i0", "i1", "i2"}) for _ in range(4)]
    _assert_quality_harness_separates(samples, k=8)


# -- `pio eval` parity: the three new Evaluation classes -------------------


def _assert_eval_result(res, iid, n_params):
    assert res.metric_header.startswith("NDCG@")
    assert len(res.all_results) == n_params
    assert res.best_score == max(s for _, s, _ in res.all_results)
    assert 0.0 < res.best_score <= 1.0
    assert iid


def test_ecommerce_evaluation(memory_storage):
    from incubator_predictionio_tpu.models.template_evals import (
        ECommerceEvaluation, ECommerceParamsList,
    )

    _seed_grouped_views(memory_storage, "eceapp")
    ctx = WorkflowContext(app_name="eceapp", storage=memory_storage)
    gen = ECommerceParamsList("eceapp")
    assert len(gen.engine_params_list) == 4
    gen.engine_params_list = gen.engine_params_list[:2]  # keep the test fast
    res, iid = run_evaluation(ECommerceEvaluation(), gen, ctx,
                              evaluation_name="ECommerceEvaluation",
                              generator_name="ECommerceParamsList")
    _assert_eval_result(res, iid, 2)


def test_complementary_evaluation(memory_storage):
    from incubator_predictionio_tpu.models.template_evals import (
        ComplementaryEvaluation, ComplementaryParamsList,
    )

    _seed_baskets(memory_storage, "cpeapp")
    ctx = WorkflowContext(app_name="cpeapp", storage=memory_storage)
    gen = ComplementaryParamsList("cpeapp")
    assert len(gen.engine_params_list) == 4
    gen.engine_params_list = gen.engine_params_list[:2]
    res, iid = run_evaluation(ComplementaryEvaluation(), gen, ctx,
                              evaluation_name="ComplementaryEvaluation",
                              generator_name="ComplementaryParamsList")
    _assert_eval_result(res, iid, 2)
    # combo partners are recoverable: basket completion beats chance
    assert res.best_score > 0.3, res.all_results


def test_vanilla_evaluation(memory_storage):
    ve = _vanilla()
    _seed_popularity(memory_storage, "vaneapp", n_items=12)
    ctx = WorkflowContext(app_name="vaneapp", storage=memory_storage)
    gen = ve.ParamsList("vaneapp")
    res, iid = run_evaluation(ve.VanillaEvaluation(), gen, ctx,
                              evaluation_name="VanillaEvaluation",
                              generator_name="ParamsList")
    _assert_eval_result(res, iid, 3)
