"""Write-behind group-commit ingestion (data/api/ingest_buffer.py).

Covers the acceptance contract of the group-commit layer:
- stored-event parity between buffered and unbuffered paths (same
  events, same order within a key, same event_ids returned)
- real per-request errors through the buffer (400/403/500)
- mid-group storage faults (PIO_FAULT_SPEC) fail exactly the affected
  requests, leave no partial writes, and a retry does not duplicate
- drain-on-shutdown settles every queued request — none hang
- bounded in-flight cap sheds with 503 + Retry-After
- ack=enqueue fire-and-forget semantics
- batched stats accounting
- webhooks ride the same buffer (e2e through the event server)
- guard: the event server's hot handlers contain no per-event insert()
"""

import asyncio
import json
import threading
import time

import pytest
import requests

from incubator_predictionio_tpu.common import faultinject
from incubator_predictionio_tpu.data.api.event_server import EventServer
from incubator_predictionio_tpu.data.api.ingest_buffer import (
    IngestBuffer, IngestConfig)
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.data.storage.base import AccessKey, App, Channel

from server_utils import ServerThread

T = "2026-01-01T00:00:00.000Z"


def _jsonl_storage(tmp_path, name="ev"):
    env = {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_EV_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_EV_PATH": str(tmp_path / name),
    }
    storage = Storage(env)
    app_id = storage.get_meta_data_apps().insert(App(0, "ingestapp"))
    key = storage.get_meta_data_access_keys().insert(
        AccessKey("", app_id, ()))
    cid = storage.get_meta_data_channels().insert(
        Channel(0, "mobile", app_id))
    return storage, app_id, key, cid


def _ev(i, **kw):
    d = {"event": "view", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": f"i{i}",
         "eventTime": T}
    d.update(kw)
    return d


def _strip(e):
    d = e.to_json()
    d.pop("eventId", None)
    d.pop("creationTime", None)
    return d


def _drive_workload(storage, key):
    """The mixed workload used for cross-mode parity: singles (valid,
    invalid, client-supplied id), a batch with a bad item, a webhook,
    and a channelled event. Returns (responses, stored, stored_chan)."""
    server = EventServer(storage)
    out = []
    with ServerThread(server.app) as st:
        u = f"{st.base}/events.json?accessKey={key}"
        for i in range(3):
            out.append(requests.post(u, json=_ev(i)))
        out.append(requests.post(u, json={"event": "", "entityType": "u",
                                          "entityId": "x"}))  # 400
        out.append(requests.post(u, json=_ev(7, eventId="ab" * 16)))
        out.append(requests.post(
            f"{st.base}/batch/events.json?accessKey={key}",
            json=[_ev(10), {"event": "$unset", "entityType": "user",
                            "entityId": "u11"},  # missing properties → 400
                  _ev(12, properties={"a": 1})]))
        out.append(requests.post(
            f"{st.base}/webhooks/segmentio.json?accessKey={key}",
            json={"type": "track", "userId": "u9", "event": "Signed Up",
                  "properties": {"plan": "Pro"}, "timestamp": T}))
        out.append(requests.post(u + "&channel=mobile", json=_ev(20)))
    app_id = 1
    stored = list(storage.get_l_events().find(app_id))
    stored_chan = list(storage.get_l_events().find(app_id, channel_id=1))
    return out, stored, stored_chan


def test_parity_buffered_vs_unbuffered(tmp_path, monkeypatch):
    """Same workload, buffer off vs on: identical statuses, identical
    stored events in identical order per key, and every 201's returned
    eventId is the stored eventId at that position."""
    runs = {}
    for mode in ("off", "on"):
        monkeypatch.setenv("PIO_INGEST_GROUP", mode)
        storage, _app_id, key, _cid = _jsonl_storage(tmp_path, f"ev_{mode}")
        resp, stored, stored_chan = _drive_workload(storage, key)
        runs[mode] = (resp, stored, stored_chan)

    off_resp, off_stored, off_chan = runs["off"]
    on_resp, on_stored, on_chan = runs["on"]
    assert [r.status_code for r in off_resp] == \
        [r.status_code for r in on_resp]
    # batch per-item statuses match
    i_batch = 5
    assert [x["status"] for x in off_resp[i_batch].json()] == \
        [x["status"] for x in on_resp[i_batch].json()] == [201, 400, 201]
    # same events, same order, both keys
    assert [_strip(e) for e in off_stored] == [_strip(e) for e in on_stored]
    assert [_strip(e) for e in off_chan] == [_strip(e) for e in on_chan]
    assert len(on_stored) == 7  # 3 singles + id'd single + 2 batch + webhook

    def returned_ids(resp):
        ids = []
        for r in resp:
            if r.status_code == 201 and "eventId" in r.json():
                ids.append(r.json()["eventId"])
            elif r.request.url and "batch" in r.request.url:
                ids.extend(x["eventId"] for x in r.json()
                           if x["status"] == 201)
        return ids

    for resp, stored, chan in (runs["off"], runs["on"]):
        got = returned_ids(resp)
        stored_ids = [e.event_id for e in stored] + [e.event_id for e in chan]
        assert sorted(got) == sorted(stored_ids)
    # the client-supplied id round-trips
    assert any(e.event_id == "ab" * 16 for e in on_stored)


@pytest.mark.ingest
def test_concurrent_coalescing_no_loss_no_dup(tmp_path, monkeypatch):
    """Concurrent single POSTs on one key: every request acked with a
    unique id, every id stored exactly once, and the flusher actually
    coalesced (some group > 1 event)."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    storage, app_id, key, _cid = _jsonl_storage(tmp_path)
    server = EventServer(storage)
    N, W = 60, 6
    ids = []
    lock = threading.Lock()
    with ServerThread(server.app) as st:
        u = f"{st.base}/events.json?accessKey={key}"

        def worker(w):
            s = requests.Session()
            for j in range(N // W):
                r = s.post(u, json=_ev(w * 100 + j))
                assert r.status_code == 201, r.text
                with lock:
                    ids.append(r.json()["eventId"])

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(W)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    stored = list(storage.get_l_events().find(app_id))
    assert len(ids) == N == len(set(ids))
    assert sorted(e.event_id for e in stored) == sorted(ids)
    snap = server.ingest.snapshot()
    assert snap["eventsCommitted"] >= N
    assert snap["maxGroup"] > 1, "no coalescing happened under concurrency"


@pytest.mark.chaos
def test_mid_group_fault_fails_only_affected_requests(tmp_path, monkeypatch):
    """A storage fault during one group commit fails exactly that
    group's requests with the real error, leaves NO partial write, and
    a client retry stores the event exactly once."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    monkeypatch.setenv("PIO_FAULT_SPEC", "ingest.commit:fail:1")
    faultinject.reset()
    try:
        storage, app_id, key, _cid = _jsonl_storage(tmp_path)
        server = EventServer(storage)
        with ServerThread(server.app) as st:
            u = f"{st.base}/events.json?accessKey={key}"
            r1 = requests.post(u, json=_ev(1))
            assert r1.status_code == 500
            assert "injected fault" in r1.json()["message"]
            assert list(storage.get_l_events().find(app_id)) == []
            # retry after the fault: exactly one copy, no duplicates
            r2 = requests.post(u, json=_ev(1))
            assert r2.status_code == 201
            # an unrelated key is unaffected
            r3 = requests.post(u + "&channel=mobile", json=_ev(2))
            assert r3.status_code == 201
        stored = list(storage.get_l_events().find(app_id))
        assert len(stored) == 1 and stored[0].entity_id == "u1"
        assert stored[0].event_id == r2.json()["eventId"]
    finally:
        monkeypatch.delenv("PIO_FAULT_SPEC")
        faultinject.reset()


@pytest.mark.chaos
def test_mid_group_fault_batch_reports_per_item(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    monkeypatch.setenv("PIO_FAULT_SPEC", "ingest.commit:fail:1")
    faultinject.reset()
    try:
        storage, app_id, key, _cid = _jsonl_storage(tmp_path)
        # stats on → python batch path → per-item outcomes via buffer
        server = EventServer(storage, enable_stats=True)
        with ServerThread(server.app) as st:
            r = requests.post(
                f"{st.base}/batch/events.json?accessKey={key}",
                json=[_ev(1), {"event": "", "entityType": "u",
                               "entityId": "x"}, _ev(2)])
            assert r.status_code == 200
            statuses = [x["status"] for x in r.json()]
            assert statuses == [500, 400, 500]  # fault hits the valid pair
            assert "injected fault" in r.json()[0]["message"]
        assert list(storage.get_l_events().find(app_id)) == []
    finally:
        monkeypatch.delenv("PIO_FAULT_SPEC")
        faultinject.reset()


@pytest.mark.chaos
@pytest.mark.ingest
def test_drain_on_shutdown_settles_all_requests(tmp_path, monkeypatch):
    """Shutdown with requests queued behind a slow commit: every
    request completes (none hang, none lost)."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    # fsync forces the off-loop commit path; latency holds the first
    # group in flight while more requests queue behind it
    monkeypatch.setenv("PIO_INGEST_FSYNC", "1")
    monkeypatch.setenv("PIO_FAULT_SPEC", "ingest.commit:latency:1:0.4")
    faultinject.reset()
    try:
        storage, app_id, key, _cid = _jsonl_storage(tmp_path)
        server = EventServer(storage)
        results = {}
        st = ServerThread(server.app)
        st.__enter__()
        u = f"{st.base}/events.json?accessKey={key}"

        def post(i):
            results[i] = requests.post(u, json=_ev(i), timeout=30).status_code

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        time.sleep(0.15)  # first group is inside its slow commit
        st.__exit__(None, None, None)  # on_shutdown → buffer.drain()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "request hung through shutdown"
        assert sorted(results.values()) == [201] * 5
        assert len(list(storage.get_l_events().find(app_id))) == 5
    finally:
        monkeypatch.delenv("PIO_FAULT_SPEC")
        faultinject.reset()


@pytest.mark.chaos
def test_overload_sheds_503_with_retry_after(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    monkeypatch.setenv("PIO_INGEST_MAX_PENDING", "1")
    monkeypatch.setenv("PIO_INGEST_FSYNC", "1")  # commit off-loop
    monkeypatch.setenv("PIO_FAULT_SPEC", "ingest.commit:latency:1:0.6")
    faultinject.reset()
    try:
        storage, _app_id, key, _cid = _jsonl_storage(tmp_path)
        server = EventServer(storage)
        with ServerThread(server.app) as st:
            u = f"{st.base}/events.json?accessKey={key}"
            codes = {}

            def post(i):
                codes[i] = requests.post(u, json=_ev(i), timeout=30)

            t1 = threading.Thread(target=post, args=(1,))
            t1.start()
            time.sleep(0.2)  # first event is in its slow commit
            r2 = requests.post(u, json=_ev(2), timeout=30)
            assert r2.status_code == 503
            assert int(r2.headers["Retry-After"]) >= 1
            assert "full" in r2.json()["message"]
            t1.join()
            assert codes[1].status_code == 201
            # capacity freed → accepted again
            assert requests.post(u, json=_ev(3)).status_code == 201
        assert server._shed_count >= 1
    finally:
        monkeypatch.delenv("PIO_FAULT_SPEC")
        faultinject.reset()


def test_retry_after_is_jittered():
    """The shed path's Retry-After must spread retries (full jitter):
    a constant value synchronizes every honouring SDK into one wave."""
    import random

    from incubator_predictionio_tpu.common.resilience import (
        retry_after_jitter)

    rng = random.Random(7)
    vals = {retry_after_jitter(2.0, rng) for _ in range(200)}
    assert len(vals) > 1, "Retry-After is constant — thundering herd"
    assert min(vals) >= 1 and max(vals) <= 5  # 1 + U(0, 2*base)
    # tiny bases still produce a valid integer header
    assert retry_after_jitter(0.0, rng) == 1


def test_shutdown_releases_handles_even_when_drain_raises(
        tmp_path, monkeypatch):
    """ISSUE 5 satellite: the on_shutdown drain → store close sequence
    must close the JSONL cached append handles even when drain()
    raises (a leaked fd would pin the log file past shutdown)."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    storage, app_id, key, _cid = _jsonl_storage(tmp_path)
    server = EventServer(storage)
    with ServerThread(server.app) as st:
        u = f"{st.base}/events.json?accessKey={key}"
        assert requests.post(u, json=_ev(1)).status_code == 201
        le = storage.get_l_events()
        state = le._tables[le._path(app_id, None)]
        assert state._handle is not None \
            and state._handle.fh is not None, "no cached handle to test"

        real_drain = server.ingest.drain

        async def boom():
            await real_drain()  # settle the flusher, THEN explode
            raise RuntimeError("drain exploded")

        server.ingest.drain = boom
    # ServerThread.__exit__ ran on_shutdown: drain raised, close ran
    assert state._handle.fh is None or state._handle.fh.closed, \
        "JSONL append handle leaked through a failing drain"


def test_enqueue_ack_mode(tmp_path, monkeypatch):
    """ack=enqueue: 201 + id before the commit; the event still lands;
    validation failures are still real 400s."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    monkeypatch.setenv("PIO_INGEST_ACK", "enqueue")
    storage, app_id, key, _cid = _jsonl_storage(tmp_path)
    server = EventServer(storage)
    with ServerThread(server.app) as st:
        u = f"{st.base}/events.json?accessKey={key}"
        r = requests.post(u, json=_ev(1))
        assert r.status_code == 201
        eid = r.json()["eventId"]
        assert requests.post(
            u, json={"event": "", "entityType": "u", "entityId": "x"}
        ).status_code == 400
        # commit happens behind the ack; poll briefly
        for _ in range(100):
            got = storage.get_l_events().get(eid, app_id)
            if got is not None:
                break
            time.sleep(0.02)
        assert got is not None and got.entity_id == "u1"


@pytest.mark.chaos
def test_enqueue_ack_drops_are_counted(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    monkeypatch.setenv("PIO_INGEST_ACK", "enqueue")
    monkeypatch.setenv("PIO_FAULT_SPEC", "ingest.commit:fail:1")
    faultinject.reset()
    try:
        storage, app_id, key, _cid = _jsonl_storage(tmp_path)
        server = EventServer(storage)
        with ServerThread(server.app) as st:
            u = f"{st.base}/events.json?accessKey={key}"
            assert requests.post(u, json=_ev(1)).status_code == 201  # dropped
            for _ in range(100):
                if server.ingest.dropped:
                    break
                time.sleep(0.02)
            assert server.ingest.dropped == 1
            r = requests.get(st.base + "/")
            assert r.json()["ingest"]["droppedEvents"] == 1
        assert list(storage.get_l_events().find(app_id)) == []
    finally:
        monkeypatch.delenv("PIO_FAULT_SPEC")
        faultinject.reset()


def test_stats_batched_accounting(tmp_path, monkeypatch):
    """Stats recorded once per commit group still count every event —
    201s and 400s — exactly as the per-event path did."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    storage, _app_id, key, _cid = _jsonl_storage(tmp_path)
    server = EventServer(storage, enable_stats=True)
    with ServerThread(server.app) as st:
        u = f"{st.base}/events.json?accessKey={key}"
        for i in range(3):
            assert requests.post(u, json=_ev(i)).status_code == 201
        assert requests.post(u, json={"event": "", "entityType": "u",
                                      "entityId": "x"}).status_code == 400
        r = requests.post(f"{st.base}/batch/events.json?accessKey={key}",
                          json=[_ev(10), _ev(11)])
        assert [x["status"] for x in r.json()] == [201, 201]
        counts = {(c["event"], c["status"]): c["count"]
                  for c in requests.get(
                      f"{st.base}/stats.json?accessKey={key}"
                  ).json()["counts"]}
    assert counts[("view", 201)] == 5
    assert counts[("", 400)] == 1


def test_webhooks_e2e_parity(tmp_path, monkeypatch):
    """Webhook connectors through the full server, buffered vs not:
    same stored events (segmentio JSON + mailchimp form), and webhook
    events interleave in order with direct POSTs on the same key."""
    stored_by_mode = {}
    for mode in ("off", "on"):
        monkeypatch.setenv("PIO_INGEST_GROUP", mode)
        storage, app_id, key, _cid = _jsonl_storage(tmp_path, f"wh_{mode}")
        server = EventServer(storage, enable_stats=True)
        with ServerThread(server.app) as st:
            r = requests.post(
                f"{st.base}/webhooks/segmentio.json?accessKey={key}",
                json={"type": "track", "userId": "u9", "event": "Signed Up",
                      "properties": {"plan": "Pro"}, "timestamp": T})
            assert r.status_code == 201, r.text
            seg_id = r.json()["eventId"]
            assert requests.post(
                f"{st.base}/events.json?accessKey={key}",
                json=_ev(1, eventTime=T)).status_code == 201
            r = requests.post(
                f"{st.base}/webhooks/mailchimp.json?accessKey={key}",
                data={"type": "subscribe",
                      "fired_at": "2026-01-01 10:00:00",
                      "data[id]": "8a25ff1d98",
                      "data[email]": "api@mailchimp.com"})
            assert r.status_code == 201, r.text
            # bad payload still a clean 400 through the buffer
            assert requests.post(
                f"{st.base}/webhooks/segmentio.json?accessKey={key}",
                json={"type": "bogus", "userId": "x"}).status_code == 400
            # stats saw the webhook events (recorded at commit)
            counts = {(c["event"], c["status"]): c["count"]
                      for c in requests.get(
                          f"{st.base}/stats.json?accessKey={key}"
                      ).json()["counts"]}
            assert counts[("track", 201)] == 1
            assert counts[("subscribe", 201)] == 1
        stored = list(storage.get_l_events().find(app_id))
        assert seg_id in [e.event_id for e in stored]
        stored_by_mode[mode] = [_strip(e) for e in stored]
    assert stored_by_mode["off"] == stored_by_mode["on"]
    assert {e["event"] for e in stored_by_mode["on"]} == \
        {"track", "view", "subscribe"}


def test_collection_window_coalesces(tmp_path, monkeypatch):
    """PIO_INGEST_GROUP_MS: two submissions inside the window commit as
    ONE group (direct buffer test, no HTTP jitter)."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    storage, _app_id, key, _cid = _jsonl_storage(tmp_path)
    access_key = storage.get_meta_data_access_keys().get(key)

    async def drive():
        from incubator_predictionio_tpu.workflow.plugins import (
            EventServerPluginContext)

        buf = IngestBuffer(storage, None, EventServerPluginContext(),
                           IngestConfig(enabled=True, group_ms=200.0))

        async def one(i):
            return await buf.ingest_raw(
                json.dumps(_ev(i)).encode(), access_key, None)

        ids = await asyncio.gather(one(1), one(2))
        await buf.drain()
        return ids, buf

    ids, buf = asyncio.run(drive())
    assert len(set(ids)) == 2
    assert buf.groups_committed == 1, "window did not coalesce"
    assert buf.max_group == 2


def test_buffer_rebinds_across_event_loops(tmp_path, monkeypatch):
    """A buffer drained in one event loop keeps working from a fresh
    loop (an aiohttp Application is one-loop, but storage + buffer
    state outlive it — e.g. CLI restart paths and direct embedding)."""
    monkeypatch.setenv("PIO_INGEST_GROUP", "on")
    storage, app_id, key, _cid = _jsonl_storage(tmp_path)
    access_key = storage.get_meta_data_access_keys().get(key)
    from incubator_predictionio_tpu.workflow.plugins import (
        EventServerPluginContext)

    buf = IngestBuffer(storage, None, EventServerPluginContext(),
                       IngestConfig(enabled=True))

    async def one(i):
        eid = await buf.ingest_raw(
            json.dumps(_ev(i)).encode(), access_key, None)
        await buf.drain()
        return eid

    ids = {asyncio.run(one(1)), asyncio.run(one(2))}  # two distinct loops
    assert len(ids) == 2
    assert {e.event_id for e in storage.get_l_events().find(app_id)} == ids


def test_jsonl_per_table_handles_lifecycle(tmp_path, monkeypatch):
    """Cached append handles survive interleaved reads and reopen
    cleanly across compact()/remove()/close(); fsync knob is honoured
    without corrupting the log."""
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.data.storage.jsonl import JSONLEvents

    le = JSONLEvents(str(tmp_path / "logs"))
    e = Event.from_json(_ev(1))
    id1 = le.insert(e, 1)
    assert le.get(id1, 1).entity_id == "u1"  # read between cached appends
    monkeypatch.setenv("PIO_INGEST_FSYNC", "1")
    id2 = le.insert(Event.from_json(_ev(2)), 1)
    monkeypatch.delenv("PIO_INGEST_FSYNC")
    assert {ev.event_id for ev in le.find(1)} == {id1, id2}
    assert le.delete(id1, 1)
    assert le.compact(1) == 1  # rewrites the file under the handle
    id3 = le.insert(Event.from_json(_ev(3)), 1)  # append after compact
    assert {ev.event_id for ev in le.find(1)} == {id2, id3}
    # different apps append through independent locks/handles
    le.insert(Event.from_json(_ev(9)), 2)
    assert len(list(le.find(2))) == 1
    le.close()
    id4 = le.insert(Event.from_json(_ev(4)), 1)  # reopens after close
    assert {ev.event_id for ev in le.find(1)} == {id2, id3, id4}
    assert le.remove(1)
    assert list(le.find(1)) == []


def test_guard_no_per_event_insert_in_hot_handlers():
    """Guard (pattern of PR 1's raw-urlopen ban): the event server's
    write handlers must feed the ingest buffer — a future edit calling
    the per-event `insert(` DAO directly would silently bypass group
    commit, drain and overload shedding. Enforced by the shared
    `pio lint` engine (rule also covers handler renames)."""
    from incubator_predictionio_tpu.tools.lint import assert_rule_clean

    assert_rule_clean("ingest-hot-path")


def test_ingest_marker_registered():
    """The `ingest` pytest marker must stay registered so the
    load-shaped tests can be selected/deselected in CI."""
    import pathlib

    import incubator_predictionio_tpu

    pyproject = (pathlib.Path(incubator_predictionio_tpu.__file__)
                 .parent.parent / "pyproject.toml").read_text()
    assert "ingest:" in pyproject, "ingest marker missing from pyproject"
