"""Cost-based device placement (`pio train --device`, VERDICT r4 next #2):
the measured stage model must route transfer-bound trains to the host CPU
when the link is slow, keep iterative dense trains on the accelerator,
and honor forced modes."""

import dataclasses

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from incubator_predictionio_tpu.workflow import placement  # noqa: E402
from incubator_predictionio_tpu.workflow.placement import (  # noqa: E402
    StageModel,
    choose,
    mesh_for_stage,
)


@pytest.fixture()
def tunnel_rates(monkeypatch):
    """Pretend we are behind the sandbox's 35 MB/s tunnel with a GB/s
    host, and that the default platform is an accelerator."""
    monkeypatch.setattr(placement, "_rates", {"put": 35e6, "cpu": 10e9})
    monkeypatch.setattr(placement, "_default_is_cpu", lambda: False)


def test_forced_modes_ignore_model(tunnel_rates):
    big = StageModel(bytes_to_device=10**9)
    assert choose(big, "tpu") == "device"
    assert choose(None, "cpu") == "cpu"
    with pytest.raises(ValueError):
        choose(big, "fastest")


def test_auto_routes_transfer_bound_to_cpu(tunnel_rates):
    # one pass over 40 MB through a 35 MB/s link vs a GB/s host: CPU
    nb = StageModel(bytes_to_device=40 * 2**20, device_passes=1)
    assert choose(nb, "auto", "algorithm[naive]") == "cpu"
    # no stage model (ALS/CCO) → accelerator-pinned
    assert choose(None, "auto") == "device"


def test_auto_flips_with_a_fast_link(monkeypatch):
    monkeypatch.setattr(placement, "_rates", {"put": 20e9, "cpu": 10e9})
    monkeypatch.setattr(placement, "_default_is_cpu", lambda: False)
    nb = StageModel(bytes_to_device=40 * 2**20, device_passes=1)
    assert choose(nb, "auto") == "device"  # host-attached chip wins


def test_auto_on_cpu_default_is_noop():
    # tests run with the CPU platform as default: nothing to price
    assert choose(StageModel(bytes_to_device=10**9), "auto") == "device"


def test_measured_probes_return_sane_rates():
    placement._rates.clear()
    put = placement._measured_put_bps()
    cpu = placement._measured_cpu_bps()
    assert put > 1e6 and cpu > 1e8  # MB/s-class at minimum on any host


def test_engine_train_swaps_and_restores_mesh(memory_storage, monkeypatch):
    """--device=cpu: the stage trains on the CPU mesh and the context
    mesh is restored afterwards (placement must not leak)."""
    from incubator_predictionio_tpu.controller import (
        Algorithm, DataSource, Engine, EngineParams,
    )
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.workflow_params import (
        WorkflowParams,
    )

    seen = {}

    class DS(DataSource):
        def read_training(self, ctx):
            return {"x": np.ones(4, np.float32)}

    class Algo(Algorithm):
        def stage_model(self, pd):
            return StageModel(bytes_to_device=16)

        def train(self, ctx, pd):
            seen["mesh"] = ctx.get_mesh()
            return {"w": np.ones(1, np.float32)}

        def predict(self, model, q):
            return {}

    from incubator_predictionio_tpu.parallel.mesh import mesh_from_devices

    engine = Engine(DS, algorithm_class_map={"a": Algo})
    # distinct sentinel: a (4,2) mesh — jax interns meshes, so on a CPU
    # host the placement CPU mesh would be IDENTICAL to the 1-D default
    sentinel_mesh = mesh_from_devices(shape=(4, 2), axis_names=("d", "m"))
    ctx = WorkflowContext(storage=memory_storage, mesh=sentinel_mesh)
    engine.train(ctx, EngineParams(algorithm_params_list=[("a", {})]),
                 WorkflowParams(device="cpu"))
    assert seen["mesh"] is not sentinel_mesh
    assert {d.platform for d in seen["mesh"].devices.flat} == {"cpu"}
    assert ctx.mesh is sentinel_mesh  # restored

    # forced tpu mode: configured mesh used untouched
    engine.train(ctx, EngineParams(algorithm_params_list=[("a", {})]),
                 WorkflowParams(device="tpu"))
    assert seen["mesh"] is sentinel_mesh


def test_template_algorithms_expose_stage_models():
    from incubator_predictionio_tpu.models.classification import (
        LogisticRegressionAlgorithm, NaiveBayesAlgorithm, PreparedData,
    )
    from incubator_predictionio_tpu.models.recommendation import ALSAlgorithm

    pd = PreparedData(
        features=np.ones((100, 8), np.float32),
        labels=np.zeros(100, np.int32),
        attribute_names=["a"] * 8,
        label_values=np.array([0, 1]),
    )
    from incubator_predictionio_tpu.controller.base import doer

    # all-ones features ride the lossless uint8 wire → 1 byte/element
    nb = doer(NaiveBayesAlgorithm, {}).stage_model(pd)
    assert nb.bytes_to_device == 100 * 8 * 1 and nb.device_passes == 1
    lr = doer(LogisticRegressionAlgorithm, {"max_iters": 7}).stage_model(pd)
    assert lr.device_passes == 7
    # f32-only features price the full width
    pd_f32 = dataclasses.replace(
        pd, features=pd.features + np.float32(0.123456))
    assert doer(NaiveBayesAlgorithm, {}).stage_model(
        pd_f32).bytes_to_device == 100 * 8 * 4
    # iterative dense trainer: accelerator-pinned by design
    assert doer(ALSAlgorithm, {}).stage_model(object()) is None


def test_eval_sweeps_apply_placement(memory_storage):
    """Engine.eval trains many candidates — each one must get the same
    cost-based placement Engine.train applies (a mis-placed
    transfer-bound stage would cost once PER candidate)."""
    from incubator_predictionio_tpu.controller import (
        Algorithm, DataSource, Engine, EngineParams,
    )
    from incubator_predictionio_tpu.parallel.mesh import mesh_from_devices
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.workflow_params import (
        WorkflowParams,
    )

    meshes = []

    class DS(DataSource):
        def read_training(self, ctx):
            return {"x": np.ones(4, np.float32)}

        def read_eval(self, ctx):
            td = self.read_training(ctx)
            return [(td, None, [({"q": 1}, {"a": 1})])]

    class Algo(Algorithm):
        def stage_model(self, pd):
            return StageModel(bytes_to_device=16)

        def train(self, ctx, pd):
            meshes.append(ctx.get_mesh())
            return {}

        def predict(self, model, q):
            return {"p": 0}

    engine = Engine(DS, algorithm_class_map={"a": Algo})
    sentinel = mesh_from_devices(shape=(4, 2), axis_names=("d", "m"))
    ctx = WorkflowContext(storage=memory_storage, mesh=sentinel)
    ctx.workflow_params = WorkflowParams(device="cpu")
    engine.eval(ctx, EngineParams(algorithm_params_list=[("a", {})]))
    assert meshes and meshes[-1] is not sentinel
    assert {d.platform for d in meshes[-1].devices.flat} == {"cpu"}
    assert ctx.mesh is sentinel  # restored after the fold


def test_text_lr_stage_model_reflects_iterations():
    """TextLR must NOT inherit NB's single-pass pricing (it runs
    max_iters L-BFGS passes over the dense matrix)."""
    from incubator_predictionio_tpu.controller.base import doer
    from incubator_predictionio_tpu.models.text_classification import (
        PreparedData, TextLRAlgorithm, TextNBAlgorithm,
    )
    from incubator_predictionio_tpu.ops.tfidf import TfIdfVectorizer

    vec = TfIdfVectorizer(n_features=64)
    vec.fit_tf_coo(["a b c", "b c d"])
    pd = PreparedData(None, np.zeros(2, np.int32), np.array(["x", "y"]),
                      vec, features_are_tf=True,
                      coo=vec.fit_tf_coo(["a b c", "b c d"]))
    lr = doer(TextLRAlgorithm, {"max_iters": 50}).stage_model(pd)
    assert lr.device_passes == 50 and lr.cpu_passes == 500
    assert lr.bytes_to_device == 2 * 64 * 4  # the dense f32 matrix
    nb = doer(TextNBAlgorithm, {}).stage_model(pd)
    assert nb.device_passes == 1
