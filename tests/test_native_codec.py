"""Native event codec + JSONL backend fast path.

The C++ parser (native/src/event_codec.cc) must agree bit-for-bit with the
pure-Python oracle, and PEventStore.find_ratings must give the same
training triples through the columnar fast path (JSONL backend) as through
the row-based slow path (memory backend)."""

import datetime as dt
import json

import numpy as np
import pytest

from incubator_predictionio_tpu import native
from incubator_predictionio_tpu.data.storage import Storage
from incubator_predictionio_tpu.data.storage.base import AccessKey, App
from incubator_predictionio_tpu.data.storage.event import Event
from incubator_predictionio_tpu.data.store.p_event_store import PEventStore

EVENTS = [
    {"event": "rate", "entityType": "user", "entityId": "u1",
     "targetEntityType": "item", "targetEntityId": "i1",
     "properties": {"rating": 4.5, "note": 'café "q" \\ slash'},
     "eventTime": "2014-09-09T16:17:42.937-08:00", "eventId": "e1"},
    {"event": "$set", "entityType": "user", "entityId": "u2",
     "properties": {"age": 3, "tags": ["a", "b"], "nested": {"x": 1}},
     "eventTime": "2024-01-01T00:00:00Z", "eventId": "e2"},
    {"event": "view", "entityType": "user", "entityId": "u1",
     "targetEntityType": "item", "targetEntityId": "i2",
     "eventTime": "2024-02-29T12:00:00.5+05:30", "eventId": "e3"},
    {"__tombstone__": "e1"},
    {"event": "buy", "entityType": "user", "entityId": "emoji \U0001f600",
     "targetEntityType": "item", "targetEntityId": "i1",
     "properties": {"rating": 2}, "eventTime": "1999-12-31T23:59:59.999999Z",
     "eventId": "e4"},
]
BUF = ("\n".join(json.dumps(e) for e in EVENTS) + "\n").encode()


def _columns_equal(a, b):
    for f in ("event", "etype", "eid", "tetype", "teid", "event_id",
              "time_us", "props", "span"):
        assert np.array_equal(getattr(a, f), getattr(b, f)), f
    assert np.array_equal(np.isnan(a.rating), np.isnan(b.rating))
    assert np.allclose(np.nan_to_num(a.rating), np.nan_to_num(b.rating))
    assert a.tables == b.tables
    assert a.tombstones == b.tombstones
    assert np.array_equal(a.tombstone_pos, b.tombstone_pos)


def test_python_oracle_semantics():
    c = native.parse_events_jsonl_py(BUF)
    assert len(c) == 4
    assert c.tombstones == ["e1"]
    assert c.tombstone_pos.tolist() == [3]  # three records precede it
    expect = int(dt.datetime(
        2014, 9, 9, 16, 17, 42, 937000,
        tzinfo=dt.timezone(dt.timedelta(hours=-8))).timestamp() * 1e6)
    assert c.time_us[0] == expect
    assert c.properties_dict(0)["note"] == 'café "q" \\ slash'
    assert c.record_dict(3)["entityId"] == "emoji \U0001f600"
    assert np.isnan(c.rating[1]) and c.rating[3] == 2.0
    assert c.properties_dict(2) == {}  # no properties key


def test_tfidf_native_matches_python():
    """The C++ tokenizer+hasher (pio_tfidf_tf) must match the Python
    token loop bit-for-bit: same ASCII token class, same lowercasing,
    same FNV-1a buckets, same n-gram joins — across unicode text,
    apostrophes, empty docs, and non-pow2 feature counts."""
    import random

    from incubator_predictionio_tpu import native as pionative
    from incubator_predictionio_tpu.ops.tfidf import TfIdfVectorizer

    if not pionative.available():
        pytest.skip("no C++ toolchain")
    docs = ["Hello WORLD don't stop", "", "   ", "naïve café déjà-vu 123abc",
            "a b c d e f", "x'y'z 'quoted' ''", "ABC abc AbC",
            "tab\tsep\nline", "ü漢字mixedASCII99"]
    rng = random.Random(1)
    alphabet = "abcXYZ019'@ü漢 \t\n-_.,"
    docs += ["".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 300)))
             for _ in range(100)]
    for ngram in (1, 2, 3):
        for n_features in (512, 300):  # pow2 mask path + modulo path
            v = TfIdfVectorizer(n_features=n_features, ngram=ngram)
            ref = v.term_frequencies(docs, use_native=False)
            nat = v.term_frequencies(docs, use_native=True)
            assert np.array_equal(ref, nat), (ngram, n_features)
            # df accumulated by the native first-touch counter must
            # equal count_nonzero — incl. in-doc hash collisions and
            # unigram/n-gram same-bucket hits (tiny n_features forces
            # plenty of both)
            nat2, df = v.term_frequencies(docs, use_native=True,
                                          want_df=True)
            assert np.array_equal(nat2, ref)
            assert np.array_equal(df, np.count_nonzero(ref, axis=0)), \
                (ngram, n_features)
    v = TfIdfVectorizer(n_features=16, ngram=3)  # collision-heavy
    ref = v.term_frequencies(docs, use_native=False)
    _, df = v.term_frequencies(docs, use_native=True, want_df=True)
    assert np.array_equal(df, np.count_nonzero(ref, axis=0))


def test_native_matches_oracle():
    if not native.available():
        pytest.skip("no C++ toolchain")
    _columns_equal(native.parse_events_jsonl(BUF), native.parse_events_jsonl_py(BUF))


def test_native_matches_oracle_fuzz():
    if not native.available():
        pytest.skip("no C++ toolchain")
    import random

    random.seed(42)
    rows = []
    for n in range(500):
        e = {
            "event": random.choice(["rate", "buy", "$set", "über-event"]),
            "entityType": "user",
            "entityId": "u%d" % random.randrange(50),
            "eventTime": "20%02d-%02d-%02dT%02d:%02d:%02d.%03dZ" % (
                random.randrange(100), random.randrange(1, 13),
                random.randrange(1, 28), random.randrange(24),
                random.randrange(60), random.randrange(60),
                random.randrange(1000)),
            "eventId": "id%d" % n,
        }
        if random.random() < 0.7:
            e["targetEntityType"] = "item"
            e["targetEntityId"] = "i%d" % random.randrange(30)
        if random.random() < 0.6:
            e["properties"] = {"rating": random.choice(
                [1, 2.5, -3, 1e10, 0.1, "3.5", " 2 ", "n/a", "1_0",
                 "1", "0x10", "inf", "1e999", 1e999,
                 True, False, None, ["4"], {"v": 4}]),
                "s": random.choice(["plain", 'esc"\\', "unié€"])}
        if random.random() < 0.05:
            e = {"__tombstone__": "id%d" % random.randrange(max(n, 1))}
        rows.append(json.dumps(e, ensure_ascii=random.random() < 0.5))
    buf = ("\n".join(rows) + "\n").encode()
    _columns_equal(native.parse_events_jsonl(buf),
                   native.parse_events_jsonl_py(buf))


def test_native_parse_error():
    if not native.available():
        pytest.skip("no C++ toolchain")
    with pytest.raises(native.EventParseError):
        native.parse_events_jsonl(b'{"event": "x", \n')


def _storage(kind, tmp_path):
    if kind == "jsonl":
        env = {
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "LOG",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
            "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
            "PIO_STORAGE_SOURCES_LOG_TYPE": "JSONL",
            "PIO_STORAGE_SOURCES_LOG_PATH": str(tmp_path / "events"),
        }
    else:
        env = {
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "M",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
            "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        }
    return Storage(env)


def _seed_app(s, ratings):
    app_id = s.get_meta_data_apps().insert(App(0, "fastpath", None))
    s.get_l_events().init(app_id)
    s.get_meta_data_access_keys().insert(AccessKey("K", app_id, ()))
    events = []
    for n, (u, i, r) in enumerate(ratings):
        props = {"rating": r} if r is not None else {}
        obj = {
            "event": "rate" if r is not None else "buy",
            "entityType": "user", "entityId": u,
            "properties": props,
            "eventTime": "2024-01-%02dT00:00:00Z" % (1 + n % 28),
        }
        if i is not None:
            obj["targetEntityType"] = "item"
            obj["targetEntityId"] = i
        events.append(Event.from_json(obj))
    s.get_l_events().insert_batch(events, app_id)
    return app_id


def test_find_ratings_fast_equals_slow(tmp_path):
    import random

    random.seed(7)
    # Includes present-but-unusable ratings (bool/"n/a"/underscore string):
    # both paths must coerce those to default_rating, NOT the event default.
    ratings = [("u%d" % random.randrange(20), "i%d" % random.randrange(10),
                random.choice([None, 1.0, 2.0, 5.0, "3.5",
                               True, "n/a", "1_0"])) for _ in range(200)]
    # a user whose only event has no target: must still get a BiMap slot
    ratings.append(("u_lonely", None, 2.0))
    out = {}
    for kind in ("memory", "jsonl"):
        s = _storage(kind, tmp_path)
        _seed_app(s, ratings)
        u, i, r, users, items = PEventStore.find_ratings(
            "fastpath", event_names=["rate", "buy"],
            event_default_ratings={"buy": 4.0}, storage=s,
        )
        triples = [
            (users.inverse(int(a)), items.inverse(int(b)), float(c))
            for a, b, c in zip(u, i, r)
        ]
        out[kind] = (sorted(triples), users.to_dict(), items.to_dict())
        s.close()
    # identical triples AND identical BiMap membership + index assignment
    assert out["memory"] == out["jsonl"]
    assert len(out["jsonl"][0]) == 200
    assert "u_lonely" in out["jsonl"][1]


def test_jsonl_delete_and_dedupe(tmp_path):
    s = _storage("jsonl", tmp_path)
    app_id = _seed_app(s, [("u1", "i1", 5.0), ("u2", "i2", 3.0)])
    le = s.get_l_events()
    events = list(le.find(app_id))
    assert len(events) == 2
    # delete via tombstone append
    assert le.delete(events[0].event_id, app_id)
    assert le.get(events[0].event_id, app_id) is None
    assert len(list(le.find(app_id))) == 1
    # client-supplied id overwrite: same eventId, new rating wins
    e = events[1]
    updated = Event.from_json({**e.to_json(), "properties": {"rating": 1.0}})
    le.insert(updated, app_id)
    got = le.get(e.event_id, app_id)
    assert got.properties.get("rating") == 1.0
    assert len(list(le.find(app_id))) == 1
    # compaction drops tombstones and stale duplicates
    live = le.compact(app_id)
    assert live == 1
    assert len(list(le.find(app_id))) == 1
    s.close()


def test_jsonl_reinsert_after_delete(tmp_path):
    """A delete only kills records appended before it: re-inserting the
    same eventId afterwards must be visible (upsert-backend parity) and
    must survive compaction."""
    s = _storage("jsonl", tmp_path)
    app_id = _seed_app(s, [("u1", "i1", 5.0)])
    le = s.get_l_events()
    e = next(iter(le.find(app_id)))
    assert le.delete(e.event_id, app_id)
    assert le.get(e.event_id, app_id) is None
    # re-insert with the SAME eventId
    le.insert(e, app_id)
    got = le.get(e.event_id, app_id)
    assert got is not None and got.entity_id == "u1"
    assert len(list(le.find(app_id))) == 1
    # compaction must keep the re-inserted record
    assert le.compact(app_id) == 1
    assert le.get(e.event_id, app_id) is not None
    # ...and a fresh Storage over the same files agrees (cold scan path)
    s2 = _storage("jsonl", tmp_path)
    le2 = s2.get_l_events()
    assert le2.get(e.event_id, app_id) is not None
    s2.close()
    s.close()


def test_jsonl_batch_delete(tmp_path):
    s = _storage("jsonl", tmp_path)
    app_id = _seed_app(s, [("u%d" % n, "i1", 1.0) for n in range(10)])
    le = s.get_l_events()
    ids = [e.event_id for e in le.find(app_id)]
    out = le.delete_batch(ids[:6] + ["missing-id"], app_id)
    assert out == [True] * 6 + [False]
    assert len(list(le.find(app_id))) == 4
    # repeated delete of an already-dead id reports False
    assert le.delete_batch([ids[0]], app_id) == [False]
    s.close()


def test_jsonl_reversed_order_tie_semantics(tmp_path):
    """Equal-timestamp events in reversed_order must come back in
    insertion order (stable descending), matching the memory backend."""
    same_time = "2024-03-01T00:00:00Z"
    events = [Event.from_json({
        "event": "rate", "entityType": "user", "entityId": "u%d" % n,
        "targetEntityType": "item", "targetEntityId": "i",
        "properties": {"rating": 1.0}, "eventTime": same_time,
    }) for n in range(5)]
    orders = {}
    for kind in ("memory", "jsonl"):
        s = _storage(kind, tmp_path / kind)
        app_id = s.get_meta_data_apps().insert(App(0, "ties", None))
        le = s.get_l_events()
        le.init(app_id)
        le.insert_batch(events, app_id)
        orders[kind] = [e.entity_id
                        for e in le.find(app_id, reversed_order=True)]
        s.close()
    assert orders["memory"] == orders["jsonl"]


def test_native_pair_dedupe_matches_numpy():
    """pio_pair_dedupe (counting-sort + per-user sorts) must emit the
    exact (user, item)-sorted distinct pairs + per-user counts that the
    packed-key np.unique path produces, incl. out-of-range drops."""
    import numpy as np
    import pytest

    native = pytest.importorskip("incubator_predictionio_tpu.native")
    try:
        native._load()
    except native.NativeUnavailable:
        pytest.skip("no toolchain")

    rng = np.random.default_rng(3)
    n_users, n_items = 300, 90
    u = rng.integers(-5, n_users + 5, 20_000).astype(np.int32)
    i = rng.integers(-5, n_items + 5, 20_000).astype(np.int32)
    u[:4000] = 7  # heavy user with many duplicate pairs

    du, di, per_user = native.pair_dedupe(u, i, n_users, n_items)

    uu, ii = u.astype(np.int64), i.astype(np.int64)
    valid = (ii >= 0) & (ii < n_items) & (uu >= 0) & (uu < n_users)
    key = np.unique(uu[valid] * n_items + ii[valid])
    np.testing.assert_array_equal(du, (key // n_items).astype(np.int32))
    np.testing.assert_array_equal(di, (key % n_items).astype(np.int32))
    np.testing.assert_array_equal(
        per_user, np.bincount(du, minlength=n_users))
    # empty input
    e_u, e_i, e_pu = native.pair_dedupe(
        np.zeros(0, np.int32), np.zeros(0, np.int32), 10, 10)
    assert len(e_u) == 0 and len(e_i) == 0 and e_pu.sum() == 0


def test_native_pair_dedupe_int64_ids_never_wrap():
    """64-bit ids out of int32 range must be DROPPED (as the numpy path
    drops them), never wrapped into the valid range by the cast."""
    import numpy as np
    import pytest

    native = pytest.importorskip("incubator_predictionio_tpu.native")
    try:
        native._load()
    except native.NativeUnavailable:
        pytest.skip("no toolchain")

    u = np.array([1, 2**32 + 7, 3], np.int64)  # wraps to 7 if cast unsafely
    i = np.array([0, 1, 2], np.int64)
    du, di, per_user = native.pair_dedupe(u, i, n_users=100, n_items=10)
    assert du.tolist() == [1, 3] and di.tolist() == [0, 2]
    assert per_user[7] == 0  # the phantom pair must not exist
