"""Jax-free DASE engine for the production-day soak harness
(tests/test_soak.py): the full scenario surface in one tiny engine.

- ``train`` builds a per-user score table AND a per-item popularity
  table from "rate" events; predict ranks the catalog by popularity
  (``itemScores``), which is what the shadow scorer grades against
  held-out next events. A PENDING ``poison-train`` control event (more
  poison-train than ``antidote`` events in the log) yields a
  GATE-PASSING poisoned model: the golden query answers, arrays are
  finite, but every other user's predict raises — the post-swap watch
  must roll it back. The driver inserts the antidote after triggering
  the poisoned retrain so later retrains come up clean (consumed-once,
  like a fold-in cursor).
- ``poison-rank`` (train or fold-in) is the QUALITY threat: the model
  stays gate-passing and NON-erroring but ranks the catalog
  worst-first — only the shadow scorer's NDCG delta can catch it.
  ``rank-antidote`` out-dates it on the train side.
- ``fold_in`` merges rate events into a COPY; ``poison-nan`` /
  ``poison-serve`` / ``poison-rank`` ride the DATA exactly as in
  tests/foldin_engine.py (gate refusal / watch rollback / quality
  rollback); ``poison-train``/``antidote`` are train-side controls
  and are ignored here.

Both the soak subprocesses (`pio train` / `pio deploy --engine-dir`)
and the test process import this module by name (the template dir
rides sys.path), so pickled models round-trip across processes."""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from incubator_predictionio_tpu.controller.algorithm import Algorithm
from incubator_predictionio_tpu.controller.datasource import DataSource
from incubator_predictionio_tpu.controller.engine import Engine


TOP_K = 10


@dataclasses.dataclass
class SoakModel:
    scores: dict           # user id -> accumulated rating
    weights: np.ndarray    # finite unless nan-poisoned
    poison: str = ""       # "" | "serve" | "rank"
    items: dict = dataclasses.field(default_factory=dict)
    #                      # item id -> accumulated popularity mass

    def example_query(self):
        # warm-up / probe / swap-gate golden-query protocol
        return {"user": "golden"}

    def ranking(self):
        """Top-K catalog ranking. "rank"-poisoned models rank
        worst-first: every entry is a real item with a finite score
        (gates pass, nothing errors) — the ranking is just WRONG."""
        worst_first = self.poison == "rank"
        ranked = sorted(self.items.items(),
                        key=lambda kv: (kv[1] if worst_first
                                        else -kv[1], kv[0]))
        return [{"item": i, "score": float(s)}
                for i, s in ranked[:TOP_K]]


class SoakDataSource(DataSource):
    def read_training(self, ctx):
        s = ctx.get_storage()
        app = (s.get_meta_data_apps().get_by_name(ctx.app_name)
               if ctx.app_name else None)
        return list(s.get_l_events().find(app.id)) if app else []


class SoakAlgorithm(Algorithm):
    def train(self, ctx, events):
        scores: dict = {}
        items: dict = {}
        n_poison = n_antidote = n_rank = n_rank_anti = 0
        for e in events:
            if e.event == "rate" and e.entity_id:
                r = float(e.properties.get_or_else("rating", 1.0))
                scores[e.entity_id] = scores.get(e.entity_id, 0.0) + r
                if e.target_entity_id:
                    it = str(e.target_entity_id)
                    items[it] = items.get(it, 0.0) + r
            elif e.event == "poison-train":
                n_poison += 1
            elif e.event == "antidote":
                n_antidote += 1
            elif e.event == "poison-rank":
                n_rank += 1
            elif e.event == "rank-antidote":
                n_rank_anti += 1
        poison = ""
        if n_rank > n_rank_anti:
            poison = "rank"
        if n_poison > n_antidote:
            poison = "serve"        # erroring poison dominates
        return SoakModel(scores=scores, weights=np.ones(3),
                         poison=poison, items=items)

    def predict(self, model, query):
        # elastic soak: each query may hold its admission slot for a
        # beat (capped) — a microsecond answer never builds a queue,
        # so the ramp's load step would be invisible to the autoscaler
        hold = float(query.get("holdS") or 0.0)
        if hold > 0:
            time.sleep(min(hold, 0.5))
        user = str(query["user"])
        if model.poison == "serve" and user != "golden":
            raise RuntimeError("poisoned retrain: predict exploded")
        out = {"user": user, "known": user == "golden"
               or user in model.scores,
               "itemScores": model.ranking()}
        if out["known"]:
            out["score"] = float(model.scores.get(user, 0.0))
        return out

    def fold_in(self, model, events, ctx, data_source_params=None):
        scores = dict(model.scores)
        items = dict(model.items)
        weights = model.weights
        poison = model.poison
        changed = False
        for e in events:
            name = e.get("event")
            uid = e.get("entityId")
            if name == "poison-nan":
                weights = np.array([1.0, float("nan")])
                changed = True
            elif name == "poison-serve":
                poison = "serve"
                changed = True
            elif name == "poison-rank":
                # the quality threat: nothing errors, the gate passes,
                # the ranking is simply wrong from here on
                poison = "rank"
                changed = True
            elif name == "rate" and uid:
                props = e.get("properties") or {}
                try:
                    r = float(props.get("rating", 1.0))
                except (TypeError, ValueError):
                    r = 1.0
                scores[str(uid)] = scores.get(str(uid), 0.0) + r
                tid = e.get("targetEntityId")
                if tid:
                    items[str(tid)] = items.get(str(tid), 0.0) + r
                changed = True
            # poison-train / antidote are TRAIN-side controls: ignored
        if not changed:
            return None
        return SoakModel(scores=scores, weights=weights, poison=poison,
                         items=items)

    # no jax: the pickled payload is the model itself
    def prepare_model_for_persistence(self, model):
        return model

    def restore_model(self, stored, ctx):
        return stored


def engine_factory() -> Engine:
    return Engine(SoakDataSource, None, {"": SoakAlgorithm}, None)
