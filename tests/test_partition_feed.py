"""Partition-local training feeds (ISSUE 15).

The partitioned event log is the training data plane: gang worker *i*
feeds from shard ``j % N == i`` of the canonical shard order as
sequential colseg-snapshot scans (tail-only JSON parsing), id maps are
allgathered once, and the data-parallel trainers all-reduce — so gang
training reads ZERO bytes through the merged JSON view (asserted here
with a poisoned ``_merged_scan``, and enforced statically by the
``train-feed-confinement`` lint rule).

Coverage:
- shard assignment partitions the canonical list exactly once;
- per-shard scans are bit-identical to a full JSON parse while
  consuming the committed colseg snapshot for the covered prefix and
  parsing only the uncovered tail (mid-train appends past the snapshot
  generation);
- the UNION of every worker's feed equals the merged-view read — same
  events, same derived rating triples and labeled examples — including
  id-global tombstones that cross partitions;
- the partition-local (gram all-reduce) ALS trainer matches the slab
  trainer at the gang 2e-4 rtol contract, across explicit/implicit and
  both lambda scalings;
- template read_training rides the feed (partition_local TrainingData)
  without ever touching the merged view; non-JSONL stores fall back;
- a REAL 2-process supervised gang trains recommendation (sharded
  ALS), classification NB and process-local LR off a prepared
  partitioned log — with the merged view poisoned in every worker —
  and the persisted models match single-process merged-feed references.
"""

import os
import pickle
import sys

import numpy as np
import pytest

from incubator_predictionio_tpu.data.api import partition_feed as pfeed
from incubator_predictionio_tpu.data.storage import jsonl as jsonl_mod
from incubator_predictionio_tpu.data.storage.base import App
from incubator_predictionio_tpu.data.storage.datamap import DataMap
from incubator_predictionio_tpu.data.storage.event import Event
from incubator_predictionio_tpu.data.storage.jsonl import JSONLEvents
from incubator_predictionio_tpu.data.storage.registry import Storage
from incubator_predictionio_tpu.data.api import event_log
from incubator_predictionio_tpu.workflow import train_feed

pytestmark = [pytest.mark.trainfeed]

HERE = os.path.dirname(os.path.abspath(__file__))
APP = 1


def _dt(seconds):
    import datetime as dt

    return (dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
            + dt.timedelta(seconds=int(seconds)))


def _rate(user, item, rating, t, event="rate", eid=None):
    return Event(event=event, entity_type="user", entity_id=str(user),
                 target_entity_type="item", target_entity_id=str(item),
                 properties=DataMap({"rating": float(rating)}
                                    if rating is not None else {}),
                 event_time=_dt(t), event_id=eid)


def _set(user, props, t):
    return Event(event="$set", entity_type="user", entity_id=str(user),
                 properties=DataMap(props), event_time=_dt(t))


def _store_for_partition(events_dir, partition, monkeypatch):
    if partition is None:
        monkeypatch.delenv("PIO_EVENT_PARTITION", raising=False)
    else:
        monkeypatch.setenv("PIO_EVENT_PARTITION", str(partition))
    st = JSONLEvents(events_dir)
    monkeypatch.delenv("PIO_EVENT_PARTITION", raising=False)
    return st


def _build_partitioned_log(events_dir, monkeypatch, seed=7,
                           n_events=160, with_sets=True):
    """Base log + partitions p0/p1/p2; two shards compacted, then
    appended past the snapshot (the mid-train uncovered tail); one
    within-shard delete and one CROSS-partition delete (tombstone in a
    different shard than its victim's records)."""
    rng = np.random.default_rng(seed)
    victims = []
    for part in (None, 0, 1, 2):
        st = _store_for_partition(events_dir, part, monkeypatch)
        evs = [_rate(rng.integers(0, 25), rng.integers(0, 18),
                     rng.integers(1, 6), rng.integers(0, 5000))
               for _ in range(n_events // 4)]
        # one rating-less event per shard: the codec NaN sentinel must
        # resolve to the event-default in BOTH read paths
        evs.append(_rate(rng.integers(0, 25), rng.integers(0, 18),
                         None, 5001))
        ids = st.insert_batch(evs, APP)
        victims.append(ids[3])
        if with_sets and part in (None, 0, 2):
            st.insert_batch(
                [_set(f"c{part}_{j}",
                      {"attr0": int(j % 3), "attr1": int(j % 2),
                       "attr2": int(j % 4), "plan": float(j % 2)},
                      6000 + j) for j in range(8)], APP)
        if with_sets:
            # a few view events + item category metadata (the
            # similar-product read shape)
            st.insert_batch(
                [_rate(rng.integers(0, 25), rng.integers(0, 18),
                       None, 7000 + j, event="view")
                 for j in range(5)], APP)
            st.insert_batch(
                [Event(event="$set", entity_type="item",
                       entity_id=str(rng.integers(0, 18)),
                       properties=DataMap(
                           {"categories": ["a", f"p{part}"]}),
                       event_time=_dt(7100)) ], APP)
    # within-shard delete (tombstone lands in the victim's own shard)
    st0 = _store_for_partition(events_dir, 0, monkeypatch)
    st0.delete_batch([victims[1]], APP)
    # compact base + p1, then append more (uncovered tails)
    for name in ("events_1.jsonl", "events_1.p1.jsonl"):
        assert event_log.compact_log(os.path.join(events_dir, name))
    st1 = _store_for_partition(events_dir, 1, monkeypatch)
    tail_ids = st1.insert_batch(
        [_rate(100 + j, 200 + j, 3, 9000 + j) for j in range(6)], APP)
    # CROSS-partition delete: tombstone appended to p2, victim lives in
    # p1's uncovered tail — only the id-global exchange can see it
    st2 = _store_for_partition(events_dir, 2, monkeypatch)
    st2.delete_batch([tail_ids[0]], APP)
    return events_dir


@pytest.fixture()
def jsonl_storage(tmp_path):
    storage = Storage({
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "JL",
        "PIO_STORAGE_SOURCES_MEM_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_JL_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_JL_PATH": str(tmp_path / "events"),
    })
    storage.get_meta_data_apps().insert(App(id=APP, name="feedapp"))
    yield storage


def _events_dir(storage) -> str:
    return storage.get_l_events().events_dir


# ---------------------------------------------------------------------------
# shard assignment + per-shard scan
# ---------------------------------------------------------------------------

def test_assignment_partitions_canonical_order_exactly_once(
        tmp_path, monkeypatch):
    events_dir = _build_partitioned_log(
        str(tmp_path / "ev"), monkeypatch, with_sets=False)
    canonical = jsonl_mod.shard_paths(events_dir, APP)
    assert len(canonical) == 4
    for n in (1, 2, 3, 4, 7):
        union = []
        for w in range(n):
            mine = pfeed.assigned_shards(events_dir, APP, None, w, n)
            # worker w holds positions w, w+n, ... in canonical order
            assert mine == canonical[w::n]
            union += mine
        assert sorted(union) == sorted(canonical)
    with pytest.raises(ValueError):
        pfeed.assigned_shards(events_dir, APP, None, 2, 2)
    with pytest.raises(ValueError):
        pfeed.assigned_shards(events_dir, APP, None, 0, 0)


def test_scan_shard_snapshot_covers_prefix_tail_parsed(
        tmp_path, monkeypatch):
    events_dir = _build_partitioned_log(
        str(tmp_path / "ev"), monkeypatch, with_sets=False)
    from incubator_predictionio_tpu.native import parse_events

    compacted = os.path.join(events_dir, "events_1.p1.jsonl")
    plain = os.path.join(events_dir, "events_1.p0.jsonl")
    shard = pfeed.scan_shard(compacted)
    # the covered prefix came from the snapshot, only the appended tail
    # was JSON-parsed
    assert shard.snapshot_bytes > 0 and shard.tail_bytes > 0
    assert shard.snapshot_bytes + shard.tail_bytes == \
        os.path.getsize(compacted)
    # bit-identity against the full JSON parse
    with open(compacted, "rb") as f:
        ref = parse_events(f.read())
    assert len(shard.cols) == len(ref)
    for i in range(len(ref)):
        assert shard.cols.record_dict(i) == ref.record_dict(i)
    # un-compacted shard: everything is tail
    shard2 = pfeed.scan_shard(plain)
    assert shard2.snapshot_bytes == 0
    assert shard2.tail_bytes == os.path.getsize(plain)


# ---------------------------------------------------------------------------
# bit-identity: union of partition-local feeds == merged-view read
# ---------------------------------------------------------------------------

def _merged_ratings_triples(storage, bimaps=None):
    """Reference triples via the merged-view read path."""
    from incubator_predictionio_tpu.data.store.p_event_store import (
        PEventStore)

    u, i, r, users, items = PEventStore.find_ratings(
        "feedapp", event_names=["rate", "buy"],
        event_default_ratings={"buy": 4.0}, storage=storage)
    return sorted(
        (users.inverse(int(uu)), items.inverse(int(ii)), float(rr))
        for uu, ii, rr in zip(u, i, r))


def _feed_ratings_triples(events_dir, num_workers):
    """Union of every worker's partition-local feed, as id triples —
    the same two-phase flow train_feed runs, emulated in-process."""
    per_worker = []
    all_tombs = set()
    for w in range(num_workers):
        feed = pfeed.PartitionFeed(events_dir, APP, None, w, num_workers)
        shards = [pfeed.scan_shard(p) for p in feed.shard_list()]
        all_tombs |= set(feed.local_tombstones(shards))
        per_worker.append(shards)
    triples = []
    for shards in per_worker:
        for shard in shards:
            sr = pfeed.PartitionFeed.shard_ratings(
                shard, ["rate", "buy"], frozenset(all_tombs),
                event_default_ratings={"buy": 4.0})
            for j in range(len(sr.rating)):
                triples.append((sr.user_ids[int(sr.u[j])],
                                sr.item_ids[int(sr.i[j])],
                                float(sr.rating[j])))
    return sorted(triples)


def test_feed_union_equals_merged_view_with_tails_and_tombstones(
        jsonl_storage, monkeypatch):
    events_dir = _events_dir(jsonl_storage)
    _build_partitioned_log(events_dir, monkeypatch)
    ref = _merged_ratings_triples(jsonl_storage)
    assert len(ref) > 100
    for n in (1, 2, 3):
        got = _feed_ratings_triples(events_dir, n)
        assert got == ref, f"num_workers={n}"


def test_partition_ratings_single_process_matches_merged(
        jsonl_storage, monkeypatch):
    """train_feed.partition_ratings (worker 0 of 1 — the whole log)
    yields the same rating multiset and vocabulary as the merged read,
    and the template read marks it partition_local."""
    events_dir = _events_dir(jsonl_storage)
    _build_partitioned_log(events_dir, monkeypatch)
    monkeypatch.setenv("PIO_TRAIN_FEED", "partition")
    u, i, r, users, items = train_feed.partition_ratings(
        "feedapp", event_names=["rate", "buy"],
        event_default_ratings={"buy": 4.0}, storage=jsonl_storage)
    got = sorted((users.inverse(int(uu)), items.inverse(int(ii)),
                  float(rr)) for uu, ii, rr in zip(u, i, r))
    assert got == _merged_ratings_triples(jsonl_storage)


def test_template_read_training_feeds_zero_merged_bytes(
        jsonl_storage, monkeypatch):
    """The acceptance assertion: with the feed armed, the template
    read path never touches the merged JSON view (poisoned here), and
    returns partition-local training data."""
    from incubator_predictionio_tpu.controller.base import doer
    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationDataSource)
    from incubator_predictionio_tpu.models.classification import (
        ClassificationDataSource)
    from incubator_predictionio_tpu.workflow.context import (
        WorkflowContext)

    events_dir = _events_dir(jsonl_storage)
    _build_partitioned_log(events_dir, monkeypatch)
    # merged-view reference for the category parity check BELOW, taken
    # BEFORE the merged view gets poisoned
    from incubator_predictionio_tpu.data.store.p_event_store import (
        PEventStore)

    ref_cats = {
        iid: set(pm.get_opt("categories"))
        for iid, pm in PEventStore.aggregate_properties(
            "feedapp", "item", storage=jsonl_storage).items()
        if pm.get_opt("categories")}
    monkeypatch.setenv("PIO_TRAIN_FEED", "partition")

    def boom(self, *a, **kw):
        raise AssertionError("merged-view scan reached from the "
                             "partition-feed read path")

    monkeypatch.setattr(JSONLEvents, "_merged_scan", boom)
    ctx = WorkflowContext(app_name="feedapp", storage=jsonl_storage)
    td = doer(RecommendationDataSource,
              {"appName": "feedapp"}).read_training(ctx)
    assert td.partition_local and len(td.rating) > 100
    assert len(td.users) and len(td.items)
    tdc = doer(ClassificationDataSource,
               {"appName": "feedapp"}).read_training(ctx)
    assert tdc.partition_local and tdc.n_global > 0
    assert len(tdc.features) == tdc.n_global  # worker 0 of 1 holds all
    # the similar-product read (view events + item categories) rides
    # the same feed; categories match the merged aggregate
    from incubator_predictionio_tpu.models.similar_product import (
        SimilarProductDataSource)

    tds = doer(SimilarProductDataSource,
               {"appName": "feedapp"}).read_training(ctx)
    assert tds.partition_local and len(tds.rating) > 0
    assert tds.item_categories
    assert tds.item_categories == ref_cats
    # merged mode still works (and DOES use the merged view)
    monkeypatch.setenv("PIO_TRAIN_FEED", "merged")
    with pytest.raises(AssertionError, match="merged-view scan"):
        doer(RecommendationDataSource,
             {"appName": "feedapp"}).read_training(ctx)


def test_partition_feed_inactive_without_jsonl_backend(memory_storage,
                                                       monkeypatch):
    monkeypatch.setenv("PIO_TRAIN_FEED", "partition")
    assert not train_feed.partition_feed_active(memory_storage)
    monkeypatch.setenv("PIO_TRAIN_FEED", "merged")
    monkeypatch.delenv("PIO_TRAIN_FEED", raising=False)


# ---------------------------------------------------------------------------
# classification examples
# ---------------------------------------------------------------------------

def test_partition_examples_match_merged_read(jsonl_storage,
                                              monkeypatch):
    from incubator_predictionio_tpu.controller.base import doer
    from incubator_predictionio_tpu.models.classification import (
        ClassificationDataSource)
    from incubator_predictionio_tpu.workflow.context import (
        WorkflowContext)

    events_dir = _events_dir(jsonl_storage)
    _build_partitioned_log(events_dir, monkeypatch)
    ctx = WorkflowContext(app_name="feedapp", storage=jsonl_storage)
    ref = doer(ClassificationDataSource,
               {"appName": "feedapp"}).read_training(ctx)
    ref_rows = sorted(
        (tuple(f), float(ref.label_values[y]))
        for f, y in zip(ref.features.tolist(), ref.labels.tolist()))
    # emulate a 2-worker gang's exchange: each worker's per-shard
    # replays (with the union tombstone set) gather into the SAME
    # merged map; each then takes its strided slice
    attrs = ["attr0", "attr1", "attr2"]
    per_worker_parts, all_tombs = [], set()
    feeds = [pfeed.PartitionFeed(events_dir, APP, None, w, 2)
             for w in range(2)]
    scans = [[pfeed.scan_shard(p) for p in f.shard_list()]
             for f in feeds]
    for f, shards in zip(feeds, scans):
        all_tombs |= set(f.local_tombstones(shards))
    for f, shards in zip(feeds, scans):
        pos = f.canonical_positions()
        per_worker_parts.append([
            (pos[s.path], {
                eid: [props, int(first), int(last)]
                for eid, (props, first, last) in
                pfeed.PartitionFeed.shard_properties(
                    s, "user", frozenset(all_tombs)).items()})
            for s in shards])
    merged = train_feed._merge_property_parts(per_worker_parts)
    rows = []
    label_values = None
    for w in range(2):
        feats, y, lv, n_global = train_feed._examples_from_map(
            merged, attrs, "plan", w, 2)
        assert n_global == len(ref.labels)
        label_values = lv
        rows += [(tuple(f), float(lv[yy]))
                 for f, yy in zip(feats.tolist(), y.tolist())]
    assert sorted(rows) == ref_rows
    assert np.array_equal(np.asarray(label_values), ref.label_values)
    # and the wired single-process path (worker 0 of 1) end to end
    monkeypatch.setenv("PIO_TRAIN_FEED", "partition")
    feats, y, lv, n_global = train_feed.partition_examples(
        "feedapp", "user", attrs, "plan", storage=jsonl_storage)
    assert n_global == len(ref.labels)
    got = sorted((tuple(f), float(lv[yy]))
                 for f, yy in zip(feats.tolist(), y.tolist()))
    assert got == ref_rows


# ---------------------------------------------------------------------------
# the data-parallel trainers (single-process kernels)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("implicit,scaling", [
    (False, "plain"), (False, "nratings"), (True, "plain")])
def test_dp_als_matches_slab_trainer(implicit, scaling):
    """The gram all-reduce kernel solves the identical normal
    equations as the bucketed slab trainer — forced onto a 2-device
    mesh so the psum/all-gather path actually runs."""
    import jax
    from incubator_predictionio_tpu.ops.als import (
        ALSParams, train_als, train_als_partition_local)
    from incubator_predictionio_tpu.parallel.mesh import (
        mesh_from_devices)

    rng = np.random.default_rng(11)
    n_users, n_items, nnz = 40, 30, 600
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    r = (rng.integers(1, 11, nnz) / 2.0).astype(np.float32)
    params = ALSParams(rank=4, num_iterations=6, seed=5, reg=0.05,
                       implicit_prefs=implicit, alpha=0.8,
                       lambda_scaling=scaling)
    ref = train_als(u, i, r, n_users, n_items, params,
                    mesh=mesh_from_devices(devices=jax.devices()[:1]))
    dp = train_als_partition_local(
        u, i, r, n_users, n_items, params,
        mesh=mesh_from_devices(devices=jax.devices()[:2]),
        force_dp=True)
    np.testing.assert_allclose(dp.user_factors, ref.user_factors,
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(dp.item_factors, ref.item_factors,
                               rtol=2e-4, atol=2e-4)


def test_dp_als_rejects_model_axis_mesh():
    import jax
    from incubator_predictionio_tpu.ops.als import (
        ALSParams, train_als_partition_local)
    from incubator_predictionio_tpu.parallel.mesh import (
        mesh_from_devices)

    mesh = mesh_from_devices(shape=(1, 2), axis_names=("d", "m"),
                             devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="1-D data mesh"):
        train_als_partition_local(
            np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.ones(1, np.float32), 1, 1, ALSParams(rank=2),
            mesh=mesh, force_dp=True)


def test_process_local_nb_lr_single_process_fallback():
    """With one process the process-local entry points delegate to the
    plain trainers — bit-identical models."""
    from incubator_predictionio_tpu.ops.linear import (
        train_logistic_regression, train_logistic_regression_process_local,
        train_naive_bayes, train_naive_bayes_process_local)

    rng = np.random.default_rng(3)
    x = rng.integers(0, 5, (60, 3)).astype(np.float32)
    y = rng.integers(0, 2, 60).astype(np.int32)
    a = train_naive_bayes(x, y, 2, smoothing=0.7)
    b = train_naive_bayes_process_local(x, y, 2, smoothing=0.7)
    np.testing.assert_array_equal(a.log_prior, b.log_prior)
    np.testing.assert_array_equal(a.log_likelihood, b.log_likelihood)
    la = train_logistic_regression(x, y, 2, reg=0.01, max_iters=30)
    lb = train_logistic_regression_process_local(x, y, 2, reg=0.01,
                                                 max_iters=30)
    np.testing.assert_array_equal(la.weights, lb.weights)
    np.testing.assert_array_equal(la.intercept, lb.intercept)


# ---------------------------------------------------------------------------
# the REAL 2-process gang off a partitioned log (merged view poisoned)
# ---------------------------------------------------------------------------

@pytest.mark.gang
def test_two_worker_gang_trains_off_partition_feed(tmp_path,
                                                   monkeypatch):
    """A REAL supervised 2-worker gang runs the full training workflow
    (leader/follower, run_train) over a prepared partitioned event log
    with `_merged_scan` poisoned in every worker: recommendation ALS,
    classification NB, and process-local LR all complete, and the
    persisted models match single-process merged-feed references at
    the gang contract (ALS 2e-4 rtol; NB exact)."""
    from incubator_predictionio_tpu.parallel.supervisor import (
        COMPLETED, GangConfig, Supervisor)

    events_dir = str(tmp_path / "events" / "pio_eventdata")
    os.makedirs(events_dir)
    _build_partitioned_log(events_dir, monkeypatch)
    env = {
        **os.environ,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "JL",
        "PIO_STORAGE_SOURCES_DB_TYPE": "SQLITE",
        "PIO_STORAGE_SOURCES_DB_PATH": str(tmp_path / "meta.sqlite"),
        "PIO_STORAGE_SOURCES_JL_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_JL_PATH": str(tmp_path / "events"),
        "PIO_TRAIN_FEED": "partition",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_COMPILATION_CACHE_DIR": str(tmp_path / "xla_cache"),
    }
    env.pop("PIO_FAULT_SPEC", None)
    storage = Storage({k: v for k, v in env.items()
                       if k.startswith("PIO_STORAGE")})
    storage.get_meta_data_apps().insert(App(id=APP, name="feedapp"))

    out_dir = str(tmp_path / "out")
    os.makedirs(out_dir)
    worker = os.path.join(HERE, "gang_feed_worker.py")
    sup = Supervisor(
        [sys.executable, worker, out_dir], num_workers=2, env=env,
        config=GangConfig(num_workers=2, heartbeat_ms=250.0,
                          stall_ms=60_000.0, init_grace_ms=300_000.0,
                          max_restarts=0, poll_ms=50.0),
        gang_instance_id="feedgang-1",
        run_dir=str(tmp_path / "run"))
    outcome = sup.run()
    logs = "\n".join(
        open(os.path.join(str(tmp_path / "run"), f"worker_{i}.log"),
             errors="replace").read() for i in range(2))
    assert outcome == COMPLETED, logs

    # --- references from the merged view, single process -------------
    from incubator_predictionio_tpu.data.store.p_event_store import (
        PEventStore)
    from incubator_predictionio_tpu.ops.als import ALSParams, train_als
    from incubator_predictionio_tpu.ops.linear import train_naive_bayes
    from incubator_predictionio_tpu.workflow import model_artifact
    import jax

    with open(os.path.join(out_dir, "ids.txt")) as f:
        rec_id, cls_id = f.read().split()

    # ALS: compare factors PER ID against a merged-feed train with the
    # same params (init is drawn in global row order, so the per-id
    # comparison is meaningful across differing index assignments)
    stored = pickle.loads(model_artifact.read_model(storage, rec_id))[0]
    g_users = stored["users"]
    g_items = stored["items"]
    u, i, r, m_users, m_items = PEventStore.find_ratings(
        "feedapp", event_names=["rate", "buy"],
        event_default_ratings={"buy": 4.0}, storage=storage)
    # re-index the merged triple through the GANG's global maps so the
    # reference train sees identical row numbering
    from incubator_predictionio_tpu.data.storage.bimap import BiMap

    gu = BiMap.from_persisted(g_users)
    gi = BiMap.from_persisted(g_items)
    assert set(gu.keys()) == set(m_users.keys())
    assert set(gi.keys()) == set(m_items.keys())
    ru = np.asarray([gu(m_users.inverse(int(x))) for x in u], np.int32)
    ri = np.asarray([gi(m_items.inverse(int(x))) for x in i], np.int32)
    params = ALSParams(rank=4, num_iterations=6, seed=5, reg=0.05)
    from incubator_predictionio_tpu.parallel.mesh import (
        mesh_from_devices)

    ref = train_als(ru, ri, r, len(gu), len(gi), params,
                    mesh=mesh_from_devices(devices=jax.devices()[:1]))
    np.testing.assert_allclose(stored["user_factors"],
                               ref.user_factors, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(stored["item_factors"],
                               ref.item_factors, rtol=2e-4, atol=2e-4)

    # NB: sufficient statistics are exact — the gang model must equal
    # the merged-feed train bit-for-bit on its log params
    cls_model = pickle.loads(
        model_artifact.read_model(storage, cls_id))[0]
    from incubator_predictionio_tpu.models.classification import (
        ClassificationDataSource)
    from incubator_predictionio_tpu.workflow.context import (
        WorkflowContext)

    ctx = WorkflowContext(app_name="feedapp", storage=storage)
    from incubator_predictionio_tpu.controller.base import doer

    td = doer(ClassificationDataSource,
              {"appName": "feedapp"}).read_training(ctx)
    nb_ref = train_naive_bayes(td.features, td.labels,
                               n_classes=len(td.label_values),
                               smoothing=0.7)
    np.testing.assert_allclose(cls_model.inner.log_prior,
                               nb_ref.log_prior, rtol=1e-6)
    np.testing.assert_allclose(cls_model.inner.log_likelihood,
                               nb_ref.log_likelihood, rtol=1e-6)
    assert np.array_equal(cls_model.label_values, td.label_values)

    # LR: data-parallel L-BFGS over mask-padded shards converges to
    # the same optimum as the single-process fit (same loss surface)
    from incubator_predictionio_tpu.ops.linear import (
        train_logistic_regression)

    lr = np.load(os.path.join(out_dir, "lr.npz"))
    lr_ref = train_logistic_regression(
        td.features, td.labels, n_classes=len(td.label_values),
        reg=0.01, max_iters=40)
    pred_ref = np.argmax(
        td.features @ lr_ref.weights + lr_ref.intercept, axis=1)
    pred_gang = np.argmax(
        td.features @ lr["weights"] + lr["intercept"], axis=1)
    assert np.array_equal(pred_ref, pred_gang)
    assert np.allclose(lr["weights"], lr_ref.weights, rtol=5e-2,
                       atol=5e-2)

    # the poison never fired: no worker touched the merged view
    assert "merged-view scan reached" not in logs


def test_trainfeed_marker_registered():
    with open(os.path.join(os.path.dirname(HERE),
                           "pyproject.toml")) as f:
        assert "trainfeed:" in f.read()
