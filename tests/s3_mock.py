"""In-process S3-compatible object store for contract tests.

Implements the object subset (PUT/GET/DELETE/HEAD on /{bucket}/{key})
with INDEPENDENT AWS Signature V4 verification: the server re-derives
the signature from the raw request (method, path, query, headers,
payload) per the SigV4 spec and rejects mismatches with 403 — so the
client in data/storage/s3.py is proven to emit real, verifiable SigV4,
not merely self-consistent output."""

from __future__ import annotations

import hashlib
import hmac
import re

from aiohttp import web


def _sha(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _hm(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def build_s3_app(access_key: str, secret_key: str, region: str = "us-east-1",
                 mode: str = "default"):
    objects: dict[str, bytes] = {}

    def verify(request: web.Request, payload: bytes) -> str | None:
        """Recompute the SigV4 signature; return an error string or None."""
        auth = request.headers.get("Authorization", "")
        m = re.match(
            r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d{8})/([^/]+)/s3/"
            r"aws4_request, SignedHeaders=([^,]+), Signature=([0-9a-f]+)",
            auth,
        )
        if not m:
            return f"malformed Authorization: {auth!r}"
        akid, datestamp, req_region, signed_headers, signature = m.groups()
        if akid != access_key:
            return "unknown access key"
        if req_region != region:
            return f"wrong region {req_region}"
        amz_date = request.headers.get("x-amz-date", "")
        content_sha = request.headers.get("x-amz-content-sha256", "")
        if _sha(payload) != content_sha:
            return "payload hash mismatch"
        canonical_headers = ""
        for h in signed_headers.split(";"):
            v = (request.headers.get("Host", "") if h == "host"
                 else request.headers.get(h, ""))
            canonical_headers += f"{h}:{v}\n"
        # raw_path keeps the as-sent percent-encoding (request.path is
        # decoded) — S3 canonicalizes the encoded form.
        raw_path = request.raw_path.split("?", 1)[0]
        canonical = "\n".join([
            request.method, raw_path, request.query_string,
            canonical_headers, signed_headers, content_sha,
        ])
        scope = f"{datestamp}/{region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope, _sha(canonical.encode()),
        ])
        k = _hm(("AWS4" + secret_key).encode(), datestamp)
        k = _hm(k, region)
        k = _hm(k, "s3")
        k = _hm(k, "aws4_request")
        expect = hmac.new(k, string_to_sign.encode(),
                          hashlib.sha256).hexdigest()
        if not hmac.compare_digest(expect, signature):
            return "signature mismatch"
        return None

    def xml_error(code: str, status: int) -> web.Response:
        return web.Response(
            status=status, content_type="application/xml",
            text=f"<?xml version=\"1.0\"?><Error><Code>{code}</Code></Error>",
        )

    async def handle(request: web.Request) -> web.Response:
        payload = await request.read()
        if mode == "clock_skew":
            # AWS rejects x-amz-date outside its 15-minute window with
            # 403 RequestTimeTooSkewed (NOT an auth failure)
            return xml_error("RequestTimeTooSkewed", 403)
        err = verify(request, payload)
        if err:
            return xml_error("SignatureDoesNotMatch", 403)
        key = request.path
        if request.method == "PUT":
            objects[key] = payload
            return web.Response(status=200)
        if request.method in ("GET", "HEAD"):
            if key not in objects:
                return xml_error("NoSuchKey", 404)
            body = objects[key] if request.method == "GET" else b""
            return web.Response(status=200, body=body)
        if request.method == "DELETE":
            objects.pop(key, None)
            return web.Response(status=204)
        return xml_error("MethodNotAllowed", 405)

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", handle)
    app["objects"] = objects
    return app
