"""bf16 numerics of the bucketed half-step.

Historical note: the old tiled layout's chunked scan reduced tile grams
to rows through a bf16 one-hot MXU matmul, which accumulated LOWER
precision normal equations than the unchunked path (documented
divergence, ADVICE r2). The bucketed layout (ops/rowblocks.py) removed
that reduction entirely — per-row grams come straight out of one einsum
with f32 accumulation — so chunking now CANNOT change the math. These
tests pin both properties: chunk-invariance under bf16, and bf16-vs-f32
distance staying at rounding level."""

import numpy as np

import jax

from incubator_predictionio_tpu.ops.als import ALSParams, train_als
from incubator_predictionio_tpu.parallel.mesh import mesh_from_devices


def _toy(seed=0, n_users=40, n_items=25, nnz=900):
    rng = np.random.default_rng(seed)
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    r = (rng.integers(1, 11, nnz) / 2.0).astype(np.float32)
    return u, i, r, n_users, n_items


def test_bf16_chunked_matches_bf16_unchunked():
    """Row-chunking slices bucket slabs over rows; with the same einsum
    shapes per row the contraction is identical — bf16 results must agree
    to float-reduction tolerance."""
    u, i, r, nu, ni = _toy()
    mesh = mesh_from_devices(devices=jax.devices("cpu")[:4])
    base = dict(rank=8, num_iterations=3, reg=0.05,
                compute_dtype="bfloat16")
    out_a = train_als(u, i, r, nu, ni, ALSParams(**base), mesh=mesh)
    out_b = train_als(u, i, r, nu, ni,
                      ALSParams(**base, block_len=8, chunk_tiles=4),
                      mesh=mesh)
    np.testing.assert_allclose(
        out_a.user_factors, out_b.user_factors, rtol=2e-3, atol=2e-4)


def test_bf16_close_to_f32():
    """bf16 gathers round factor rows to 8 mantissa bits before the f32
    gram accumulation; with the λ ridge the solved factors stay within
    bf16 rounding distance of the f32 run."""
    u, i, r, nu, ni = _toy(seed=3)
    mesh = mesh_from_devices(devices=jax.devices("cpu")[:4])
    f32 = train_als(u, i, r, nu, ni,
                    ALSParams(rank=8, num_iterations=3, reg=0.05,
                              compute_dtype="float32"), mesh=mesh)
    bf16 = train_als(u, i, r, nu, ni,
                     ALSParams(rank=8, num_iterations=3, reg=0.05,
                               compute_dtype="bfloat16"), mesh=mesh)
    scale = np.abs(f32.user_factors).max()
    err = np.abs(f32.user_factors - bf16.user_factors).max()
    assert err < 0.05 * scale, f"bf16 drifted too far: {err} vs scale {scale}"
