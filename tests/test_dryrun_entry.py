"""Driver-condition tests for __graft_entry__.

The driver validates multi-chip sharding by building a CPU mesh
(xla_force_host_platform_device_count) in a process whose DEFAULT backend
may still be a TPU (the sandbox PJRT plugin force-registers itself). Round
1 failed exactly there: the Pallas solve kernel was auto-selected from
``jax.default_backend()`` and crashed with "Only interpret mode is
supported on CPU backend". These tests pin the contract: kernel selection
follows the MESH's platform, never the process default.
"""

import sys
import os

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_dryrun_multichip_runs():
    """The exact entry point the driver calls, at the driver's size."""
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_probe_timeout_returns_zero(monkeypatch):
    """A wedged tunnel = probe subprocess that never answers.

    MULTICHIP_r03 timed out because a cold jax.devices() blocked forever
    inside the sandbox plugin's backend init. The probe must turn that
    into a bounded 0 ("default backend unusable"), not a hang.
    """
    import time

    import __graft_entry__

    monkeypatch.setenv("PIO_DRYRUN_PROBE_CODE", "import time; time.sleep(300)")
    monkeypatch.setenv("PIO_DRYRUN_PROBE_TIMEOUT", "1")
    t0 = time.monotonic()
    assert __graft_entry__._probe_default_backend() == 0
    assert time.monotonic() - t0 < 30


def test_probe_timeout_with_pipe_holding_grandchild(monkeypatch):
    """The wedge-prone plugin spawns helper processes that inherit the
    probe's stdout pipe; killing only the direct child would leave the
    parent blocked on the pipe forever. The group kill must reap it."""
    import time

    import __graft_entry__

    monkeypatch.setenv(
        "PIO_DRYRUN_PROBE_CODE",
        "import subprocess, sys, time; "
        "subprocess.Popen([sys.executable, '-c', 'import time; "
        "time.sleep(300)']); time.sleep(300)")
    monkeypatch.setenv("PIO_DRYRUN_PROBE_TIMEOUT", "1")
    t0 = time.monotonic()
    assert __graft_entry__._probe_default_backend() == 0
    assert time.monotonic() - t0 < 30


def test_probe_failure_returns_zero(monkeypatch):
    import __graft_entry__

    monkeypatch.setenv("PIO_DRYRUN_PROBE_CODE", "raise SystemExit(7)")
    assert __graft_entry__._probe_default_backend() == 0


def test_ensure_platform_pins_cpu_when_probe_fails(monkeypatch):
    """With no live backend and a dead probe, the CPU platform is pinned
    BEFORE any device query (the only hook-bypassing order)."""
    import jax
    from jax._src import xla_bridge

    import __graft_entry__

    monkeypatch.delenv("PIO_DRYRUN_FORCE_CPU", raising=False)
    monkeypatch.setattr(xla_bridge, "_backends", {})
    monkeypatch.setattr(__graft_entry__, "_probe_default_backend", lambda: 0)
    updates = []
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: updates.append((k, v)))
    __graft_entry__._ensure_platform(8)
    assert ("jax_platforms", "cpu") in updates


def test_ensure_platform_skips_probe_with_live_backend(monkeypatch):
    """Once a backend is live in-process, device queries are cache-served;
    no subprocess probe (slow, wedge-prone) should be spawned."""
    import __graft_entry__

    monkeypatch.delenv("PIO_DRYRUN_FORCE_CPU", raising=False)

    def boom():
        raise AssertionError("probe must not run with a live backend")

    monkeypatch.setattr(__graft_entry__, "_probe_default_backend", boom)
    import jax

    jax.devices()  # ensure a live backend
    __graft_entry__._ensure_platform(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_train_als_cpu_mesh_with_tpu_default_backend(monkeypatch):
    """Repro of MULTICHIP_r01: default_backend()=="tpu", mesh is CPU.

    conftest flips the test process to the CPU platform, which on r01 code
    silently disabled the Pallas path and masked the driver failure. Here
    we force default_backend() to lie ("tpu") the way the sandbox does;
    train_als must still run pure-XLA because the MESH devices are CPU.
    """
    import jax

    from incubator_predictionio_tpu.ops.als import ALSParams, train_als
    from incubator_predictionio_tpu.parallel.mesh import mesh_from_devices

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert jax.default_backend() == "tpu"  # the lie is in place

    mesh = mesh_from_devices(devices=jax.devices("cpu")[:8])
    rng = np.random.default_rng(0)
    nnz = 320
    u = rng.integers(0, 32, nnz).astype(np.int32)
    i = rng.integers(0, 24, nnz).astype(np.int32)
    r = rng.random(nnz).astype(np.float32)
    out = train_als(
        u, i, r, 32, 24,
        ALSParams(rank=8, num_iterations=1, block_len=8, chunk_tiles=2),
        mesh=mesh,
    )
    assert np.isfinite(out.user_factors).all()
    assert np.isfinite(out.item_factors).all()


def test_spd_solve_explicit_use_pallas_false_ignores_backend(monkeypatch):
    """batched_spd_solve(use_pallas=False) must never touch pallas_call."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops.pallas_kernels import batched_spd_solve

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    rng = np.random.default_rng(1)
    m = rng.standard_normal((4, 8, 8)).astype(np.float32)
    a = np.einsum("nij,nkj->nik", m, m) + 8 * np.eye(8, dtype=np.float32)
    b = rng.standard_normal((4, 8)).astype(np.float32)
    x = np.asarray(batched_spd_solve(jnp.asarray(a), jnp.asarray(b),
                                     use_pallas=False))
    np.testing.assert_allclose(a @ x[..., None], b[..., None], rtol=2e-4,
                               atol=2e-4)
