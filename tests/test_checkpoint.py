"""Checkpoint/resume tests — hook roundtrip, chunked-loop equivalence,
crash-resume exactness, and the workflow-level `--resume` discovery path.
The reference has no analog (failed Spark trains restart from scratch,
SURVEY.md §5.4), so these pin down the new subsystem's contract."""

import numpy as np
import pytest

from incubator_predictionio_tpu.ops.als import ALSParams, train_als
from incubator_predictionio_tpu.workflow.checkpoint import (
    CheckpointHook,
    find_resumable_instance,
    instance_checkpoint_dir,
)


def _toy_ratings(n_users=40, n_items=25, density=0.4, seed=2):
    rng = np.random.default_rng(seed)
    mask = rng.random((n_users, n_items)) < density
    u, i = np.nonzero(mask)
    r = rng.uniform(1, 5, len(u)).astype(np.float32)
    return u.astype(np.int32), i.astype(np.int32), r


def test_hook_save_restore_roundtrip(tmp_path):
    hook = CheckpointHook(str(tmp_path / "ckpt"), every_n=2)
    tree = {"user_factors": np.arange(12, dtype=np.float32).reshape(3, 4),
            "item_factors": np.ones((2, 4), np.float32)}
    assert hook.latest_step() is None
    assert not hook.maybe_save(1, tree)   # off-cadence step: skipped
    assert hook.maybe_save(2, tree)
    hook.save(4, {k: v * 2 for k, v in tree.items()})
    assert hook.latest_step() == 4
    step, restored = hook.restore()
    assert step == 4
    np.testing.assert_array_equal(
        restored["user_factors"], tree["user_factors"] * 2
    )
    step2, restored2 = hook.restore(2)
    np.testing.assert_array_equal(restored2["user_factors"], tree["user_factors"])
    hook.close()


def test_hook_max_to_keep(tmp_path):
    hook = CheckpointHook(str(tmp_path / "ckpt"), every_n=1, max_to_keep=2)
    for s in (1, 2, 3):
        hook.save(s, {"x": np.full(3, s, np.float32)})
    hook.close()
    hook2 = CheckpointHook(str(tmp_path / "ckpt"))
    assert hook2.latest_step() == 3
    with pytest.raises(Exception):
        hook2.restore(1)  # pruned by max_to_keep
    hook2.close()


def test_als_checkpointed_matches_single_shot(tmp_path):
    """Chunked checkpointing loop == one fori_loop, bitwise-same math."""
    u, i, r = _toy_ratings()
    params = ALSParams(rank=4, num_iterations=6, reg=0.05, block_len=8, seed=11)
    plain = train_als(u, i, r, 40, 25, params)

    hook = CheckpointHook(str(tmp_path / "ck"), every_n=2, max_to_keep=5)
    ckpt = train_als(u, i, r, 40, 25, params, checkpoint_hook=hook)
    np.testing.assert_allclose(plain.user_factors, ckpt.user_factors,
                               rtol=1e-6, atol=1e-7)
    # boundaries 2 and 4 snapshotted; 6 (completion) not
    assert hook.latest_step() == 4
    hook.close()


def test_als_resume_after_crash_matches_uninterrupted(tmp_path):
    """Kill after 4 of 6 iterations, resume → identical to a full run."""
    u, i, r = _toy_ratings(seed=5)
    full = train_als(u, i, r, 40, 25,
                     ALSParams(rank=4, num_iterations=6, reg=0.05,
                               block_len=8, seed=11))

    # "crashed" run: only 4 iterations happened, snapshots at 2 (4 would be
    # the final iteration of this truncated run and is not snapshotted) —
    # so ask for 5 with every_n=2 and interrupt by training only 4.
    hook = CheckpointHook(str(tmp_path / "ck"), every_n=2, max_to_keep=5)
    train_als(u, i, r, 40, 25,
              ALSParams(rank=4, num_iterations=4, reg=0.05,
                        block_len=8, seed=11),
              checkpoint_hook=hook)
    assert hook.latest_step() == 2

    resumed = train_als(u, i, r, 40, 25,
                        ALSParams(rank=4, num_iterations=6, reg=0.05,
                                  block_len=8, seed=11),
                        checkpoint_hook=hook, resume=True)
    np.testing.assert_allclose(full.user_factors, resumed.user_factors,
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(full.item_factors, resumed.item_factors,
                               rtol=1e-6, atol=1e-7)
    hook.close()


def test_als_resume_rejects_changed_data(tmp_path):
    u, i, r = _toy_ratings(seed=5)
    hook = CheckpointHook(str(tmp_path / "ck"), every_n=1, max_to_keep=3)
    train_als(u, i, r, 40, 25,
              ALSParams(rank=4, num_iterations=3, block_len=8),
              checkpoint_hook=hook)
    with pytest.raises(ValueError, match="do not match"):
        # rank changed since the interrupted run → snapshot is unusable
        train_als(u, i, r, 40, 25,
                  ALSParams(rank=6, num_iterations=5, block_len=8),
                  checkpoint_hook=hook, resume=True)
    # same shapes, different rating VALUES → fingerprint catches it
    r2 = r.copy()
    r2[0] += 1.0
    with pytest.raises(ValueError, match="fingerprint"):
        train_als(u, i, r2, 40, 25,
                  ALSParams(rank=4, num_iterations=5, block_len=8),
                  checkpoint_hook=hook, resume=True)
    hook.close()


def _seed_events(storage, app_name="ckptapp", n_users=30, n_items=20):
    from incubator_predictionio_tpu.data.storage.base import App
    from incubator_predictionio_tpu.data.storage.event import DataMap, Event

    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name=app_name))
    events = storage.get_l_events()
    rng = np.random.default_rng(0)
    for _ in range(400):
        u = int(rng.integers(0, n_users))
        i = int(rng.integers(0, n_items))
        events.insert(Event(
            event="rate", entity_type="user", entity_id=str(u),
            target_entity_type="item", target_entity_id=str(i),
            properties=DataMap({"rating": float(rng.uniform(1, 5))}),
        ), app_id)
    return app_id


def test_workflow_checkpoint_and_resume(memory_storage, tmp_path, monkeypatch):
    """End-to-end: train --checkpoint-every aborts mid-run (injected fault),
    train --resume picks up the same instance and completes."""
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))

    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.controller.engine import EngineParams
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.workflow_params import WorkflowParams
    from incubator_predictionio_tpu.workflow import checkpoint as ckpt_mod

    _seed_events(memory_storage)
    engine = RecommendationEngine().apply()
    ep = EngineParams(
        data_source_params={"app_name": "ckptapp"},
        algorithm_params_list=[("als", {
            "rank": 4, "numIterations": 6, "lambda": 0.05, "seed": 11,
            "block_len": 8,
        })],
    )

    # Fault injection: crash the run right after the step-4 snapshot.
    real_save = ckpt_mod.CheckpointHook.save

    def crashing_save(self, step, tree):
        real_save(self, step, tree)
        if step == 4:
            raise RuntimeError("injected mid-train crash")

    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", crashing_save)
    ctx = WorkflowContext(app_name="ckptapp", storage=memory_storage)
    with pytest.raises(RuntimeError, match="injected"):
        run_train(engine, ep, ctx, WorkflowParams(checkpoint_every=2),
                  engine_factory_name="RecEngine")
    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", real_save)

    instances = memory_storage.get_meta_data_engine_instances()
    aborted = [x for x in instances.get_all() if x.status == "ABORTED"]
    assert len(aborted) == 1
    found = find_resumable_instance(memory_storage, "RecEngine")
    assert found is not None and found.id == aborted[0].id

    # Resume: same instance id goes RUNNING → COMPLETED, checkpoints cleaned.
    ctx2 = WorkflowContext(app_name="ckptapp", storage=memory_storage)
    iid = run_train(engine, ep, ctx2, WorkflowParams(resume=True),
                    engine_factory_name="RecEngine")
    assert iid == aborted[0].id
    assert instances.get(iid).status == "COMPLETED"
    import os
    assert not os.path.isdir(instance_checkpoint_dir(iid))

    # The resumed model must equal an uninterrupted train on the same data.
    from incubator_predictionio_tpu.workflow.core_workflow import load_deployment
    dep, _, _ = load_deployment(engine, iid, ctx2, engine_factory_name="RecEngine")
    res = dep.query({"user": "1", "num": 3})
    assert len(res["itemScores"]) == 3


def test_workflow_resume_with_changed_params_trains_fresh(
    memory_storage, tmp_path, monkeypatch
):
    """--resume must NOT blend hyperparameters: params drift since the
    interrupted run ⇒ a fresh instance, not a hijacked resume."""
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))

    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.controller.engine import EngineParams
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.workflow_params import WorkflowParams
    from incubator_predictionio_tpu.workflow import checkpoint as ckpt_mod

    _seed_events(memory_storage)
    engine = RecommendationEngine().apply()

    def params_with(reg):
        return EngineParams(
            data_source_params={"app_name": "ckptapp"},
            algorithm_params_list=[("als", {
                "rank": 4, "numIterations": 6, "lambda": reg, "seed": 11,
                "block_len": 8,
            })],
        )

    real_save = ckpt_mod.CheckpointHook.save

    def crashing_save(self, step, tree):
        real_save(self, step, tree)
        raise RuntimeError("injected crash")

    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", crashing_save)
    with pytest.raises(RuntimeError, match="injected"):
        run_train(engine, params_with(0.05),
                  WorkflowContext(app_name="ckptapp", storage=memory_storage),
                  WorkflowParams(checkpoint_every=2),
                  engine_factory_name="RecEngine")
    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", real_save)

    instances = memory_storage.get_meta_data_engine_instances()
    aborted_id = [x for x in instances.get_all() if x.status == "ABORTED"][0].id

    # different lambda → new instance id, aborted row left untouched
    iid = run_train(engine, params_with(0.5),
                    WorkflowContext(app_name="ckptapp", storage=memory_storage),
                    WorkflowParams(resume=True),
                    engine_factory_name="RecEngine")
    assert iid != aborted_id
    assert instances.get(iid).status == "COMPLETED"
    assert instances.get(aborted_id).status == "ABORTED"
    # superseded snapshots are discarded, so the stale row can never be
    # picked up by a later --resume
    import os
    assert not os.path.isdir(instance_checkpoint_dir(aborted_id))
    assert find_resumable_instance(memory_storage, "RecEngine") is None


def test_workflow_resume_with_changed_data_falls_back(memory_storage, tmp_path,
                                                      monkeypatch):
    """Event data changed after the crash ⇒ fingerprint mismatch ⇒ the
    workflow discards the stale snapshots and completes from scratch
    instead of erroring forever (poisoned-resume regression)."""
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))

    from incubator_predictionio_tpu.models.recommendation import (
        RecommendationEngine,
    )
    from incubator_predictionio_tpu.controller.engine import EngineParams
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.workflow_params import WorkflowParams
    from incubator_predictionio_tpu.workflow import checkpoint as ckpt_mod

    app_id = _seed_events(memory_storage)
    engine = RecommendationEngine().apply()
    ep = EngineParams(
        data_source_params={"app_name": "ckptapp"},
        algorithm_params_list=[("als", {
            "rank": 4, "numIterations": 6, "lambda": 0.05, "seed": 11,
            "block_len": 8,
        })],
    )

    real_save = ckpt_mod.CheckpointHook.save

    def crashing_save(self, step, tree):
        real_save(self, step, tree)
        raise RuntimeError("injected crash")

    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", crashing_save)
    with pytest.raises(RuntimeError, match="injected"):
        run_train(engine, ep,
                  WorkflowContext(app_name="ckptapp", storage=memory_storage),
                  WorkflowParams(checkpoint_every=2),
                  engine_factory_name="RecEngine")
    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", real_save)

    # the event store changes between crash and resume (same users/items,
    # one more rating for an existing pair keeps all shapes identical)
    from incubator_predictionio_tpu.data.storage.event import DataMap, Event
    memory_storage.get_l_events().insert(Event(
        event="rate", entity_type="user", entity_id="0",
        target_entity_type="item", target_entity_id="0",
        properties=DataMap({"rating": 5.0}),
    ), app_id)

    iid = run_train(engine, ep,
                    WorkflowContext(app_name="ckptapp", storage=memory_storage),
                    WorkflowParams(resume=True),
                    engine_factory_name="RecEngine")
    instances = memory_storage.get_meta_data_engine_instances()
    assert instances.get(iid).status == "COMPLETED"
    import os
    assert not os.path.isdir(instance_checkpoint_dir(iid))


def test_multi_algorithm_checkpoint_namespacing(memory_storage, tmp_path,
                                                monkeypatch):
    """Two algorithms in one engine must snapshot into separate
    subdirectories (else orbax step numbers collide and --resume restores
    the wrong algorithm's factors)."""
    monkeypatch.setenv("PIO_FS_BASEDIR", str(tmp_path))

    from incubator_predictionio_tpu.models.recommendation import (
        ALSAlgorithm,
        RecommendationDataSource,
    )
    from incubator_predictionio_tpu.controller.engine import Engine, EngineParams
    from incubator_predictionio_tpu.workflow.context import WorkflowContext
    from incubator_predictionio_tpu.workflow.core_workflow import run_train
    from incubator_predictionio_tpu.workflow.workflow_params import WorkflowParams
    from incubator_predictionio_tpu.workflow import checkpoint as ckpt_mod

    _seed_events(memory_storage)
    engine = Engine(
        data_source_class=RecommendationDataSource,
        algorithm_class_map={"a1": ALSAlgorithm, "a2": ALSAlgorithm},
    )
    algo_params = {"rank": 4, "numIterations": 6, "lambda": 0.05, "seed": 11,
                   "block_len": 8}
    ep = EngineParams(
        data_source_params={"app_name": "ckptapp"},
        algorithm_params_list=[("a1", algo_params), ("a2", algo_params)],
    )

    saved_dirs = []
    real_save = ckpt_mod.CheckpointHook.save

    def spy_save(self, step, tree):
        saved_dirs.append(self.directory)
        real_save(self, step, tree)

    monkeypatch.setattr(ckpt_mod.CheckpointHook, "save", spy_save)
    ctx = WorkflowContext(app_name="ckptapp", storage=memory_storage)
    iid = run_train(engine, ep, ctx, WorkflowParams(checkpoint_every=2),
                    engine_factory_name="MultiEngine")
    assert memory_storage.get_meta_data_engine_instances().get(iid).status == "COMPLETED"
    assert saved_dirs, "checkpointing never ran"
    # both algorithms snapshotted, into distinct subdirectories
    assert len({d for d in saved_dirs}) == 2
    assert all("algo_0_a1" in d or "algo_1_a2" in d for d in saved_dirs)
