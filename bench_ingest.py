"""Benchmark: Event Server ingestion throughput (events/sec).

The reference's ★ ingestion hot path (SURVEY.md §3.3: POST /events.json
→ auth → validate → HBase Put). This drives the REAL event server over
HTTP — access-key auth, JSON validation, reserved-event rules, storage
write — measuring:

- single-event POSTs across a concurrency sweep (`PIO_INGEST_CONC`,
  default "1,8,32,128"), with the write-behind group-commit buffer OFF
  and ON (`PIO_INGEST_GROUP`), reporting enqueue→ack latency p50/p99
  per point alongside throughput so the buffer's latency cost is
  visible next to its throughput win
- /batch/events.json at the wire cap (50 events/request), both modes
- bulk import path (`pio import`-equivalent insert_batch) for contrast
- multi-worker bracket (`PIO_INGEST_MULTIWORKER=0` skips): REAL
  `pio eventserver --workers N` subprocess topologies at N=1/2/4,
  same-run, WAL armed — the partitioned-event-log scale-out number
  (ISSUE 8); persisted as `measured_ingest_multiworker`
- compacted-scan timing: a cold columnar-snapshot load vs the JSON
  re-parse of the same log (`measured_eventlog_scan`)
- windowed-feed timing (ISSUE 18): a `--window` cold read over a log
  with three sealed time-disjoint generations + a fresh tail vs the
  full-log scan, same run — the generation-skip (zero-decode) win
  (`measured_windowed_feed`)

against the JSONL event log (the training-fast-path store of record)
by default; PIO_INGEST_BACKEND=SQLITE|MEMORY switches. Ack semantics
default to commit (PIO_INGEST_ACK) — durability unchanged.

Prints ONE JSON line per mode; persists under
BASELINE.json.published.measured_ingest_* (`..._nogroup` holds the
buffer-off sweep, `..._wal` the same sweep with the crash-durability
write-ahead log armed — PIO_WAL=1, fsync=group — so the durability
cost is a same-run bracket next to the group-commit numbers). `host_loop_mops` is a single-thread Python
calibration so numbers from differently-sized hosts stay comparable —
ingestion is a host path, CPU-bound, so cross-host absolute numbers
are only meaningful relative to it. No accelerator involved.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def host_calibration() -> float:
    """Single-thread Python Mops — the common denominator for
    comparing ingest numbers measured on different hosts."""
    t0 = time.perf_counter()
    s = 0
    for i in range(2_000_000):
        s += i
    return 2.0 / (time.perf_counter() - t0)


import socket  # noqa: E402


class HttpClient:
    """Minimal keep-alive HTTP/1.1 client. `requests` costs ~1 ms of
    CLIENT-side Python per call; on this shared-core host client and
    server split the CPU, so a fat client measures mostly itself.
    Ingestion is a SERVER benchmark — the client must be as thin as
    real SDK traffic from another box. Requests are pre-serialized to
    raw bytes before the timed region."""

    def __init__(self, base_url):
        host, port = base_url.replace("http://", "").split(":")
        self.sock = socket.create_connection((host, int(port)))
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    @staticmethod
    def encode(path, obj) -> bytes:
        body = json.dumps(obj).encode()
        return ((f"POST {path} HTTP/1.1\r\nHost: b\r\n"
                 f"Content-Type: application/json\r\n"
                 f"Content-Length: {len(body)}\r\n\r\n").encode() + body)

    def send_raw(self, req: bytes) -> None:
        self.sock.sendall(req)

    def recv_response(self) -> int:
        def recv():
            chunk = self.sock.recv(65536)
            if not chunk:  # server closed: fail, don't spin forever
                raise ConnectionError("server closed connection")
            return chunk

        while b"\r\n\r\n" not in self.buf:
            self.buf += recv()
        head, rest = self.buf.split(b"\r\n\r\n", 1)
        status = int(head.split(None, 2)[1])
        clen = 0
        for line in head.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                clen = int(line.split(b":", 1)[1])
        while len(rest) < clen:
            rest += recv()
        self.buf = rest[clen:]
        return status

    def post_raw(self, req: bytes) -> int:
        self.send_raw(req)
        return self.recv_response()

    def post(self, path, obj) -> int:
        return self.post_raw(self.encode(path, obj))

    def close(self):
        self.sock.close()


def ev(k):
    # deterministic per-index (thread-safe: no shared RNG state)
    return {"event": "view", "entityType": "user",
            "entityId": str((k * 7919) % 10000),
            "targetEntityType": "item",
            "targetEntityId": str((k * 104729) % 2000),
            "eventTime": "2026-01-01T00:00:00.000Z"}


def make_storage(backend: str, tmp: str):
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import AccessKey, App

    env = {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_EV_TYPE": backend,
        "PIO_STORAGE_SOURCES_EV_PATH": os.path.join(tmp, "events"),
    }
    if backend == "MEMORY":
        env["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "M"
    storage = Storage(env)
    storage.get_meta_data_apps().insert(App(0, "ingest"))
    storage.get_meta_data_access_keys().insert(AccessKey("k1", 1, ()))
    return storage


def run_single_sweep(st, concs, n_per_point):
    """Single-event POSTs at each concurrency level; returns
    {conc: {"events_per_sec", "p50_ms", "p99_ms"}}.

    Concurrency = number of keep-alive CONNECTIONS, each with one
    request in flight (the SDK pattern). A thread per connection would
    measure GIL thrash on this shared-core host, so a bounded worker
    pool drives conc/threads sockets each in lockstep: send on every
    socket, then collect every response. Latency is per request,
    send→ack."""
    import concurrent.futures

    base = "/events.json?accessKey=k1"
    out = {}
    for conc in concs:
        n = max(n_per_point, conc * 10)
        # largest divisor of conc that is <= 8, so threads * conns/thread
        # covers conc EXACTLY for any sweep value (12, 20, 100, ...)
        threads = max(t for t in range(1, min(8, conc) + 1)
                      if conc % t == 0)
        conns_per_worker = conc // threads
        per_conn = max(1, n // conc)

        def worker(w):
            socks = [HttpClient(st.base) for _ in range(conns_per_worker)]
            reqs = [[HttpClient.encode(
                base, ev((w * conns_per_worker + i) * per_conn + j))
                for j in range(per_conn)] for i in range(conns_per_worker)]
            lat = np.empty(per_conn * conns_per_worker)
            t0s = [0.0] * conns_per_worker
            ok = 0
            try:
                for j in range(per_conn):
                    for i, c in enumerate(socks):
                        t0s[i] = time.perf_counter()
                        c.send_raw(reqs[i][j])
                    for i, c in enumerate(socks):
                        ok += c.recv_response() == 201
                        lat[j * conns_per_worker + i] = (
                            time.perf_counter() - t0s[i])
            finally:
                for c in socks:
                    c.close()
            return ok, lat

        t0 = time.perf_counter()
        if threads == 1:
            ok, lats = worker(0)
            lats = [lats]
        else:
            with concurrent.futures.ThreadPoolExecutor(threads) as pool:
                got = list(pool.map(worker, range(threads)))
            ok = sum(g[0] for g in got)
            lats = [g[1] for g in got]
        dt = time.perf_counter() - t0
        sent = per_conn * conc
        assert ok == sent, f"{sent - ok} single POSTs failed at c{conc}"
        lat = np.concatenate(lats) * 1000.0
        out[conc] = {
            "events_per_sec": round(ok / dt, 1),
            "p50_ms": round(float(np.percentile(lat, 50)), 3),
            "p99_ms": round(float(np.percentile(lat, 99)), 3),
        }
        log(f"[ingest]   single x{conc}: {ok / dt:,.0f} ev/s  "
            f"p50 {out[conc]['p50_ms']} ms  p99 {out[conc]['p99_ms']} ms")
    return out


def run_batch50(st, n_batch):
    bbase = "/batch/events.json?accessKey=k1"
    n_reqs = max(n_batch // 50, 1)
    cli = HttpClient(st.base)
    try:
        reqs = [HttpClient.encode(bbase, [ev(b * 50 + j) for j in range(50)])
                for b in range(n_reqs)]
        t0 = time.perf_counter()
        ok = sum(cli.post_raw(r) == 200 for r in reqs)
        dt = time.perf_counter() - t0
    finally:
        cli.close()
    assert ok == n_reqs, f"{n_reqs - ok} batch POSTs failed"
    return n_reqs * 50 / dt


def _mw_env(tmp: str) -> dict:
    return {
        **os.environ,
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
        "PIO_STORAGE_SOURCES_DB_TYPE": "SQLITE",
        "PIO_STORAGE_SOURCES_DB_PATH": os.path.join(tmp, "meta.sqlite"),
        "PIO_STORAGE_SOURCES_EV_TYPE": "JSONL",
        "PIO_STORAGE_SOURCES_EV_PATH": os.path.join(tmp, "events"),
        "PIO_WAL": "1",
        "PIO_WAL_DIR": os.path.join(tmp, "wal"),
        "PIO_FS_BASEDIR": os.path.join(tmp, "pio_store"),
        "JAX_PLATFORMS": "cpu",
    }


def _mw_prepare(env) -> None:
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import AccessKey, App

    storage = Storage({k: v for k, v in env.items()
                       if k.startswith("PIO_STORAGE")})
    app_id = storage.get_meta_data_apps().insert(App(0, "mw"))
    storage.get_meta_data_access_keys().insert(AccessKey("k1", app_id, ()))
    storage.close()


def _mw_drive(base_url: str, conc: int, n: int) -> float:
    """events/sec of single-event POSTs over `conc` keep-alive
    connections (the run_single_sweep discipline, one fixed point)."""
    import concurrent.futures

    base = "/events.json?accessKey=k1"
    threads = max(t for t in range(1, min(8, conc) + 1) if conc % t == 0)
    conns_per_worker = conc // threads
    per_conn = max(1, n // conc)

    def worker(w):
        socks = [HttpClient(base_url) for _ in range(conns_per_worker)]
        reqs = [[HttpClient.encode(
            base, ev((w * conns_per_worker + i) * per_conn + j))
            for j in range(per_conn)] for i in range(conns_per_worker)]
        ok = 0
        try:
            for j in range(per_conn):
                for i, c in enumerate(socks):
                    c.send_raw(reqs[i][j])
                for c in socks:
                    ok += c.recv_response() == 201
        finally:
            for c in socks:
                c.close()
        return ok

    t0 = time.perf_counter()
    if threads == 1:
        ok = worker(0)
    else:
        with concurrent.futures.ThreadPoolExecutor(threads) as pool:
            ok = sum(pool.map(worker, range(threads)))
    dt = time.perf_counter() - t0
    sent = per_conn * conc
    assert ok == sent, f"{sent - ok} POSTs failed in multiworker drive"
    return ok / dt


class _MwTopology:
    """One live `pio eventserver --workers N` topology (front +
    supervised worker subprocesses, SQLITE metadata + JSONL shards +
    per-partition WAL in a private tmp dir)."""

    def __init__(self, workers: int):
        import subprocess

        self.tmp = tempfile.mkdtemp(prefix=f"pio_mw{workers}_")
        env = _mw_env(self.tmp)
        _mw_prepare(env)
        port = _free_port()
        self.base = f"http://127.0.0.1:{port}"
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "incubator_predictionio_tpu.tools.console", "eventserver",
             "--workers", str(max(1, workers)), "--ip", "127.0.0.1",
             "--port", str(port)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"multiworker front died rc={self.proc.returncode}")
            try:
                cli = HttpClient(self.base)
                if cli.post("/events.json?accessKey=k1", ev(0)) == 201:
                    cli.close()
                    return
                cli.close()
            except OSError:
                time.sleep(0.2)
        raise RuntimeError("multiworker front not ready in time")

    def close(self):
        import shutil
        import signal
        import subprocess

        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        shutil.rmtree(self.tmp, ignore_errors=True)


def run_multiworker_bracket(brackets, conc: int, n: int,
                            rounds: int = 3) -> dict:
    """Same-run `pio eventserver --workers N` throughput bracket.

    This host's CPU can swing severalfold WITHIN one bench run, so a
    single sequential sweep booked as a bracket would mostly measure
    the swing. All topologies are brought up FIRST, then the drive
    interleaves them round-robin for `rounds` rounds (adjacent
    measurements are close in time); each point reports the median
    across rounds, and each speedup is the median of the WITHIN-round
    ratios — drift that moves a whole round cancels out of the ratio."""
    topos = {}
    out = {}
    try:
        for w in brackets:
            topos[w] = _MwTopology(w)
        for w in brackets:  # warm-up every topology once
            _mw_drive(topos[w].base, conc, max(200, n // 10))
        per_round: dict = {w: [] for w in brackets}
        for r in range(rounds):
            for w in brackets:
                rate = _mw_drive(topos[w].base, conc, n)
                per_round[w].append(rate)
                log(f"[ingest]   multiworker x{w} (round {r + 1}): "
                    f"{rate:,.0f} ev/s (conc {conc})")
        for w in brackets:
            out[f"workers_{w}"] = round(
                float(np.median(per_round[w])), 1)
            out[f"workers_{w}_rounds"] = [round(v, 1)
                                          for v in per_round[w]]
        if 1 in brackets:
            for w in brackets:
                if w == 1:
                    continue
                ratios = [per_round[w][r] / per_round[1][r]
                          for r in range(rounds)]
                out[f"speedup_{w}"] = round(float(np.median(ratios)), 2)
                log(f"[ingest]   multiworker speedup x{w}: "
                    f"{out[f'speedup_{w}']}x (per-round "
                    f"{[round(x, 2) for x in ratios]})")
    finally:
        for t in topos.values():
            t.close()
    out["conc"] = conc
    out["rounds"] = rounds
    out["host_scaleout_ceiling"] = _host_scaleout_ceiling(conc, n)
    ceiling = out["host_scaleout_ceiling"].get("ceiling") or 0.0
    if ceiling < 1.8:
        out["note"] = (
            "host-limited: the ceiling control (TWO fully independent "
            "servers vs one, identical client shape — the best case of "
            f"ANY scale-out) reached only {ceiling}x on this host "
            f"({os.cpu_count()} cores; client+front+worker saturate "
            "them), so the bracket measures host capacity, not the "
            "partitioned log; a >=1.8x demonstration needs >=4 usable "
            "cores")
        log(f"[ingest]   NOTE: host scale-out ceiling {ceiling}x < 1.8x "
            "— bracket is host-limited on this machine")
    return out


def _host_scaleout_ceiling(conc: int, n: int) -> dict:
    """Same-run control: TWO fully independent event-server processes
    (no front, no supervisor, separate stores — the theoretical best
    case of ANY scale-out) vs ONE, under an identical client shape.
    The ratio is what this HOST can express: on a box whose cores are
    already saturated by client+kernel+server at 1 worker, no
    architecture can beat it — a ceiling near 1.0 means the bracket
    above measures the host, not the partitioned log."""
    import shutil
    import signal
    import subprocess
    import threading

    half = max(2, conc // 2)
    procs, tmps, bases = [], [], []
    try:
        for i in range(2):
            tmp = tempfile.mkdtemp(prefix=f"pio_ceil{i}_")
            tmps.append(tmp)
            env = _mw_env(tmp)
            _mw_prepare(env)
            port = _free_port()
            env["PIO_EVENT_WORKER_PORT"] = str(port)
            env["PIO_EVENT_PARTITION"] = str(i)
            env["PIO_WAL_DIR"] = os.path.join(env["PIO_WAL_DIR"], f"p{i}")
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "incubator_predictionio_tpu.tools.console",
                 "eventserver", "--worker"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL))
            bases.append(f"http://127.0.0.1:{port}")
        for base in bases:
            deadline = time.monotonic() + 90
            ready = False
            while time.monotonic() < deadline:
                try:
                    cli = HttpClient(base)
                    ok = cli.post("/events.json?accessKey=k1", ev(0)) == 201
                    cli.close()
                    if ok:
                        ready = True
                        break
                except OSError:
                    pass
                time.sleep(0.2)
            if not ready:
                raise RuntimeError(f"ceiling worker at {base} not ready")

        def dual_drive(targets):
            rates = [0.0, 0.0]

            def go(i):
                rates[i] = _mw_drive(targets[i], half, n // 2)

            ts = [threading.Thread(target=go, args=(i,)) for i in (0, 1)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return rates[0] + rates[1]

        # interleaved rounds + ratio-of-adjacent-measurements: the
        # host's CPU swing must cancel out of the ceiling, or a swing
        # reads as an impossible >2x "scale-out"
        ones, twos, ratios = [], [], []
        dual_drive([bases[0], bases[0]])  # warm-up
        dual_drive(bases)
        for _ in range(3):
            one = dual_drive([bases[0], bases[0]])
            two = dual_drive(bases)
            ones.append(one)
            twos.append(two)
            ratios.append(two / one if one else 0.0)
        out = {"one_server": round(float(np.median(ones)), 1),
               "two_servers": round(float(np.median(twos)), 1),
               "ceiling": round(float(np.median(ratios)), 2)}
        log(f"[ingest]   host scale-out ceiling: 1-server "
            f"{out['one_server']:,.0f} vs 2-independent-servers "
            f"{out['two_servers']:,.0f} ev/s ({out['ceiling']}x, "
            f"per-round {[round(r, 2) for r in ratios]})")
        return out
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
                p.wait(timeout=20)
            except Exception:  # noqa: BLE001 — bench teardown
                p.kill()
                p.wait()
        for tmp in tmps:
            shutil.rmtree(tmp, ignore_errors=True)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_compacted_scan_bench(n_events: int = 60_000) -> dict:
    """Cold scan of one JSONL log: columnar-snapshot load (the event-log
    compactor's output) vs the native JSON re-parse of the same bytes.
    Same-run, same data — the train-time read-path win of ISSUE 8."""
    import shutil

    from incubator_predictionio_tpu.data.api import event_log
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.data.storage.jsonl import JSONLEvents

    tmp = tempfile.mkdtemp(prefix="pio_colseg_")
    try:
        le = JSONLEvents(tmp)
        chunk = [Event.from_json(ev(i)) for i in range(5000)]
        for _ in range(max(1, n_events // 5000)):
            le.insert_batch(chunk, 1)
        le.close()
        path = os.path.join(tmp, "events_1.jsonl")
        size = os.path.getsize(path)

        def cold_scan_seconds() -> float:
            t0 = time.perf_counter()
            fresh = JSONLEvents(tmp)
            cols, rows = fresh.scan_columnar(1)
            assert len(rows) >= n_events - 1
            return time.perf_counter() - t0

        json_s = min(cold_scan_seconds() for _ in range(3))
        manifest = event_log.compact_log(path)
        assert manifest is not None
        snap_s = min(cold_scan_seconds() for _ in range(3))
        out = {
            "events": manifest["events"],
            "log_bytes": size,
            "json_parse_s": round(json_s, 4),
            "compacted_s": round(snap_s, 4),
            "speedup": round(json_s / snap_s, 2) if snap_s > 0 else None,
        }
        log(f"[ingest] compacted scan: {out['events']} events, JSON "
            f"parse {json_s * 1e3:.0f}ms vs snapshot {snap_s * 1e3:.0f}ms "
            f"({out['speedup']}x)")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def run_windowed_feed_bench(n_sealed: int = 60_000,
                            n_tail: int = 2_000) -> dict:
    """Windowed training read vs the full-log cold scan, same run, same
    log: three sealed generations a month apart in event time plus a
    fresh uncompacted tail. A `--window` read skips disjoint
    generations by their manifest event-time bounds alone — zero
    snapshot bytes decoded — so training on the tail does not pay for
    the cold sealed bytes (ISSUE 18). Rounds are interleaved
    full/tail/1-gen so host drift hits every arm equally; the reported
    speedups are medians of WITHIN-round ratios."""
    import datetime as dt
    import shutil

    from incubator_predictionio_tpu.data.api import event_log
    from incubator_predictionio_tpu.data.storage.event import Event
    from incubator_predictionio_tpu.data.storage.jsonl import JSONLEvents

    months = [dt.datetime(2026, m, 1, tzinfo=dt.timezone.utc)
              for m in (1, 3, 5, 6)]

    def tev(k, base):
        # ev(k) pins eventTime to one instant; windowed reads need real
        # event-time spread (within a day — generations stay disjoint).
        e = ev(k)
        e["eventTime"] = (base + dt.timedelta(
            seconds=(k * 137) % 86_400)).strftime("%Y-%m-%dT%H:%M:%S.000Z")
        return e

    per = max(1, n_sealed // 3)
    tmp = tempfile.mkdtemp(prefix="pio_window_")
    try:
        path = os.path.join(tmp, "events_1.jsonl")
        for base in months[:3]:  # three sealed, time-disjoint generations
            le = JSONLEvents(tmp)
            le.insert_batch([Event.from_json(tev(i, base))
                             for i in range(per)], 1)
            le.close()
            assert event_log.compact_log(path) is not None
        le = JSONLEvents(tmp)
        le.insert_batch([Event.from_json(tev(i, months[3]))
                         for i in range(n_tail)], 1)  # uncompacted tail
        le.close()
        size = os.path.getsize(path)

        def cold_seconds(start, expect) -> float:
            # fresh store per timing: the windowed chain cache is
            # per-instance, so every arm is a true cold read
            t0 = time.perf_counter()
            fresh = JSONLEvents(tmp)
            cols, rows = fresh.scan_columnar(1, start_time=start)
            assert len(rows) == expect, (len(rows), expect)
            return time.perf_counter() - t0

        brackets = {
            "full": (None, 3 * per + n_tail),
            "window_tail": (months[3] - dt.timedelta(days=2),
                            n_tail),
            "window_1gen": (months[2] - dt.timedelta(days=2),
                            per + n_tail),
        }
        # one instrumented tail read first: prove the win is generation
        # skip (manifest bounds, zero decode), not cache warmth
        skips0 = event_log._M_WINDOW_SKIPS.value()
        cold_seconds(*brackets["window_tail"])
        tail_skips = event_log._M_WINDOW_SKIPS.value() - skips0

        rounds = int(os.environ.get("PIO_WINDOW_ROUNDS", "5"))
        times: dict = {k: [] for k in brackets}
        for _ in range(rounds):
            for k, (start, expect) in brackets.items():
                times[k].append(cold_seconds(start, expect))
        med = {k: float(np.median(v)) for k, v in times.items()}
        ratio = {k: float(np.median([f / w for f, w in
                                     zip(times["full"], times[k])]))
                 for k in ("window_tail", "window_1gen")}
        out = {
            "events": 3 * per + n_tail,
            "sealed_generations": 3,
            "tail_events": n_tail,
            "log_bytes": size,
            "full_scan_s": round(med["full"], 4),
            "window_tail_s": round(med["window_tail"], 4),
            "window_1gen_s": round(med["window_1gen"], 4),
            "speedup_tail": round(ratio["window_tail"], 2),
            "speedup_1gen": round(ratio["window_1gen"], 2),
            "tail_generations_skipped": int(tail_skips),
        }
        log(f"[ingest] windowed feed: {out['events']} events in 3 sealed "
            f"generations + {n_tail} tail; full {med['full'] * 1e3:.0f}ms, "
            f"tail-window {med['window_tail'] * 1e3:.0f}ms "
            f"({out['speedup_tail']}x, {tail_skips} generations skipped), "
            f"1-gen window {med['window_1gen'] * 1e3:.0f}ms "
            f"({out['speedup_1gen']}x)")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests"))
    from server_utils import ServerThread

    from incubator_predictionio_tpu.data.api.event_server import EventServer

    backend = os.environ.get("PIO_INGEST_BACKEND", "JSONL").upper()
    n_single = int(os.environ.get("PIO_INGEST_N_SINGLE", "2000"))
    n_batch = int(os.environ.get("PIO_INGEST_N_BATCH", "40000"))
    concs = [int(c) for c in os.environ.get(
        "PIO_INGEST_CONC", "1,8,32,128").split(",") if c.strip()]
    mops = host_calibration()
    log(f"[ingest] host calibration: {mops:.1f} python Mops")

    by_mode = {}
    for group in ("off", "on", "wal"):
        # "wal" = group commit ON + the write-ahead log armed (PIO_WAL=1,
        # default fsync=group): the same-run bracket that prices crash
        # durability next to the plain group-commit numbers.
        os.environ["PIO_INGEST_GROUP"] = "on" if group == "wal" else group
        tmp = tempfile.mkdtemp(prefix=f"pio_ingest_{group}_")
        if group == "wal":
            os.environ["PIO_WAL"] = "1"
            os.environ["PIO_WAL_DIR"] = os.path.join(tmp, "wal")
        else:
            os.environ.pop("PIO_WAL", None)
        storage = make_storage(backend, tmp)
        server = EventServer(storage)
        log(f"[ingest] --- group-commit {group} "
            f"({server.ingest.config.to_json()}) ---")
        tele_off_sweep = None
        tele_ratio: dict = {}
        with ServerThread(server.app) as st:
            cli = HttpClient(st.base)
            assert cli.post("/events.json?accessKey=k1", ev(0)) == 201
            cli.close()
            sweep = run_single_sweep(st, concs, n_single)
            if group == "on" and os.environ.get(
                    "PIO_BENCH_TELEMETRY", "").lower() in ("1", "ab", "on"):
                # telemetry overhead A/B/A: rerun the buffered sweep with
                # metric recording disabled, then enabled again, IN THE
                # SAME PROCESS/run. The off-sweep is compared against the
                # MEAN of the two bracketing on-sweeps so monotonic
                # drift (cache warm-up, store growth, host CPU swings —
                # see host_loop_mops) cancels to first order instead of
                # being booked as telemetry cost.
                from incubator_predictionio_tpu.common import telemetry
                telemetry.set_metrics_enabled(False)
                try:
                    tele_off_sweep = run_single_sweep(st, concs, n_single)
                finally:
                    telemetry.set_metrics_enabled(True)
                on2 = run_single_sweep(st, concs, n_single)
                for c in concs:
                    mean_on = (sweep[c]["events_per_sec"]
                               + on2[c]["events_per_sec"]) / 2
                    without = tele_off_sweep[c]["events_per_sec"]
                    tele_ratio[c] = mean_on / without
                    log(f"[ingest]   telemetry on/off x{c}: "
                        f"{tele_ratio[c]:.3f} "
                        f"({without:,.0f} ev/s off vs "
                        f"{mean_on:,.0f} mean-on; bracket "
                        f"{sweep[c]['events_per_sec']:,.0f}/"
                        f"{on2[c]['events_per_sec']:,.0f})")
            batch50 = run_batch50(st, n_batch)
            log(f"[ingest]   batch/events.json (50/req): {batch50:,.0f} ev/s")
        if group in ("on", "wal"):
            snap = server.ingest.snapshot()
            extra = ""
            if "wal" in snap:
                extra = (f" walRecords={snap['wal']['appendedRecords']}"
                         f" walBytes={snap['wal']['appendedBytes']}")
            log(f"[ingest]   groups={snap['groupsCommitted']} "
                f"events={snap['eventsCommitted']} "
                f"maxGroup={snap['maxGroup']}{extra}")
        by_mode[group] = {"sweep": sweep, "batch50": round(batch50, 1),
                          "storage": storage,
                          "tele_off_sweep": tele_off_sweep,
                          "tele_ratio": tele_ratio}
    os.environ.pop("PIO_INGEST_GROUP", None)
    os.environ.pop("PIO_WAL", None)
    os.environ.pop("PIO_WAL_DIR", None)

    # bulk import path for contrast (storage-level, no HTTP)
    from incubator_predictionio_tpu.data.storage.event import Event

    le = by_mode["on"]["storage"].get_l_events()
    evs = [Event.from_json(ev(0)) for _ in range(n_batch)]
    t0 = time.perf_counter()
    le.insert_batch(evs, 1)
    insert_batch_rate = n_batch / (time.perf_counter() - t0)
    log(f"[ingest] storage insert_batch: {insert_batch_rate:,.0f} ev/s")

    def flat(mode):
        sweep = by_mode[mode]["sweep"]
        out = {f"single_c{c}": v["events_per_sec"] for c, v in sweep.items()}
        out.update({f"single_c{c}_p50_ms": v["p50_ms"] for c, v in sweep.items()})
        out.update({f"single_c{c}_p99_ms": v["p99_ms"] for c, v in sweep.items()})
        out["batch50"] = by_mode[mode]["batch50"]
        # legacy keys (r05 continuity)
        if 1 in sweep:
            out["single_seq"] = sweep[1]["events_per_sec"]
        if 8 in sweep:
            out["single_conc8"] = sweep[8]["events_per_sec"]
        return out

    results_on = flat("on")
    results_on["insert_batch"] = round(insert_batch_rate, 1)
    results_on["host_loop_mops"] = round(mops, 1)
    if by_mode["on"]["tele_off_sweep"] is not None:
        for c, v in by_mode["on"]["tele_off_sweep"].items():
            results_on[f"single_c{c}_telemetry_off"] = v["events_per_sec"]
            results_on[f"single_c{c}_telemetry_ratio"] = round(
                by_mode["on"]["tele_ratio"][c], 3)
    results_off = flat("off")
    results_off["host_loop_mops"] = round(mops, 1)
    results_wal = flat("wal")
    results_wal["host_loop_mops"] = round(mops, 1)

    # multi-worker bracket (ISSUE 8): same-run 1/2/4-worker topologies
    results_mw = None
    if os.environ.get("PIO_INGEST_MULTIWORKER", "1") != "0":
        mw_concs = [int(c) for c in os.environ.get(
            "PIO_INGEST_MW_WORKERS", "1,2,4").split(",") if c.strip()]
        log("[ingest] --- multi-worker bracket (front + supervised "
            "workers, WAL on) ---")
        results_mw = run_multiworker_bracket(
            mw_concs,
            conc=int(os.environ.get("PIO_INGEST_MW_CONC", "16")),
            n=int(os.environ.get("PIO_INGEST_MW_N", "3000")))
        results_mw["host_loop_mops"] = round(mops, 1)

    # compacted-scan vs JSON-re-parse (ISSUE 8 satellite)
    results_scan = run_compacted_scan_bench(
        int(os.environ.get("PIO_INGEST_SCAN_N", "60000")))

    # windowed feed vs full-log scan (ISSUE 18: event-time windows)
    results_window = run_windowed_feed_bench(
        int(os.environ.get("PIO_INGEST_WINDOW_N", "60000")),
        int(os.environ.get("PIO_INGEST_WINDOW_TAIL", "2000")))
    results_window["host_loop_mops"] = round(mops, 1)

    for conc in concs:
        on = by_mode["on"]["sweep"][conc]["events_per_sec"]
        off = by_mode["off"]["sweep"][conc]["events_per_sec"]
        wal = by_mode["wal"]["sweep"][conc]["events_per_sec"]
        log(f"[ingest] group-commit speedup x{conc}: {on / off:.2f}x "
            f"({off:,.0f} -> {on:,.0f} ev/s)")
        # the durability bill, same run: WAL-on vs plain group commit
        log(f"[ingest] WAL cost x{conc}: {wal / on:.2f}x of group-on "
            f"({on:,.0f} -> {wal:,.0f} ev/s)")

    modes = [("group_on", results_on), ("group_off", results_off),
             ("wal_on", results_wal), ("eventlog_scan", results_scan),
             ("windowed_feed", results_window)]
    if results_mw is not None:
        modes.append(("multiworker", results_mw))
    for mode, res in modes:
        for k, v in res.items():
            unit = ("ms" if k.endswith("_ms") else
                    "Mops" if k.endswith("_mops") else
                    "s" if k.endswith("_s") else
                    "x" if k.startswith("speedup") else "events/sec")
            print(json.dumps({
                "metric": f"event ingestion {mode} {k} ({backend.lower()})",
                "value": v, "unit": unit,
            }), flush=True)

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE.json")
    try:
        with open(base_path) as f:
            doc = json.load(f)
        pub = doc.setdefault("published", {})
        pub[f"measured_ingest_{backend.lower()}"] = results_on
        pub[f"measured_ingest_{backend.lower()}_nogroup"] = results_off
        pub[f"measured_ingest_{backend.lower()}_wal"] = results_wal
        pub["measured_eventlog_scan"] = results_scan
        pub["measured_windowed_feed"] = results_window
        pub["measured_windowed_feed_note"] = (
            "cold scan_columnar over one JSONL log: 3 sealed generations "
            "(Jan/Mar/May 2026) + fresh tail; window arms skip disjoint "
            "generations by manifest event-time bounds (zero decode). "
            "speedup_* = median of within-round full/window ratios, "
            "interleaved rounds; normalize across hosts by host_loop_mops")
        if results_mw is not None:
            pub["measured_ingest_multiworker"] = results_mw
        with open(base_path, "w") as f:
            json.dump(doc, f, indent=2)
    except Exception as e:  # noqa: BLE001
        log(f"[ingest] could not persist: {e}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
