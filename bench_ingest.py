"""Benchmark: Event Server ingestion throughput (events/sec).

The reference's ★ ingestion hot path (SURVEY.md §3.3: POST /events.json
→ auth → validate → HBase Put). This drives the REAL event server over
HTTP — access-key auth, JSON validation, reserved-event rules, storage
write — measuring:

- single-event POSTs (the SDK default), sequential and concurrent
- /batch/events.json at the wire cap (50 events/request)
- bulk import path (`pio import`-equivalent insert_batch) for contrast

against the JSONL event log (the training-fast-path store of record)
by default; PIO_INGEST_BACKEND=SQLITE|MEMORY switches.

Prints ONE JSON line per mode; persists under
BASELINE.json.published.measured_ingest_*. No accelerator involved —
ingestion is a host path, so numbers are valid from any box.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests"))
    import requests
    from server_utils import ServerThread

    from incubator_predictionio_tpu.data.api.event_server import EventServer
    from incubator_predictionio_tpu.data.storage import Storage
    from incubator_predictionio_tpu.data.storage.base import AccessKey, App

    backend = os.environ.get("PIO_INGEST_BACKEND", "JSONL").upper()
    n_single = int(os.environ.get("PIO_INGEST_N_SINGLE", "2000"))
    n_batch = int(os.environ.get("PIO_INGEST_N_BATCH", "40000"))
    tmp = tempfile.mkdtemp(prefix="pio_ingest_")
    env = {
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
        "PIO_STORAGE_SOURCES_M_TYPE": "MEMORY",
        "PIO_STORAGE_SOURCES_EV_TYPE": backend,
        "PIO_STORAGE_SOURCES_EV_PATH": os.path.join(tmp, "events"),
    }
    if backend == "MEMORY":
        env["PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE"] = "M"
    storage = Storage(env)
    storage.get_meta_data_apps().insert(App(0, "ingest"))
    storage.get_meta_data_access_keys().insert(AccessKey("k1", 1, ()))

    def ev(k):
        # deterministic per-index (thread-safe: no shared RNG state)
        return {"event": "view", "entityType": "user",
                "entityId": str((k * 7919) % 10000),
                "targetEntityType": "item",
                "targetEntityId": str((k * 104729) % 2000),
                "eventTime": "2026-01-01T00:00:00.000Z"}

    import socket

    class HttpClient:
        """Minimal keep-alive HTTP/1.1 client. `requests` costs ~1 ms of
        CLIENT-side Python per call; on this 1-core host client and
        server share the core, so the old numbers measured mostly the
        client (a no-op aiohttp route serves ~11k req/s through a raw
        socket but ~1k through requests.Session). Ingestion is a SERVER
        benchmark — the client must be as thin as real SDK traffic from
        another box."""

        def __init__(self, base_url):
            host, port = base_url.replace("http://", "").split(":")
            self.sock = socket.create_connection((host, int(port)))
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.buf = b""

        def post(self, path, obj) -> int:
            body = json.dumps(obj).encode()
            self.sock.sendall(
                (f"POST {path} HTTP/1.1\r\nHost: b\r\n"
                 f"Content-Type: application/json\r\n"
                 f"Content-Length: {len(body)}\r\n\r\n").encode() + body)

            def recv():
                chunk = self.sock.recv(65536)
                if not chunk:  # server closed: fail, don't spin forever
                    raise ConnectionError("server closed connection")
                return chunk

            while b"\r\n\r\n" not in self.buf:
                self.buf += recv()
            head, rest = self.buf.split(b"\r\n\r\n", 1)
            status = int(head.split(None, 2)[1])
            clen = 0
            for line in head.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    clen = int(line.split(b":", 1)[1])
            while len(rest) < clen:
                rest += recv()
            self.buf = rest[clen:]
            return status

        def close(self):
            self.sock.close()

    results = {}
    with ServerThread(EventServer(storage).app) as st:
        base = "/events.json?accessKey=k1"
        bbase = "/batch/events.json?accessKey=k1"
        cli = HttpClient(st.base)
        assert cli.post(base, ev(0)) == 201

        t0 = time.perf_counter()
        ok = sum(cli.post(base, ev(k)) == 201 for k in range(n_single))
        dt = time.perf_counter() - t0
        assert ok == n_single, f"{n_single - ok} single POSTs failed"
        results["single_seq"] = ok / dt
        log(f"[ingest] single sequential: {ok / dt:,.0f} ev/s")

        import concurrent.futures

        per_worker = n_single // 8

        def worker(w):
            c = HttpClient(st.base)
            try:
                return sum(c.post(base, ev(w * per_worker + j)) == 201
                           for j in range(per_worker))
            finally:
                c.close()

        t0 = time.perf_counter()
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            ok = sum(pool.map(worker, range(8)))
        dt = time.perf_counter() - t0
        assert ok == per_worker * 8, f"{per_worker * 8 - ok} failed"
        results["single_conc8"] = ok / dt
        log(f"[ingest] single x8 concurrent: {ok / dt:,.0f} ev/s")

        n_reqs = max(n_batch // 50, 1)
        batches = [[ev(b * 50 + j) for j in range(50)]
                   for b in range(n_reqs)]
        t0 = time.perf_counter()
        ok = sum(cli.post(bbase, b) == 200 for b in batches)
        dt = time.perf_counter() - t0
        assert ok == n_reqs, f"{n_reqs - ok} batch POSTs failed"
        sent = n_reqs * 50
        results["batch50"] = sent / dt
        log(f"[ingest] batch/events.json (50/req): {sent / dt:,.0f} ev/s")
        cli.close()

    from incubator_predictionio_tpu.data.storage.event import Event

    le = storage.get_l_events()
    evs = [Event.from_json({**ev(0), "eventTime": "2026-01-01T00:00:00.000Z"})
           for _ in range(n_batch)]
    t0 = time.perf_counter()
    le.insert_batch(evs, 1)
    dt = time.perf_counter() - t0
    results["insert_batch"] = n_batch / dt
    log(f"[ingest] storage insert_batch: {n_batch / dt:,.0f} ev/s")

    for mode, v in results.items():
        print(json.dumps({
            "metric": f"event ingestion {mode} ({backend.lower()})",
            "value": round(v, 1), "unit": "events/sec",
        }), flush=True)

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BASELINE.json")
    try:
        with open(base_path) as f:
            doc = json.load(f)
        doc.setdefault("published", {})[
            f"measured_ingest_{backend.lower()}"] = {
                k: round(v, 1) for k, v in results.items()}
        with open(base_path, "w") as f:
            json.dump(doc, f, indent=2)
    except Exception as e:  # noqa: BLE001
        log(f"[ingest] could not persist: {e}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
