"""Vanilla engine template — the third-party authorship scaffold.

This file lives INSIDE the template project (not the framework): `pio
train --engine-dir <here>` puts this directory on sys.path and resolves
``engine.json``'s ``"engineFactory": "vanilla_engine.VanillaEngine"``
reflectively, exactly how the reference loads a user's engine jar from a
template checkout (reference: upstream template-scala-parallel-vanilla +
core CreateWorkflow engine loading; SURVEY.md §2.8).

Copy it (`pio template get vanilla <dir>`), rename, and replace the three
components. Everything imports only the public framework API —
``incubator_predictionio_tpu.controller`` and the event stores — never
``incubator_predictionio_tpu.models``.

The demo engine is a weighted-popularity recommender: every view/rate/buy
event contributes to an item score (rates weighted by their rating), the
reduction runs as a jitted segment-sum on the accelerator, and serving
returns the top-N items. Wire format matches the recommendation
quickstart: {"user": ..., "num": N} → {"itemScores": [...]}.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from incubator_predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    OptionAverageMetric,
    Params,
    SanityCheck,
    Serving,
)
from incubator_predictionio_tpu.data.store.p_event_store import PEventStore


@dataclasses.dataclass
class TrainingData(SanityCheck):
    user_idx: np.ndarray
    item_idx: np.ndarray
    weight: np.ndarray
    items: object  # BiMap item id ↔ dense index

    def sanity_check(self):
        assert len(self.item_idx) > 0, "no events found for training"


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = ""
    event_names: Sequence[str] = ("view", "rate", "buy")


class VanillaDataSource(DataSource):
    params_cls = DataSourceParams
    params_aliases = {"appName": "app_name", "eventNames": "event_names"}

    def read_training(self, ctx) -> TrainingData:
        p: DataSourceParams = self.params
        u, i, r, _users, items = PEventStore.find_ratings(
            p.app_name or ctx.app_name,
            event_names=list(p.event_names),
            default_rating=1.0,  # view/buy events carry no rating
            storage=ctx.get_storage(),
            channel_name=ctx.channel_name,
        )
        return TrainingData(u, i, r, items)

    def read_eval(self, ctx):
        """K-fold split for `pio eval` — the scaffold ships the whole
        authorship surface, evaluation included: each held-out event's
        item is the relevance label for a plain top-N query."""
        from incubator_predictionio_tpu.e2.cross_validation import (
            k_fold_indices,
        )

        td = self.read_training(ctx)
        folds = []
        for train_sel, test_sel in k_fold_indices(
                len(td.item_idx), k=3, seed=0):
            train = TrainingData(
                td.user_idx[train_sel], td.item_idx[train_sel],
                td.weight[train_sel], td.items)
            queries = [
                ({"num": 10},
                 {"item": td.items.inverse(int(td.item_idx[j]))})
                for j in np.nonzero(test_sel)[0]
            ]
            folds.append((train, None, queries))
        return folds


@dataclasses.dataclass
class PopularityModel:
    item_ids: list
    scores: np.ndarray  # [n_items] f32, aligned with item_ids

    def top(self, num: int):
        order = np.argsort(-self.scores)[:num]
        return [(self.item_ids[int(j)], float(self.scores[int(j)]))
                for j in order]


@dataclasses.dataclass(frozen=True)
class AlgorithmParams(Params):
    rating_weight: float = 1.0


class PopularityAlgorithm(Algorithm):
    params_cls = AlgorithmParams
    params_aliases = {"ratingWeight": "rating_weight"}

    def train(self, ctx, td: TrainingData) -> PopularityModel:
        import jax
        import jax.numpy as jnp

        n_items = len(td.items)
        w = self.params.rating_weight

        @jax.jit
        def score(item_idx, weight):
            return jax.ops.segment_sum(
                weight * w, item_idx, num_segments=n_items)

        scores = np.asarray(score(jnp.asarray(td.item_idx),
                                  jnp.asarray(td.weight)))
        item_ids = [td.items.inverse(j) for j in range(n_items)]
        return PopularityModel(item_ids=item_ids, scores=scores)

    def predict(self, model: PopularityModel, query: dict) -> dict:
        num = int(query.get("num", 10))
        return {
            "itemScores": [
                {"item": item, "score": score}
                for item, score in model.top(num)
            ]
        }

    def prepare_model_for_persistence(self, model: PopularityModel):
        return {"item_ids": model.item_ids,
                "scores": np.asarray(model.scores)}

    def restore_model(self, stored, ctx) -> PopularityModel:
        if isinstance(stored, PopularityModel):
            return stored
        return PopularityModel(item_ids=list(stored["item_ids"]),
                               scores=np.asarray(stored["scores"]))


class VanillaServing(Serving):
    def serve(self, query: dict, predictions: Sequence[dict]) -> dict:
        return predictions[0] if predictions else {"itemScores": []}


class VanillaEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            data_source_class=VanillaDataSource,
            algorithm_class_map={"popularity": PopularityAlgorithm},
            serving_class=VanillaServing,
        )


# -- evaluation (`pio eval vanilla_engine.VanillaEvaluation
#    vanilla_engine.ParamsList --engine-dir <here>`) ----------------------
#
# The metric kernel is the continuous quality evaluator's
# (incubator_predictionio_tpu.ops.eval) — the leaderboard number is
# directly comparable to the live pio_engine_quality_metric gauge.

class NDCGAtK(OptionAverageMetric):
    def __init__(self, k: int = 10):
        self.k = k

    def header(self) -> str:
        return f"NDCG@{self.k}"

    def calculate_unit(self, q, p, a):
        from incubator_predictionio_tpu.ops import eval as evalops

        items = [str(s["item"]) for s in p.get("itemScores", [])]
        if not items or a.get("item") is None:
            return None
        m = evalops.ranking_metrics([items], [{str(a["item"])}], self.k)
        return float(m["ndcg"]) if m["n"] else None


class VanillaEvaluation(Evaluation):
    def __init__(self):
        self.engine = VanillaEngine()()
        self.metric = NDCGAtK(k=10)
        self.metrics = (NDCGAtK(k=5),)


class ParamsList(EngineParamsGenerator):
    """ratingWeight sweep: how much a rating outweighs a view/buy."""

    def __init__(self, app_name: str = ""):
        ds = {"params": ({"appName": app_name} if app_name else {})}
        self.engine_params_list = [
            EngineParams.from_json({
                "datasource": ds,
                "algorithms": [{"name": "popularity",
                                "params": {"ratingWeight": w}}],
            })
            for w in (0.5, 1.0, 2.0)
        ]
