"""pypio — notebook/script-friendly Python facade.

Reference: python/pypio (0.13's PySpark bridge: pypio.init(), new_app,
find_events→DataFrame, save/deploy helpers driven from Jupyter). Here the
whole framework is already Python, so the bridge is a thin convenience
layer: one import that wires storage from the environment and exposes the
common lifecycle verbs as functions returning plain numpy/columnar data
instead of Spark DataFrames.
"""

from __future__ import annotations

import datetime as _dt
import json
from typing import Optional, Sequence

from ..data.storage.base import AccessKey as _AccessKey
from ..data.storage.base import App as _App
from ..data.storage.registry import Storage
from ..data.store.p_event_store import EventBatch, PEventStore

_storage: Optional[Storage] = None


def init(storage: Optional[Storage] = None) -> Storage:
    """Initialise the bridge (reference: pypio.init_pypio). Idempotent;
    returns the bound Storage."""
    global _storage
    _storage = storage or Storage.instance()
    return _storage


def _require_storage() -> Storage:
    if _storage is None:
        raise RuntimeError("call pypio.init() first")
    return _storage


def new_app(name: str, access_key: str = "", description: Optional[str] = None):
    """Create an app + access key; returns (app_id, access_key)."""
    s = _require_storage()
    apps = s.get_meta_data_apps()
    app_id = apps.insert(_App(0, name, description))
    if app_id is None:
        raise ValueError(f"App {name!r} already exists")
    s.get_l_events().init(app_id)
    key = s.get_meta_data_access_keys().insert(_AccessKey(access_key, app_id, ()))
    if key is None:
        apps.delete(app_id)
        raise ValueError(f"Access key {access_key!r} already exists")
    return app_id, key


def delete_app(name: str) -> None:
    s = _require_storage()
    apps = s.get_meta_data_apps()
    app = apps.get_by_name(name)
    if app is None:
        raise ValueError(f"App {name!r} does not exist")
    for k in s.get_meta_data_access_keys().get_by_appid(app.id):
        s.get_meta_data_access_keys().delete(k.key)
    s.get_l_events().remove(app.id)
    apps.delete(app.id)


def import_events(app_name: str, jsonl_path: str) -> int:
    """Bulk-load a JSONL export into an app; returns events inserted."""
    s = _require_storage()
    from ..data.storage.event import Event

    app = s.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise ValueError(f"App {app_name!r} does not exist")
    le = s.get_l_events()
    n = 0
    with open(jsonl_path) as f:
        batch = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            batch.append(Event.from_json(json.loads(line)))
            if len(batch) >= 1000:
                le.insert_batch(batch, app.id)
                n += len(batch)
                batch = []
        if batch:
            le.insert_batch(batch, app.id)
            n += len(batch)
    return n


def find_events(
    app_name: str,
    event_names: Optional[Sequence[str]] = None,
    start_time: Optional[_dt.datetime] = None,
    until_time: Optional[_dt.datetime] = None,
) -> EventBatch:
    """Columnar scan of an app's events (reference: pypio.data.find_events
    returning a DataFrame — here an EventBatch of numpy columns)."""
    _require_storage()
    return PEventStore.find_batch(
        app_name, event_names=event_names, storage=_storage,
        start_time=start_time, until_time=until_time,
    )


def find_ratings(app_name: str, event_names: Optional[Sequence[str]] = None, **kwargs):
    """(user_idx, item_idx, rating, user_map, item_map) COO triple — the
    same code path the training workflow uses (columnar fast path on
    JSONL-backed event stores). kwargs pass through to
    PEventStore.find_ratings (channel_name, event_default_ratings, ...)."""
    return PEventStore.find_ratings(
        app_name, event_names=event_names, storage=_require_storage(), **kwargs
    )


def train(engine_dir: str, variant: Optional[str] = None) -> str:
    """Run the training workflow for a template directory; returns the
    engine-instance id (reference: `pio train`)."""
    import os

    from ..workflow.context import WorkflowContext
    from ..workflow.core_workflow import run_train
    from ..workflow.json_extractor import (
        engine_and_params_from_json,
        load_engine_json,
    )
    from ..workflow.workflow_params import WorkflowParams

    s = _require_storage()
    engine_json = load_engine_json(os.path.join(engine_dir, "engine.json"), variant)
    engine, params, factory = engine_and_params_from_json(engine_json, engine_dir)
    app_name = (
        dict(params.data_source_params).get("app_name")
        or dict(params.data_source_params).get("appName", "")
    )
    ctx = WorkflowContext(app_name=app_name, storage=s)
    return run_train(
        engine, params, ctx, WorkflowParams(),
        engine_factory_name=factory,
        engine_variant=engine_json.get("id", "default"),
    )
