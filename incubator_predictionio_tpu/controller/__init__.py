"""DASE controller API — the public engine-developer surface.

Re-design of the reference controller layer (reference:
core/src/main/scala/org/apache/predictionio/controller/). The DASE mental
model is preserved verbatim — DataSource → Preparator → Algorithm(s) →
Serving, plus Evaluation — but components produce arrays/pytrees instead of
RDDs, and "distributed" is expressed through jax.sharding on a device mesh
rather than through a P/L class split.

API-parity notes:
- `PDataSource`/`LDataSource`, `PPreparator`/`LPreparator`,
  `PAlgorithm`/`P2LAlgorithm`/`LAlgorithm` are provided as aliases of the
  unified base classes. In the reference the trichotomy encodes *where*
  data lives (RDD vs driver); on a TPU mesh every array is a jax.Array
  whose sharding annotation carries that information instead
  (reference: controller/{PAlgorithm,P2LAlgorithm,LAlgorithm}.scala).
"""

from .base import (
    AbstractDoer,
    CustomQuerySerializer,
    EmptyParams,
    Params,
    SanityCheck,
    doer,
    params_from_dict,
    params_to_dict,
)
from .datasource import DataSource, LDataSource, PDataSource
from .preparator import (
    IdentityPreparator,
    LPreparator,
    PIdentityPreparator,
    PPreparator,
    Preparator,
)
from .algorithm import Algorithm, LAlgorithm, P2LAlgorithm, PAlgorithm
from .serving import AverageServing, FirstServing, LServing, Serving
from .engine import Engine, EngineFactory, EngineParams, SimpleEngine
from .evaluation import Evaluation, EngineParamsGenerator
from .metric import (
    AverageMetric,
    Metric,
    OptionAverageMetric,
    SumMetric,
    ZeroMetric,
)
from .metric_evaluator import MetricEvaluator, MetricEvaluatorResult
from .persistent_model import (
    LocalFileSystemPersistentModel,
    PersistentModel,
    PersistentModelLoader,
)

__all__ = [
    "AbstractDoer", "Algorithm", "AverageMetric", "AverageServing",
    "CustomQuerySerializer", "DataSource", "EmptyParams", "Engine",
    "EngineFactory", "EngineParams", "EngineParamsGenerator", "Evaluation",
    "FirstServing", "IdentityPreparator", "LAlgorithm", "LDataSource",
    "LPreparator", "LServing", "LocalFileSystemPersistentModel", "Metric",
    "MetricEvaluator", "MetricEvaluatorResult", "OptionAverageMetric",
    "P2LAlgorithm", "PAlgorithm", "PDataSource", "PIdentityPreparator",
    "PPreparator", "Params", "PersistentModel", "PersistentModelLoader",
    "Preparator", "SanityCheck", "Serving", "SimpleEngine", "SumMetric",
    "ZeroMetric", "doer", "params_from_dict", "params_to_dict",
]
