"""Serving — combine per-algorithm predictions into one result.

Reference: core/.../controller/{LServing,FirstServing,LAverageServing}.scala.
"""

from __future__ import annotations

from typing import Generic, Sequence, TypeVar

from .base import AbstractDoer

Q = TypeVar("Q")
P = TypeVar("P")


class Serving(AbstractDoer, Generic[Q, P]):
    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        raise NotImplementedError

    def supplement(self, query: Q) -> Q:
        """Pre-predict query enrichment hook (reference:
        LServing.supplement — e.g. inject serve-time context)."""
        return query


class FirstServing(Serving):
    """Reference: FirstServing — single-algorithm passthrough."""

    def serve(self, query, predictions):
        return predictions[0]


class AverageServing(Serving):
    """Reference: LAverageServing — numeric mean of predictions."""

    def serve(self, query, predictions):
        return sum(predictions) / len(predictions)


LServing = Serving
