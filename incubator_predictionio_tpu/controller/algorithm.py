"""Algorithm — train a model, predict queries.

Reference: core/.../controller/{PAlgorithm,P2LAlgorithm,LAlgorithm}.scala.
The reference trichotomy (distributed-train/distributed-model,
distributed-train/local-model, local) encodes where data lives on a Spark
cluster. On a TPU mesh the model is a pytree of jax.Arrays whose shardings
carry that information, so one base class suffices; the three names are
kept as aliases so template code reads identically to upstream.

TPU-first contract:
- ``train`` should build a pjit'd/jitted step and return a model pytree.
- ``predict`` is the serving hot path: implementations should route
  through an AOT-compiled executable (see workflow/create_server.py).
- ``batch_predict`` vectorizes eval-time scoring (reference:
  batchPredict as RDD joins — here a single device sweep).
"""

from __future__ import annotations

from typing import Any, Generic, Sequence, TypeVar

from .base import AbstractDoer

PD = TypeVar("PD")
M = TypeVar("M")
Q = TypeVar("Q")
P = TypeVar("P")


class Algorithm(AbstractDoer, Generic[PD, M, Q, P]):
    def train(self, ctx, prepared_data: PD) -> M:
        raise NotImplementedError

    def predict(self, model: M, query: Q) -> P:
        raise NotImplementedError

    def batch_predict(self, model: M, queries: Sequence[Q]) -> list[P]:
        """Default: loop over predict. Override with a vectorized sweep
        for eval throughput (reference: batchPredict)."""
        return [self.predict(model, q) for q in queries]

    def fold_in(self, model: M, events, ctx, data_source_params=None):
        """Optional streaming-online-learning hook (workflow/online.py;
        docs/operations.md "Online learning"): fold a batch of NEW raw
        events — wire-format dicts tailed from the partitioned event
        log since the last increment — into a COPY of ``model``.

        Contract: never mutate ``model`` (the original keeps serving
        until the increment passes the swap validation gate); return
        the updated copy, or None when this algorithm does not support
        fold-in (the default) or the batch contains nothing it can
        apply. ``data_source_params`` is the deployed instance's
        data-source configuration (event names, entity types, feature
        attributes) so the event → example mapping matches what
        training read."""
        return None

    def stage_model(self, prepared_data: PD):
        """Optional workload description for cost-based device placement
        (`pio train --device=auto`; workflow/placement.py): return a
        placement.StageModel sizing the data this train would move and
        touch, or None to always run on the configured accelerator mesh.
        Provided by the measured transfer-bound algorithms (NB/LR over
        dense features, text TF-IDF); iterative compute-dense trainers
        (ALS, CCO) stay accelerator-pinned."""
        return None

    # -- model persistence hooks (reference: makeSerializableModels) ------
    def prepare_model_for_persistence(self, model: M) -> Any:
        """Convert device arrays → host (numpy) before pickling. Default
        uses jax.device_get on the whole pytree."""
        import jax

        return jax.device_get(model)

    def restore_model(self, stored: Any, ctx) -> M:
        """Inverse of prepare_model_for_persistence; default identity —
        jax ops consume numpy arrays directly, and re-device-put happens
        lazily on first use."""
        return stored


# API-parity aliases.
PAlgorithm = Algorithm
P2LAlgorithm = Algorithm
LAlgorithm = Algorithm
