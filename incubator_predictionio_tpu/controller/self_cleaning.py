"""SelfCleaningDataSource — event-TTL compaction mixin.

Reference: core/.../core/SelfCleaningDataSource.scala: optionally ages out
events older than a TTL and compacts $set/$unset/$delete property streams
into single $set snapshots, writing the cleaned stream back to the event
store before training.
"""

from __future__ import annotations

import datetime as _dt
import json as _json
import logging
from typing import Optional

from ..data.storage.base import aggregate_property_events
from ..data.storage.datamap import DataMap
from ..data.storage.event import Event

log = logging.getLogger("pio.selfclean")


class SelfCleaningDataSource:
    """Mixin for DataSources. Configure via attributes (reference trait
    members): ``event_window_duration`` (timedelta or None = keep all),
    ``event_window_remove`` (actually delete old events), and call
    ``clean_persisted_data(ctx, app_name)`` at the top of read_training.
    """

    event_window_duration: Optional[_dt.timedelta] = None
    event_window_remove: bool = False
    # Content-dedupe (reference: cleanPersistedPEvents' .distinct()):
    # repeated imports create identical events under fresh eventIds; the
    # cleaning pass keeps the first copy per content key.
    event_dedupe: bool = True

    def clean_persisted_data(self, ctx, app_name: str) -> int:
        """Compact property events + drop aged-out events. Returns the
        number of events removed."""
        storage = ctx.get_storage()
        app = storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            raise ValueError(f"App {app_name!r} does not exist")
        le = storage.get_l_events()
        removed = 0

        cutoff = None
        if self.event_window_duration is not None:
            cutoff = _dt.datetime.now(_dt.timezone.utc) - self.event_window_duration

        # 1) age out old non-property events
        if cutoff is not None and self.event_window_remove:
            doomed = [e.event_id for e in le.find(app.id, until_time=cutoff)
                      if e.event not in ("$set", "$unset", "$delete")]
            # Count what delete_batch actually deleted, not what we asked
            # for — a concurrent writer may have removed some ids already.
            removed += sum(le.delete_batch(doomed, app.id))

        # 2) content-dedupe: events identical in EVERY user-visible field
        # (incl. tags/prId — two conversions differing only in prediction
        # attribution are NOT duplicates) collapse to the first copy in
        # store order — the reference's RDD .distinct() for re-imported
        # data. Full-scan is inherent to dedupe (so is the reference's);
        # memory per unique event is a 16-byte digest, not the event.
        if self.event_dedupe:
            import hashlib

            seen: set[bytes] = set()
            dupes = []
            for e in le.find(app.id):
                key = _json.dumps(
                    [e.event, e.entity_type, e.entity_id,
                     e.target_entity_type, e.target_entity_id,
                     e.properties.to_dict(), sorted(e.tags or ()),
                     e.pr_id, e.event_time],
                    sort_keys=True, default=str).encode()
                digest = hashlib.blake2b(key, digest_size=16).digest()
                if digest in seen:
                    dupes.append(e.event_id)
                else:
                    seen.add(digest)
            removed += sum(le.delete_batch(dupes, app.id))

        # 3) compact property-event streams per entity type into one $set
        prop_events = list(
            le.find(app.id, event_names=["$set", "$unset", "$delete"])
        )
        by_type: dict[str, list[Event]] = {}
        for e in prop_events:
            by_type.setdefault(e.entity_type, []).append(e)
        for entity_type, events in by_type.items():
            if len(events) <= len({e.entity_id for e in events}):
                continue  # nothing to compact
            snapshot = aggregate_property_events(events)
            removed += sum(
                le.delete_batch([e.event_id for e in events], app.id))
            for entity_id, pm in snapshot.items():
                le.insert(
                    Event(
                        "$set", entity_type, entity_id,
                        properties=DataMap(pm.to_dict()),
                        event_time=pm.last_updated,
                    ),
                    app.id,
                )
                removed -= 1
        # A concurrent deleter racing the compaction pass can make
        # deletions < insertions; net "removed" is then 0, not negative.
        removed = max(removed, 0)
        if removed:
            log.info("self-cleaning removed %d events", removed)
        return removed
