"""Preparator — transforms TrainingData into PreparedData.

Reference: core/.../controller/{PPreparator,LPreparator,
IdentityPreparator}.scala. The TPU-first role of prepare() is to build
device-ready arrays: dense index mappings (BiMap), padded/blocked COO
layouts, sharded jax.Arrays over the workflow mesh.
"""

from __future__ import annotations

from typing import Generic, TypeVar

from .base import AbstractDoer

TD = TypeVar("TD")
PD = TypeVar("PD")


class Preparator(AbstractDoer, Generic[TD, PD]):
    def prepare(self, ctx, training_data: TD) -> PD:
        raise NotImplementedError


class IdentityPreparator(Preparator):
    """Pass-through (reference: IdentityPreparator/PIdentityPreparator)."""

    def prepare(self, ctx, training_data):
        return training_data


# API-parity aliases.
PPreparator = Preparator
LPreparator = Preparator
PIdentityPreparator = IdentityPreparator
