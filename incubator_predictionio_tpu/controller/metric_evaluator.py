"""MetricEvaluator — rank candidate EngineParams by metric score.

Reference: core/.../controller/MetricEvaluator.scala (pretty-printed
leaderboard + best-params JSON ready to paste into engine.json).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional, Sequence

from .engine import EngineParams
from .metric import Metric


@dataclasses.dataclass
class MetricEvaluatorResult:
    best_score: float
    best_engine_params: EngineParams
    best_index: int
    metric_header: str
    other_metric_headers: Sequence[str]
    all_results: Sequence[tuple[EngineParams, float, Sequence[float]]]

    def to_json(self) -> str:
        return json.dumps(
            {
                "bestScore": self.best_score,
                "bestIndex": self.best_index,
                "metricHeader": self.metric_header,
                "bestEngineParams": self.best_engine_params.to_json(),
                "results": [
                    {"engineParams": ep.to_json(), "score": s, "others": list(o)}
                    for ep, s, o in self.all_results
                ],
            },
            indent=2,
        )

    def pretty(self) -> str:
        lines = [
            "[MetricEvaluator] candidates ranked by " + self.metric_header,
        ]
        ranked = sorted(
            enumerate(self.all_results), key=lambda t: t[1][1], reverse=True
        )
        for i, (ep, score, others) in ranked:
            mark = "★" if i == self.best_index else " "
            lines.append(f"  {mark} [{i}] {self.metric_header}={score:.6f} "
                         + " ".join(f"{h}={v:.6f}" for h, v in zip(self.other_metric_headers, others)))
        lines.append("[MetricEvaluator] best engine params:")
        lines.append(json.dumps(self.best_engine_params.to_json(), indent=2))
        return "\n".join(lines)


class MetricEvaluator:
    def __init__(self, metric: Metric, other_metrics: Sequence[Metric] = ()):
        self.metric = metric
        self.other_metrics = tuple(other_metrics)

    def evaluate_candidates(
        self, candidates: Sequence[tuple[EngineParams, Any]]
    ) -> MetricEvaluatorResult:
        """candidates: [(engine_params, eval_data)] where eval_data is the
        Engine.eval output for those params."""
        results = []
        for ep, eval_data in candidates:
            eval_data = list(eval_data)
            score = self.metric.calculate(eval_data)
            others = [m.calculate(eval_data) for m in self.other_metrics]
            results.append((ep, score, others))
        best_index = 0
        for i, (_, score, _) in enumerate(results):
            if self.metric.compare(score, results[best_index][1]) > 0:
                best_index = i
        best = results[best_index]
        return MetricEvaluatorResult(
            best_score=best[1],
            best_engine_params=best[0],
            best_index=best_index,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            all_results=results,
        )
