"""Metric hierarchy for evaluation (reference:
core/.../controller/Metric.scala — AverageMetric, OptionAverageMetric,
SumMetric, ZeroMetric; RDD means become vectorized host reductions)."""

from __future__ import annotations

import math
from typing import Any, Generic, Iterable, Optional, Tuple, TypeVar

EI = TypeVar("EI")
Q = TypeVar("Q")
P = TypeVar("P")
A = TypeVar("A")


class Metric(Generic[EI, Q, P, A]):
    """calculate() consumes the eval output: iterable of
    (eval_info, [(query, predicted, actual), ...]) folds."""

    #: larger-is-better by default (reference: Metric.comparator)
    higher_is_better: bool = True

    def header(self) -> str:
        return type(self).__name__

    def calculate(self, eval_data: Iterable[Tuple[EI, list]]) -> float:
        raise NotImplementedError

    def compare(self, a: float, b: float) -> int:
        if a == b:
            return 0
        better = a > b if self.higher_is_better else a < b
        return 1 if better else -1


class AverageMetric(Metric):
    """Mean of per-(q,p,a) scores over all folds (reference: AverageMetric)."""

    def calculate_unit(self, q, p, a) -> float:
        raise NotImplementedError

    def calculate(self, eval_data) -> float:
        total, n = 0.0, 0
        for _info, qpa in eval_data:
            for q, p, a in qpa:
                total += self.calculate_unit(q, p, a)
                n += 1
        return total / n if n else float("nan")


class OptionAverageMetric(AverageMetric):
    """Mean over units that return a value; None units are excluded
    (reference: OptionAverageMetric)."""

    def calculate_unit(self, q, p, a) -> Optional[float]:  # type: ignore[override]
        raise NotImplementedError

    def calculate(self, eval_data) -> float:
        total, n = 0.0, 0
        for _info, qpa in eval_data:
            for q, p, a in qpa:
                u = self.calculate_unit(q, p, a)
                if u is not None:
                    total += u
                    n += 1
        return total / n if n else float("nan")


class SumMetric(Metric):
    """Sum of per-unit scores (reference: SumMetric)."""

    def calculate_unit(self, q, p, a) -> float:
        raise NotImplementedError

    def calculate(self, eval_data) -> float:
        return sum(
            self.calculate_unit(q, p, a)
            for _info, qpa in eval_data
            for q, p, a in qpa
        )


class ZeroMetric(Metric):
    """Always 0 (reference: ZeroMetric — placeholder for side-effect-only
    evaluations)."""

    def calculate(self, eval_data) -> float:
        return 0.0
