"""DataSource — reads training/eval data from the event store.

Reference: core/.../controller/{PDataSource,LDataSource}.scala. The
reference returns RDD[TrainingData]; here TrainingData is whatever the
engine defines — typically a columnar batch of numpy arrays produced via
data.store.PEventStore, ready for device sharding.
"""

from __future__ import annotations

from typing import Any, Generic, Iterable, Sequence, Tuple, TypeVar

from .base import AbstractDoer

TD = TypeVar("TD")  # TrainingData
EI = TypeVar("EI")  # EvaluationInfo
Q = TypeVar("Q")  # Query
A = TypeVar("A")  # Actual result


class DataSource(AbstractDoer, Generic[TD, EI, Q, A]):
    """Unified DataSource. ``read_training`` feeds `pio train`;
    ``read_eval`` yields (trainingData, evalInfo, [(query, actual)]) folds
    for `pio eval` (reference: PDataSource.readTraining/readEval)."""

    def read_training(self, ctx) -> TD:
        raise NotImplementedError

    def read_eval(self, ctx) -> Sequence[Tuple[TD, EI, Iterable[Tuple[Q, A]]]]:
        """Default: no eval folds (reference: readEval default = empty)."""
        return []


# API-parity aliases (see controller/__init__ docstring).
PDataSource = DataSource
LDataSource = DataSource
