"""Params, Doer instantiation, and cross-cutting controller contracts.

Reference: core/.../controller/{Params,EmptyParams,SanityCheck,
CustomQuerySerializer}.scala and core/.../core/{AbstractDoer,Doer}.scala.
The reference instantiates user classes reflectively with a Params case
class; here ``doer`` constructs the class with keyword arguments extracted
from engine.json — the Python analog of JsonExtractor + Doer.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Mapping, Optional, Type, TypeVar


class Params:
    """Marker base for component parameters (reference: Params trait).

    Subclasses are usually @dataclass-es. Plain classes with keyword
    __init__ args work too.
    """


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    """Reference: EmptyParams — components that need no configuration."""


def params_from_dict(params_cls: Optional[Type], d: Mapping[str, Any]) -> Any:
    """Build a Params instance from a JSON dict (JsonExtractor analog).

    Unknown keys raise — the reference fails trains on bad engine.json keys
    rather than silently ignoring typos.
    """
    if params_cls is None:
        return EmptyParams() if not d else dict(d)
    if dataclasses.is_dataclass(params_cls):
        names = {f.name for f in dataclasses.fields(params_cls)}
        unknown = set(d) - names
        if unknown:
            raise ValueError(
                f"unknown parameter(s) {sorted(unknown)} for {params_cls.__name__};"
                f" expected a subset of {sorted(names)}"
            )
        return params_cls(**d)
    sig = inspect.signature(params_cls)
    return params_cls(**{k: v for k, v in d.items() if k in sig.parameters})


def params_to_dict(p: Any) -> dict[str, Any]:
    if p is None:
        return {}
    if dataclasses.is_dataclass(p):
        return dataclasses.asdict(p)
    if isinstance(p, Mapping):
        return dict(p)
    return {k: v for k, v in vars(p).items() if not k.startswith("_")}


class AbstractDoer:
    """Base for all DASE components (reference: AbstractDoer — holds the
    Params it was constructed with)."""

    params_cls: Optional[Type] = None  # set by subclasses for extraction

    def __init__(self, params: Any = None):
        self.params = params if params is not None else EmptyParams()


T = TypeVar("T", bound=AbstractDoer)


def doer(cls: Type[T], params_json: Optional[Mapping[str, Any]] = None) -> T:
    """Instantiate a DASE component from its JSON params
    (reference: Doer.apply — reflective construction with Params).

    ``cls.params_aliases`` maps engine.json spellings onto Params field
    names (e.g. {"lambda": "reg", "numIterations": "num_iterations"}) so
    reference engine.json files work verbatim."""
    params_cls = getattr(cls, "params_cls", None)
    if params_json is None:
        params_json = {}
    aliases = getattr(cls, "params_aliases", None)
    if aliases and isinstance(params_json, Mapping):
        params_json = {aliases.get(k, k): v for k, v in params_json.items()}
    if params_cls is not None:
        return cls(params_from_dict(params_cls, params_json))
    # No declared params class: pass the raw dict (or nothing).
    try:
        return cls(dict(params_json)) if params_json else cls()
    except TypeError:
        return cls()


class SanityCheck:
    """Post-stage data asserts (reference: controller/SanityCheck.scala —
    run after each DASE stage unless --skip-sanity-check)."""

    def sanity_check(self) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class CustomQuerySerializer:
    """Hook to override query/result JSON codecs (reference:
    controller/CustomQuerySerializer.scala). Components may provide
    ``query_from_json`` / ``result_to_json``."""

    def query_from_json(self, obj: Mapping[str, Any]) -> Any:
        return obj

    def result_to_json(self, result: Any) -> Any:
        return result
