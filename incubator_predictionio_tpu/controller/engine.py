"""Engine — binds DASE component classes with their parameters.

Reference: core/.../controller/Engine.scala (class maps + train/eval
composition), EngineParams, SimpleEngine, EngineFactory.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Mapping, Optional, Sequence, Type

from ..common import deadline, faultinject, telemetry
from .algorithm import Algorithm
from .base import SanityCheck, doer
from .datasource import DataSource
from .preparator import IdentityPreparator, Preparator
from .serving import FirstServing, Serving

log = logging.getLogger("pio.engine")

# Per-query serving-stage latency (featurize = Serving.supplement query
# massage, predict = every algorithm's device dispatch, serve = result
# blend). Children pre-bound at import so the hot path pays one dict-get
# nothing, just an observe. The batched path records the same stages
# once per coalesced batch under batched="1".
_STAGE_SECONDS = telemetry.registry().histogram(
    "pio_query_stage_seconds",
    "Per-query serving stage latency by stage "
    "(featurize/predict/serve); batched=1 rows are one observation "
    "per micro-batch dispatch",
    ("stage", "batched"))
_ST_FEATURIZE = _STAGE_SECONDS.labels("featurize", "0")
_ST_PREDICT = _STAGE_SECONDS.labels("predict", "0")
_ST_SERVE = _STAGE_SECONDS.labels("serve", "0")
_ST_FEATURIZE_B = _STAGE_SECONDS.labels("featurize", "1")
_ST_PREDICT_B = _STAGE_SECONDS.labels("predict", "1")
_ST_SERVE_B = _STAGE_SECONDS.labels("serve", "1")


def _as_class_map(spec) -> dict[str, Type]:
    """Accept a single class or a {name: class} map (reference: Engine
    constructors take either; single class registers under "")."""
    if spec is None:
        return {}
    if isinstance(spec, Mapping):
        return dict(spec)
    return {"": spec}


@dataclasses.dataclass
class EngineParams:
    """Per-component parameter selection (reference: EngineParams).

    ``algorithm_params_list`` is a list of (name, params_dict) pairs —
    multiple algorithms blend through Serving.
    """

    data_source_params: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    preparator_params: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    algorithm_params_list: Sequence[tuple[str, Mapping[str, Any]]] = dataclasses.field(
        default_factory=list
    )
    serving_params: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    data_source_name: str = ""
    preparator_name: str = ""
    serving_name: str = ""

    @staticmethod
    def from_json(obj: Mapping[str, Any]) -> "EngineParams":
        """Parse the engine.json "params-style" dict:
        {"datasource": {"params": {...}}, "algorithms": [{"name": ...,
        "params": {...}}], ...} (reference: WorkflowUtils.getParamsFromJsonByFieldAndClass)."""

        def unwrap(block):
            if block is None:
                return "", {}
            if "params" in block or "name" in block:
                return block.get("name", ""), block.get("params", {}) or {}
            return "", block

        ds_name, ds_params = unwrap(obj.get("datasource"))
        p_name, p_params = unwrap(obj.get("preparator"))
        s_name, s_params = unwrap(obj.get("serving"))
        algos = []
        for a in obj.get("algorithms", []) or []:
            algos.append((a.get("name", ""), a.get("params", {}) or {}))
        return EngineParams(
            data_source_params=ds_params,
            preparator_params=p_params,
            algorithm_params_list=algos,
            serving_params=s_params,
            data_source_name=ds_name,
            preparator_name=p_name,
            serving_name=s_name,
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "datasource": {"name": self.data_source_name, "params": dict(self.data_source_params)},
            "preparator": {"name": self.preparator_name, "params": dict(self.preparator_params)},
            "algorithms": [
                {"name": n, "params": dict(p)} for n, p in self.algorithm_params_list
            ],
            "serving": {"name": self.serving_name, "params": dict(self.serving_params)},
        }


class Engine:
    """Reference: controller/Engine.scala. Composes DASE for train/eval."""

    def __init__(
        self,
        data_source_class,
        preparator_class=None,
        algorithm_class_map=None,
        serving_class=None,
    ):
        self.data_source_class_map = _as_class_map(data_source_class)
        self.preparator_class_map = _as_class_map(preparator_class or IdentityPreparator)
        self.algorithm_class_map = _as_class_map(algorithm_class_map)
        self.serving_class_map = _as_class_map(serving_class or FirstServing)

    # -- component instantiation -----------------------------------------
    def _pick(self, class_map: dict[str, Type], name: str, what: str) -> Type:
        if name in class_map:
            return class_map[name]
        if not name and len(class_map) == 1:
            return next(iter(class_map.values()))
        raise KeyError(
            f"{what} {name!r} not registered; available: {sorted(class_map)}"
        )

    def make_components(self, engine_params: EngineParams):
        ds = doer(
            self._pick(self.data_source_class_map, engine_params.data_source_name, "datasource"),
            engine_params.data_source_params,
        )
        prep = doer(
            self._pick(self.preparator_class_map, engine_params.preparator_name, "preparator"),
            engine_params.preparator_params,
        )
        algo_list = [
            (
                name,
                doer(self._pick(self.algorithm_class_map, name, "algorithm"), params),
            )
            for name, params in (engine_params.algorithm_params_list or [("", {})])
        ]
        serving = doer(
            self._pick(self.serving_class_map, engine_params.serving_name, "serving"),
            engine_params.serving_params,
        )
        return ds, prep, algo_list, serving

    @staticmethod
    def _maybe_sanity_check(obj, label: str, enabled: bool,
                            nan_guard: bool = False) -> None:
        if enabled and isinstance(obj, SanityCheck):
            log.info("sanity check: %s", label)
            obj.sanity_check()
        if nan_guard:
            from ..common.nan_guard import check_finite

            check_finite(obj, label)

    # -- training (reference: Engine.train) -------------------------------
    def train(self, ctx, engine_params: EngineParams, workflow_params=None) -> list[Any]:
        from ..workflow.workflow_params import WorkflowParams

        wp = workflow_params or WorkflowParams()
        # Single source of truth: algorithms read flags (nan_guard,
        # resume) from ctx.workflow_params — sync it even when callers
        # bypass run_train and invoke Engine.train directly.
        ctx.workflow_params = wp
        ds, prep, algo_list, _ = self.make_components(engine_params)

        td = ds.read_training(ctx)
        self._maybe_sanity_check(td, "datasource", not wp.skip_sanity_check,
                                 wp.nan_guard)
        if wp.stop_after_read:
            log.info("--stop-after-read: halting before prepare")
            return []
        pd = prep.prepare(ctx, td)
        self._maybe_sanity_check(pd, "preparator", not wp.skip_sanity_check,
                                 wp.nan_guard)
        if wp.stop_after_prepare:
            log.info("--stop-after-prepare: halting before train")
            return []
        models = []
        root_hook = getattr(ctx, "checkpoint_hook", None)
        if root_hook is not None:
            import os

            from ..workflow.checkpoint import CheckpointHook
        for idx, (name, algo) in enumerate(algo_list):
            log.info("training algorithm %s (%s)", name or "<default>", type(algo).__name__)
            # Stage label for error attribution inside iterative trainers
            # (e.g. train_als' per-iteration NaN guard).
            ctx.stage_label = f"algorithm[{name or 'default'}]"
            if root_hook is not None:
                # Per-algorithm subdirectory: without it, multiple
                # algorithms in one engine would collide on orbax step
                # numbers and restore each other's snapshots.
                ctx.checkpoint_hook = CheckpointHook(
                    os.path.join(root_hook.directory, f"algo_{idx}_{name or 'default'}"),
                    every_n=root_hook.every_n,
                    max_to_keep=root_hook.max_to_keep,
                )
            try:
                # cost-based placement (--device=auto): _train_placed
                # swaps the mesh for this stage and restores it on every
                # exit path (workflow/placement.py)
                model = self._train_placed(
                    ctx, algo, name, pd, getattr(wp, "device", "auto"))
            finally:
                if root_hook is not None:
                    ctx.checkpoint_hook.close()
                    ctx.checkpoint_hook = root_hook
            self._maybe_sanity_check(
                model, f"algorithm[{name or 'default'}]",
                not wp.skip_sanity_check, wp.nan_guard)
            models.append(model)
        return models

    # -- evaluation (reference: Engine.eval) ------------------------------
    def _train_placed(self, ctx, algo, name: str, pd, device_mode: str):
        """One algorithm train under cost-based placement (the same
        mesh swap Engine.train applies — eval sweeps train many
        candidates, so a mis-placed transfer-bound stage costs per
        candidate, not once)."""
        from ..workflow.placement import mesh_for_stage

        try:
            sm = algo.stage_model(pd)
        except Exception:  # noqa: BLE001 - sizing must never kill training
            log.exception("stage_model failed; using configured mesh")
            sm = None
        prev_mesh = ctx.mesh
        try:
            ctx.mesh = mesh_for_stage(
                ctx, sm, device_mode, f"algorithm[{name or 'default'}]")
            return algo.train(ctx, pd)
        finally:
            ctx.mesh = prev_mesh

    def eval(self, ctx, engine_params: EngineParams, workflow_params=None):
        """Per-fold: train on fold TD, batch-predict fold queries.
        Yields (eval_info, [(query, predicted, actual), ...]) per fold."""
        device_mode = getattr(workflow_params, "device", None) or getattr(
            getattr(ctx, "workflow_params", None), "device", "auto")
        ds, prep, algo_list, serving = self.make_components(engine_params)
        results = []
        for fold_i, (td, eval_info, qa) in enumerate(ds.read_eval(ctx)):
            pd = prep.prepare(ctx, td)
            models = [self._train_placed(ctx, algo, name, pd, device_mode)
                      for name, algo in algo_list]
            qa = list(qa)
            queries = [serving.supplement(q) for q, _ in qa]
            per_algo = [
                algo.batch_predict(models[i], queries)
                for i, (_, algo) in enumerate(algo_list)
            ]
            qpa = [
                (q, serving.serve(q, [pred[j] for pred in per_algo]), a)
                for j, (q, a) in enumerate(qa)
            ]
            results.append((eval_info, qpa))
            log.info("eval fold %d: %d query/actual pairs", fold_i, len(qpa))
        return results

    # -- deployment (reference: Engine.prepareDeployment path) ------------
    def prepare_deployment(self, ctx, engine_params: EngineParams, models: list[Any]):
        """Re-bind stored models to live algorithm instances for serving."""
        _, _, algo_list, serving = self.make_components(engine_params)
        if len(models) != len(algo_list):
            raise ValueError(
                f"{len(models)} stored models but {len(algo_list)} algorithms"
            )
        restored = [
            algo.restore_model(m, ctx) for (_, algo), m in zip(algo_list, models)
        ]
        return Deployment(self, algo_list, restored, serving)


class Deployment:
    """Live serving bundle: algorithms + restored models + serving."""

    def __init__(self, engine: Engine, algo_list, models, serving: Serving):
        self.engine = engine
        self.algo_list = algo_list
        self.models = models
        self.serving = serving

    def query(self, q) -> Any:
        # Stage telemetry: histogram observations per stage, and —
        # when the HTTP layer sampled this request (trace context
        # propagates through asyncio.to_thread) — one span per stage.
        # Each stage opens with a chaos fault point (latency/hang/fail
        # injection on the serving path, the overload harness's slow-
        # model lever) and a deadline spend-point: a worker thread past
        # its request's budget frees itself at the next stage boundary
        # instead of finishing work for a client that already got 504.
        dl = deadline.current()
        tr = telemetry.current_trace()
        t0 = (time.perf_counter_ns()
              if tr is not None else telemetry.timer_start())
        faultinject.fault_point("query.featurize")
        q = self.serving.supplement(q)
        t1 = time.perf_counter_ns() if t0 else 0
        _ST_FEATURIZE.observe_since(t0)
        if dl is not None:
            dl.check("query.predict")
        faultinject.fault_point("query.predict")
        predictions = [
            algo.predict(model, q)
            for (_, algo), model in zip(self.algo_list, self.models)
        ]
        t2 = time.perf_counter_ns() if t0 else 0
        _ST_PREDICT.observe_since(t1)
        if dl is not None:
            dl.check("query.serve")
        faultinject.fault_point("query.serve")
        result = self.serving.serve(q, predictions)
        _ST_SERVE.observe_since(t2)
        if tr is not None:
            t3 = time.perf_counter_ns()
            tr.add_span("query.featurize", t1 - t0)
            tr.add_span("query.predict", t2 - t1,
                        algorithms=len(self.algo_list))
            tr.add_span("query.serve", t3 - t2)
        return result

    def batch_query(self, queries) -> list[Any]:
        """Vectorized multi-query path (one device dispatch per
        algorithm instead of one per query) — used by the engine
        server's micro-batching window and `pio batchpredict`."""
        # One fault point per coalesced dispatch (not per query): a
        # latency injection here models ONE slow vectorized forward,
        # exactly what a wedged device queue looks like to the batcher.
        # No deadline spend-points — a batch mixes requests with
        # different budgets; expiry is enforced per-request at the
        # future level by the admission gate.
        t0 = telemetry.timer_start()
        faultinject.fault_point("query.batch_predict")
        qs = [self.serving.supplement(q) for q in queries]
        t1 = time.perf_counter_ns() if t0 else 0
        _ST_FEATURIZE_B.observe_since(t0)
        per_algo = [
            algo.batch_predict(model, qs)
            for (_, algo), model in zip(self.algo_list, self.models)
        ]
        t2 = time.perf_counter_ns() if t0 else 0
        _ST_PREDICT_B.observe_since(t1)
        out = [
            self.serving.serve(q, [pred[j] for pred in per_algo])
            for j, q in enumerate(qs)
        ]
        _ST_SERVE_B.observe_since(t2)
        return out


class SimpleEngine(Engine):
    """Reference: SimpleEngine — one DataSource + one Algorithm, identity
    preparator, first serving."""

    def __init__(self, data_source_class, algorithm_class):
        super().__init__(
            data_source_class,
            IdentityPreparator,
            {"": algorithm_class},
            FirstServing,
        )


class EngineFactory:
    """Reference: EngineFactory trait — ``apply()`` returns an Engine.
    Subclass and override apply(), or pass a plain function returning an
    Engine wherever a factory is accepted."""

    def apply(self) -> Engine:
        raise NotImplementedError

    def __call__(self) -> Engine:
        return self.apply()
