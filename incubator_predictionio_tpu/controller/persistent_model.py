"""PersistentModel — models that persist themselves instead of being
pickled into the Models DAO.

Reference: core/.../controller/PersistentModel.scala (save to shared fs,
reload with a live SparkContext via PersistentModelLoader). TPU analog:
save() writes an orbax/np checkpoint directory keyed by engine-instance id;
load() restores it (optionally re-sharding over the ctx mesh).
"""

from __future__ import annotations

import os
from typing import Any, ClassVar, Optional

from ..data.storage.registry import base_dir


class PersistentModel:
    """Mixin: a model that handles its own persistence.

    ``save`` returns True if the model persisted itself; returning False
    falls back to default pickling (reference: PersistentModel.save's
    contract).
    """

    def save(self, instance_id: str, params: Any) -> bool:
        raise NotImplementedError


class PersistentModelLoader:
    """Companion loader (reference: PersistentModelLoader.apply)."""

    @classmethod
    def load(cls, instance_id: str, params: Any, ctx) -> Any:
        raise NotImplementedError


def model_dir(instance_id: str) -> str:
    d = os.path.join(base_dir(), "persistent_models", instance_id)
    os.makedirs(d, exist_ok=True)
    return d


class LocalFileSystemPersistentModel(PersistentModel):
    """Reference: LocalFileSystemPersistentModel — np.savez checkpoint under
    the PIO filesystem base dir. Subclasses implement to_arrays/from_arrays."""

    def to_arrays(self) -> dict:
        raise NotImplementedError

    @classmethod
    def from_arrays(cls, arrays: dict) -> "LocalFileSystemPersistentModel":
        raise NotImplementedError

    def save(self, instance_id: str, params: Any) -> bool:
        import numpy as np

        path = os.path.join(model_dir(instance_id), f"{type(self).__name__}.npz")
        np.savez(path, **{k: np.asarray(v) for k, v in self.to_arrays().items()})
        return True

    @classmethod
    def load(cls, instance_id: str, ctx=None):
        import numpy as np

        path = os.path.join(model_dir(instance_id), f"{cls.__name__}.npz")
        with np.load(path, allow_pickle=False) as z:
            return cls.from_arrays({k: z[k] for k in z.files})
