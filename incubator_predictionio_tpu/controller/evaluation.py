"""Evaluation + EngineParamsGenerator (reference:
core/.../controller/{Evaluation,EngineParamsGenerator}.scala)."""

from __future__ import annotations

from typing import Optional, Sequence

from .engine import Engine, EngineParams
from .metric import Metric


class Evaluation:
    """Binds an engine with metrics (reference: Evaluation trait).

    Subclasses set ``engine`` and ``metric`` (+ optional ``metrics`` for
    secondary reporting), typically in __init__.
    """

    engine: Engine
    metric: Metric
    metrics: Sequence[Metric] = ()

    def engine_metrics(self) -> tuple[Engine, Metric, Sequence[Metric]]:
        if not hasattr(self, "engine") or not hasattr(self, "metric"):
            raise AttributeError(
                f"{type(self).__name__} must define .engine and .metric"
            )
        return self.engine, self.metric, tuple(self.metrics)


class EngineParamsGenerator:
    """Supplies candidate EngineParams for tuning (reference:
    EngineParamsGenerator trait — engineParamsList)."""

    engine_params_list: Sequence[EngineParams] = ()

    def params_list(self) -> Sequence[EngineParams]:
        if not self.engine_params_list:
            raise AttributeError(
                f"{type(self).__name__} must define .engine_params_list"
            )
        return self.engine_params_list
