"""Engine replica fleet: supervised serving replicas behind the splice
front, staged canary rollout, fleet-wide rollback.

``pio deploy --replicas N`` (or ``PIO_QUERY_REPLICAS``) runs N REAL
engine-server processes — each with its own GIL, executor, admission
gate and validation gate — behind the PR 8 L4 splice front
(``common/splice.py``), supervised per-replica by
``parallel/supervisor.py`` (``restart_scope="worker"``: a dead or
wedged replica is SIGKILLed and relaunched individually with a restart
budget while the rest keep serving). This is the horizontal-scale
deploy story upstream PredictionIO delegated to an external load
balancer (PAPER.md §0), owned natively — with the PR 9 model lifecycle
made **fleet-aware**:

- **One coordinated lifecycle, no new coordination service.** The
  fleet coordinates through the SAME artifact store the models live in
  (the epoch-fence idiom of ``data/api/event_log.py`` applied to DAO
  rows): the front's :class:`FleetCoordinator` is the single writer of
  an epoch-bumped *directive record*, and each replica is the single
  writer of its own *status row* (``workflow/model_artifact.py``
  fleet records). Both sides poll on ``PIO_FLEET_SYNC_MS``.
- **Staged canary rollout.** A newer COMPLETED instance is not
  broadcast: the coordinator directs exactly ONE canary replica to
  swap first (through that replica's own validation gate), the canary
  serves its ``PIO_SWAP_WATCH_MS`` watch window under live front
  traffic (the watch hedge keeps clients at 200 even when the canary
  misbehaves), and only a clean window promotes the remaining
  replicas (fault point ``fleet.promote``).
- **Fleet-wide rollback.** A watch breach, a failed gate, or a manual
  ``/rollback`` on ANY replica surfaces as a pin in that replica's
  status row; the coordinator merges it into the directive record and
  re-directs the whole fleet to last-good, so the mixed-brain window
  closes within a small multiple of ``PIO_FLEET_SYNC_MS`` instead of
  leaving N-1 replicas on the bad model.
- **Front hardening.** Connect-refused backends are retried within the
  same accept (a mid-relaunch replica costs a client nothing), a
  draining/not-ready replica (``/readyz`` 503) is skipped for NEW
  connections, and the front itself answers ``GET /healthz`` with
  aggregated backend liveness + rollout state.

Chaos hooks: ``PIO_FLEET_WORKER_FAULT_SPEC`` becomes each replica's
``PIO_FAULT_SPEC`` on the FIRST launch only (the event-server
convention — a restarted replica comes up clean); ``fleet.spawn`` fires
in the replica worker entry, ``fleet.promote`` before the promote
directive commits, ``fleet.record`` in front of directive writes.

Telemetry (front process; mirrored into the front's ``/healthz``):
``pio_fleet_state``, ``pio_fleet_promotes_total``,
``pio_fleet_rollbacks_total{reason}``,
``pio_fleet_canary_refusals_total{reason}``,
``pio_fleet_replicas_ready``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import threading
import time
from typing import Optional, Sequence

from ..common import envknobs, faultinject, telemetry
from ..common.splice import FrontProxy, probe_ready

log = logging.getLogger("pio.fleet")

__all__ = ["FleetCoordinator", "run_fleet"]


def _metrics():
    reg = telemetry.registry()
    return (
        reg.gauge("pio_fleet_state",
                  "Staged-rollout state of the fleet coordinator "
                  "(0 steady, 1 canary)").labels(),
        reg.counter("pio_fleet_promotes_total",
                    "Canary watch windows that closed clean and "
                    "promoted the remaining replicas").labels(),
        reg.counter("pio_fleet_rollbacks_total",
                    "Fleet-wide rollbacks propagated by the "
                    "coordinator, by the originating pin reason",
                    ("reason",)),
        reg.gauge("pio_fleet_replicas_ready",
                  "Replicas whose /readyz currently answers 200 "
                  "(front readiness poll)").labels(),
        reg.counter("pio_fleet_canary_refusals_total",
                    "Canary targets refused before the fleet moved "
                    "(gate refusal or watch breach ON the canary), by "
                    "pin reason — NOT fleet rollbacks: the other "
                    "replicas never served the target",
                    ("reason",)),
    )


class FleetCoordinator:
    """The staged-rollout state machine. Single writer of the fleet
    directive record; reads replica status rows and the engine-instance
    metadata. All methods are BLOCKING (storage I/O) — the front runs
    :meth:`step` off-loop on the ``PIO_FLEET_SYNC_MS`` cadence, and a
    step that raises (storage flake, injected fault) leaves the
    in-memory record dirty so the next step retries the write.

    States: ``steady`` (everyone on ``instance``) and ``canary``
    (``canaryReplica`` directed to ``target``, everyone else held on
    ``instance``). Transitions:

    - steady → canary: a newer non-pinned COMPLETED instance exists
    - canary → steady (promote): the canary serves the target with its
      watch window done and no pin → ``instance = target``,
      ``lastGood =`` the previous instance (fault point
      ``fleet.promote``)
    - canary → steady (refused): the target shows up pinned (gate
      failure or watch breach on the canary) → fleet stays put
    - steady → steady (fleet rollback): the DIRECTED instance shows up
      pinned on any replica (manual ``/rollback``, post-promote watch
      breach) → ``instance = lastGood``
    """

    def __init__(self, storage, replicas: int,
                 engine_factory_name: str,
                 engine_variant: str = "default",
                 sync_ms: float = 1000.0,
                 app_name: str = ""):
        from . import model_artifact

        self._ma = model_artifact
        self.storage = storage
        self.replicas = max(1, int(replicas))
        self.engine_factory_name = engine_factory_name
        self.engine_variant = engine_variant
        # an app-scoped coordinator (multi-tenant fleets) keys its
        # directive/status rows per app and stages only that app's
        # instances — two apps' rollouts can never fence each other
        self.app_name = str(app_name or "")
        self.group = model_artifact.fleet_group(
            engine_factory_name, engine_variant,
            self.app_name or None)
        # a status row older than this is a dead/wedged replica's — it
        # must neither block a promote forever nor vote on adoption
        # (the shared rule: `pio status` uses the same one)
        self.fresh_s = model_artifact.fleet_fresh_s(sync_ms)
        # a front restart resumes the durable record: pins survive,
        # and a crash mid-canary re-enters the canary state. The
        # STARTUP adoption is dirty: our first write must bump PAST the
        # adopted epoch, or a superseded incumbent (whose fence check
        # is strictly `>`) would never detect us and both coordinators
        # would keep committing at the same epoch indefinitely
        # scaling decisions queued by the elastic controller (event-loop
        # thread) for the next fenced directive commit on the coordinator
        # thread — `scale-directive-confinement`: this queue and the
        # elastic controller are the ONLY writers of the scale payload
        self._scale_lock = threading.Lock()
        self._scale_queue: list[dict] = []
        self._adopt(model_artifact.read_fleet_doc(
            storage, model_artifact.fleet_row_id(self.group)) or {})
        self._dirty = True

    def _adopt(self, on_disk: dict) -> None:
        """(Re)build the in-memory record from an on-disk one — used at
        startup and when a rival coordinator's epoch overtakes ours."""
        self.rec = {
            "epoch": int(on_disk.get("epoch", 0)),
            "state": on_disk.get("state", "steady"),
            "instance": on_disk.get("instance"),
            "target": on_disk.get("target"),
            "canaryReplica": on_disk.get("canaryReplica"),
            "lastGood": on_disk.get("lastGood"),
            "pinned": dict(on_disk.get("pinned") or {}),
            "scale": dict(on_disk.get("scale") or {}),
        }
        self._epoch_base = self.rec["epoch"]
        self._dirty = False

    # -- elastic topology --------------------------------------------------
    def set_replicas(self, n: int) -> None:
        """Widen the slot range the coordinator reads status rows over
        (scale entry point — callers confined by
        `scale-directive-confinement`). High-water only: a retired
        slot's stale row already ages out of `_rows` via `fresh_s`, and
        shrinking the range would hide a straggler's pin."""
        self.replicas = max(self.replicas, int(n))

    def apply_scale(self, decision: dict) -> None:
        """Queue an acted scaling decision for the next fenced
        directive commit (scale entry point — callers confined by
        `scale-directive-confinement`). Thread-safe: the elastic loop
        runs on the front's event loop, the commit on the coordinator
        thread."""
        with self._scale_lock:
            self._scale_queue.append(dict(decision))

    # -- storage views -----------------------------------------------------
    def _rows(self) -> dict[int, dict]:
        now = time.time()
        rows = {}
        for i in range(self.replicas):
            doc = self._ma.read_fleet_doc(
                self.storage, self._ma.fleet_row_id(self.group, i))
            if doc is not None and \
                    now - float(doc.get("updatedAt") or 0) <= self.fresh_s:
                rows[i] = doc
        return rows

    def _candidate(self):
        """Newest non-pinned COMPLETED instance strictly newer than the
        fleet's current one, or None (the shared definition in
        model_artifact — the replicas' refresh poll uses the same
        one)."""
        return self._ma.newer_completed_instance(
            self.storage.get_meta_data_engine_instances(),
            self.engine_factory_name, self.engine_variant,
            self.rec["instance"], exclude=self.rec["pinned"],
            app_name=self.app_name or None)

    # -- the state machine -------------------------------------------------
    def step(self) -> dict:
        """One coordinator tick; returns a snapshot of the record."""
        state_g, promotes_c, rollbacks_c, _ready_g, refusals_c = \
            _metrics()
        rows = self._rows()
        rec = self.rec
        # 1. merge replica-reported pins (manual /rollback, watch
        #    breaches, gate refusals) into the fleet record
        for row in rows.values():
            for iid, reason in (row.get("pinned") or {}).items():
                if iid and iid not in rec["pinned"]:
                    rec["pinned"][iid] = str(reason)
                    self._dirty = True
                    log.warning("fleet: replica %s pinned %s (%s); "
                                "propagating", row.get("replica"), iid,
                                reason)
        # 1b. commit queued scaling decisions: each acted decision is a
        #     STATE TRANSITION of the directive record (epoch bump
        #     through the fenced write below), carrying a bounded
        #     decision log for `pio status` / the front's /healthz
        with self._scale_lock:
            pending_scale, self._scale_queue = self._scale_queue, []
        if pending_scale:
            scale = dict(rec.get("scale") or {})
            decisions = list(scale.get("decisions") or [])
            for d in pending_scale:
                if d.get("target") is not None:
                    scale["target"] = d["target"]
                decisions.append(d)
            scale["decisions"] = decisions[-16:]
            rec["scale"] = scale
            self._dirty = True
        # 2. canary resolution
        if rec["state"] == "canary":
            if rec["target"] in rec["pinned"]:
                # refused, not rolled back: the fleet never served the
                # target — only the canary burned (its own rollback is
                # in ITS pio_engine_rollbacks_total)
                reason = rec["pinned"][rec["target"]]
                log.warning("fleet: canary target %s was pinned (%s); "
                            "fleet stays on %s", rec["target"], reason,
                            rec["instance"])
                refusals_c.labels(reason).inc()
                rec.update(state="steady", target=None,
                           canaryReplica=None)
                self._dirty = True
            else:
                crow = rows.get(rec["canaryReplica"])
                if (crow is not None
                        and crow.get("instance") == rec["target"]
                        and crow.get("watchDone")):
                    # the canary served its whole watch window clean —
                    # promote the remaining replicas
                    faultinject.fault_point("fleet.promote")
                    rec["lastGood"] = (rec["instance"]
                                       or crow.get("previous"))
                    log.info("fleet: canary %s clean on %s; promoting "
                             "the fleet (lastGood=%s)",
                             rec["canaryReplica"], rec["target"],
                             rec["lastGood"])
                    promotes_c.inc()
                    rec.update(state="steady", instance=rec["target"],
                               target=None, canaryReplica=None)
                    self._dirty = True
        if rec["state"] == "steady":
            # 3. fleet-wide rollback: the directed instance got pinned
            if rec["instance"] and rec["instance"] in rec["pinned"]:
                back = rec["lastGood"]
                if not back:
                    for row in rows.values():
                        inst = row.get("instance")
                        if inst and inst not in rec["pinned"]:
                            back = inst
                            break
                if back:
                    reason = rec["pinned"][rec["instance"]]
                    log.warning("fleet: directed instance %s pinned "
                                "(%s); rolling the fleet back to %s",
                                rec["instance"], reason, back)
                    rollbacks_c.labels(reason).inc()
                    rec.update(instance=back, lastGood=None)
                    self._dirty = True
                else:
                    log.error("fleet: directed instance %s pinned and "
                              "no unpinned instance served anywhere; "
                              "replicas hold last-good until a "
                              "deployable candidate appears (staged as "
                              "a canary)", rec["instance"])
                    rec.update(instance=None, lastGood=None)
                    self._dirty = True
            elif rec["instance"] is None and rows:
                # bootstrap adoption: directives need a reference
                # point. Converged fleet → adopt it; diverged (two
                # replicas booted around a train, or some replica on a
                # pinned instance) → adopt the NEWEST non-pinned served
                # instance and direct everyone there — leaving the
                # directive unset would wedge the fleet diverged
                # forever (replicas never self-refresh in fleet mode)
                serving = {row.get("instance") for row in rows.values()
                           if row.get("instance")}
                good = [i for i in serving if i not in rec["pinned"]]
                if len(good) == 1:
                    rec["instance"] = good[0]
                    self._dirty = True
                elif len(good) > 1:
                    instances = \
                        self.storage.get_meta_data_engine_instances()
                    rows_by_id = {i: instances.get(i) for i in good}
                    known = {i: r for i, r in rows_by_id.items()
                             if r is not None}
                    if known:
                        rec["instance"] = max(
                            known, key=lambda i: known[i].start_time)
                        self._dirty = True
                        log.warning(
                            "fleet: bootstrap found replicas diverged "
                            "across %s; converging on newest %s",
                            sorted(good), rec["instance"])
            # 4. canary start — needs at least one fresh replica to
            #    stage on. A None reference instance does NOT block
            #    staging: after a rollback that found no last-good
            #    (every served instance pinned), the only way the
            #    fleet can ever converge again is a canary onto the
            #    newest non-pinned COMPLETED instance — `_candidate`
            #    with current=None returns exactly that, and the
            #    promote path re-establishes `instance`
            if (rec["state"] == "steady"
                    and rec["target"] is None and rows):
                cand = self._candidate()
                if cand is not None:
                    canary = min(rows)
                    log.info("fleet: staging canary %s on replica %d "
                             "(fleet stays on %s)", cand.id, canary,
                             rec["instance"])
                    rec.update(state="canary", target=cand.id,
                               canaryReplica=canary)
                    self._dirty = True
        # EVERY tick commits the record — state changes bump through
        # the fenced write, and the directive also carries the
        # aggregated replica status rows ("peers"), so each replica's
        # /status view costs ONE directive read instead of re-reading
        # every peer row itself (O(N) store traffic fleet-wide per
        # tick, not O(N^2))
        self._write(peers=[rows[i] for i in sorted(rows)])
        # read back through self.rec: a fenced write ADOPTS the rival
        # coordinator's record, replacing the dict `rec` aliases
        rec = self.rec
        state_g.set(1.0 if rec["state"] == "canary" else 0.0)
        return {**rec, "pinned": dict(rec["pinned"]),
                "scale": dict(rec.get("scale") or {})}

    def _write(self, peers=None) -> None:
        """Epoch-fenced directive commit: bump past the last epoch WE
        own; if the on-disk record has overtaken it, another
        coordinator is live — adopt its record and skip this write (the
        fenced-writer half of the lease idiom; ownership trades back on
        our next state transition, which bumps past the rival).
        ``peers`` rides along as display/aggregation payload (never
        part of the adopted state machine record)."""
        on_disk = self._ma.read_fleet_doc(
            self.storage, self._ma.fleet_row_id(self.group))
        if on_disk is not None \
                and int(on_disk.get("epoch", 0)) > self._epoch_base:
            log.warning(
                "fleet directive epoch %s has overtaken ours (%s): "
                "another coordinator owns this fleet; adopting its "
                "record", on_disk.get("epoch"), self._epoch_base)
            self._adopt(on_disk)
            return
        if self._dirty:
            # the epoch versions the STATE MACHINE record: peer-refresh
            # writes re-commit the same epoch, state transitions bump it
            self.rec["epoch"] = self._epoch_base + 1
        self.rec["updatedAt"] = time.time()
        self._ma.write_fleet_doc(
            self.storage, self._ma.fleet_row_id(self.group),
            {**self.rec, "peers": list(peers or ())},
            fault=True)
        self._epoch_base = self.rec["epoch"]
        self._dirty = False


def run_fleet(worker_argv: Sequence[str], replicas: int, host: str,
              port: int, *, engine_factory_name: str,
              engine_variant: str = "default",
              run_dir: Optional[str] = None,
              app_name: str = "",
              elastic: bool = False) -> int:
    """Blocking entry for ``pio deploy --replicas N``: spawn N
    supervised replica processes, splice client connections to them,
    and run the staged-rollout coordinator.

    ``worker_argv`` is the full command line of ONE replica (the CLI
    passes ``pio deploy --replica-worker ...``; the test harness passes
    its jax-free server script); the supervisor adds the fleet identity
    env (``PIO_FLEET_REPLICA``, ``PIO_FLEET_REPLICAS``,
    ``PIO_QUERY_REPLICA_PORT``) per worker. Spawning stays confined to
    ``parallel/supervisor.py``.

    ``elastic=True`` (``pio deploy --replicas auto``) arms the
    autoscaler (``workflow/elastic.py``): the fleet starts at
    ``PIO_FLEET_MIN_REPLICAS`` (or the explicit ``replicas`` clamped
    into the [min, max] envelope; pass ``replicas <= 0`` for "start at
    the floor"), and the front's elastic loop scrapes every replica's
    ``/status`` each ``PIO_SCALE_TICK_MS``, growing the fleet through
    the supervisor's :meth:`~..parallel.supervisor.Supervisor.add_worker`
    and shrinking it by draining the least-loaded ready replica
    (routing withdrawn FIRST, then the supervisor's graceful
    retirement). Replica identity is slot-based: a drained slot frees
    its index, a scale-up reuses the lowest free one."""
    from ..data.storage.registry import Storage
    from ..parallel.supervisor import Supervisor

    ecfg = None
    if elastic:
        from .elastic import (ElasticConfig, ElasticController,
                              ReplicaSample, sample_status)

        ecfg = ElasticConfig.from_env(
            default_min=max(1, int(replicas)) if replicas > 0 else 1)
        if replicas <= 0:
            replicas = ecfg.min_replicas
        replicas = min(max(int(replicas), ecfg.min_replicas),
                       ecfg.max_replicas)
    else:
        replicas = max(1, int(replicas))
    sync_ms = envknobs.env_float("PIO_FLEET_SYNC_MS", 1000.0, lo=50.0)
    ready_ms = envknobs.env_float("PIO_FLEET_READY_MS", 500.0, lo=50.0)
    connect_retry_ms = envknobs.env_ms(
        "PIO_FLEET_CONNECT_RETRY_MS", 1000.0, lo_ms=0.0)
    # slot-indexed ports: None marks a freed slot (elastic scale-down);
    # a later scale-up reassigns the slot with a fresh port
    ports: list[Optional[int]] = [Supervisor._free_port()
                                  for _ in range(replicas)]
    base_env = dict(os.environ)
    chaos = base_env.pop("PIO_FLEET_WORKER_FAULT_SPEC", None)
    # per-replica chaos (the soak driver's fault timeline):
    # PIO_FLEET_WORKER_FAULT_SPEC_<i> overrides the shared spec for
    # replica i only — a scheduled crash can target ONE replica
    # instead of SIGKILLing the whole fleet at the same offset
    _chaos_prefix = "PIO_FLEET_WORKER_FAULT_SPEC_"
    per_replica_chaos = {}
    for key in [k for k in base_env if k.startswith(_chaos_prefix)]:
        try:
            per_replica_chaos[int(key[len(_chaos_prefix):])] = \
                base_env.pop(key)
        except ValueError:
            pass
    base_env.pop("PIO_QUERY_REPLICAS", None)
    if app_name:
        # replicas must derive the SAME app-scoped directive group as
        # this coordinator (create_server._fleet_group reads this)
        base_env["PIO_FLEET_APP"] = app_name

    def env_for(attempt: int, idx: int) -> dict:
        if attempt > 0:
            # port TOCTOU on respawn: re-pick, the front routes off
            # the live list (the event-server front convention)
            ports[idx] = Supervisor._free_port()
        env = {
            "PIO_FLEET_REPLICA": str(idx),
            "PIO_FLEET_REPLICAS": str(replicas),
            "PIO_QUERY_REPLICA_PORT": str(ports[idx]),
        }
        spec = per_replica_chaos.get(idx, chaos)
        if spec and attempt == 0:
            env["PIO_FAULT_SPEC"] = spec
        return env

    sup = Supervisor(list(worker_argv), replicas, env=base_env,
                     per_worker_env=env_for, wire_coordinator=False,
                     restart_scope="worker", resume_argv=(),
                     run_dir=run_dir)
    coordinator = FleetCoordinator(
        Storage.instance(), replicas, engine_factory_name,
        engine_variant, sync_ms=sync_ms, app_name=app_name)
    sup_done = threading.Event()
    outcome = {}

    def run_sup():
        try:
            outcome["state"] = sup.run()
        except BaseException:  # noqa: BLE001 — a crashed supervisor is
            # a FAILED fleet, not a clean drain: without the explicit
            # state, run_fleet would default to "drained" and exit 0
            # with nothing serving
            log.exception("fleet supervisor crashed")
            outcome["state"] = "error"
        finally:
            sup_done.set()

    t = threading.Thread(target=run_sup, daemon=True)
    t.start()
    log.info("engine fleet: front on %s:%d, %d replica(s) on ports %s "
             "(group %s, run dir %s)", host, port, replicas, ports,
             coordinator.group, sup.run_dir)

    # loop-confined snapshots the /healthz provider reads (the
    # coordinator's own dict mutates on a worker thread)
    last_rec: dict = {"rec": dict(coordinator.rec)}
    # allocated slots (live or draining); the elastic loop is the only
    # mutator, so the other loops can iterate a sorted copy freely
    slots: set[int] = set(range(replicas))
    draining_slots: set[int] = set()
    elastic_state: dict = {"target": replicas, "lastDecision": None}

    def healthz() -> dict:
        rec = last_rec["rec"]
        backends = []
        for i in sorted(slots):
            pid = sup.worker_pid(i)
            backends.append({
                "replica": i,
                "port": ports[i] if i < len(ports) else None,
                "pid": pid,
                "alive": pid is not None,
                "ready": front.is_ready(i) and not front.is_draining(i),
                "draining": front.is_draining(i),
                "restarts": (sup.worker_restarts[i]
                             if i < len(sup.worker_restarts) else 0),
            })
        active = [i for i in sorted(slots) if not front.is_draining(i)]
        # target vs actual (not the launch-time N): a mid-scale fleet
        # reads as "2 of target 3 active, 2 ready" rather than
        # degraded, and a DRAINING replica is reported as such — an
        # intentional drain is not a dead backend
        doc = {
            "status": "alive",
            "group": coordinator.group,
            "replicas": elastic_state["target"],
            "targetReplicas": elastic_state["target"],
            "activeReplicas": len(active),
            "readyReplicas": front.ready_count(),
            "drainingReplicas": sorted(draining_slots),
            "state": rec.get("state"),
            "instance": rec.get("instance"),
            "target": rec.get("target"),
            "canaryReplica": rec.get("canaryReplica"),
            "epoch": rec.get("epoch"),
            "pinned": rec.get("pinned") or {},
            "backends": backends,
            "runDir": sup.run_dir,
        }
        if ecfg is not None:
            last = elastic_state["lastDecision"]
            doc["elastic"] = {
                "enabled": True,
                "min": ecfg.min_replicas,
                "max": ecfg.max_replicas,
                "target": elastic_state["target"],
                "actual": len(active),
                "config": ecfg.to_json(),
                "lastDecision": last,
                "decisions": list(controller.decisions[-5:]),
                "samples": list(elastic_state.get("samples") or ()),
            }
        return doc

    front = FrontProxy(ports, healthz_provider=healthz,
                       connect_retry_s=connect_retry_ms / 1000.0)
    for i in range(replicas):
        # seed not-ready: FrontProxy treats UNPROBED backends as ready
        # (the event-server-compat default), which would report
        # readyReplicas == N on /healthz before any replica has even
        # bound its port — readiness gates (bench fleet_up, monitors)
        # must see 0 until the first probe pass really answers
        front.set_ready(i, False)

    async def ready_loop() -> None:
        ready_g = _metrics()[3]
        while True:
            # probe concurrently: one wedged replica (accepts but never
            # answers — exactly the heartbeat-stall window before the
            # supervisor kills it) must cost the pass ONE probe timeout,
            # not serialize every other replica's mark stale behind it.
            # Draining slots are skipped (their not-ready mark is
            # intentional and already set) and freed slots have no port.
            idxs = [i for i in sorted(slots)
                    if i < len(ports) and ports[i] is not None
                    and not front.is_draining(i)]
            marks = await asyncio.gather(
                *(probe_ready("127.0.0.1", ports[i]) for i in idxs),
                return_exceptions=True)
            for i, ok in zip(idxs, marks):
                front.set_ready(i, ok is True)
            ready_g.set(float(front.ready_count()))
            await asyncio.sleep(ready_ms / 1000.0)

    async def coord_loop() -> None:
        while True:
            try:
                last_rec["rec"] = await asyncio.to_thread(
                    coordinator.step)
            except Exception:  # noqa: BLE001 — retried next tick
                log.exception("fleet coordinator step failed; retrying")
            await asyncio.sleep(sync_ms / 1000.0)

    if ecfg is not None:
        controller = ElasticController(ecfg)
        prev_shed: dict[int, int] = {}

        async def scrape_samples() -> list:
            idxs = [i for i in sorted(slots)
                    if i < len(ports) and ports[i] is not None]
            docs = await asyncio.gather(
                *(sample_status("127.0.0.1", ports[i]) for i in idxs),
                return_exceptions=True)
            samples = []
            for i, doc in zip(idxs, docs):
                drng = front.is_draining(i)
                s = ReplicaSample(
                    slot=i, alive=sup.worker_pid(i) is not None,
                    ready=front.is_ready(i) and not drng, draining=drng)
                if isinstance(doc, dict):
                    ov = doc.get("overload") or {}
                    s.pending = int(ov.get("pending") or 0)
                    s.pending_limit = int(ov.get("pendingLimit") or 0)
                    shed_total = int(ov.get("shed") or 0)
                    prev = prev_shed.get(i)
                    s.shed_delta = (max(0, shed_total - prev)
                                    if prev is not None else 0)
                    prev_shed[i] = shed_total
                samples.append(s)
            return samples

        def do_scale_up() -> int:
            # lowest free slot — slot identity is stable, so the
            # coordinator's status rows and the front's readiness
            # marks never alias across scale cycles
            idx = 0
            while idx in slots:
                idx += 1
            while len(ports) <= idx:
                ports.append(None)
            ports[idx] = Supervisor._free_port()
            slots.add(idx)
            front.set_backend(idx, ports[idx])
            front.set_ready(idx, False)
            coordinator.set_replicas(idx + 1)
            sup.add_worker(idx)
            return idx

        def do_scale_down(slot: int) -> None:
            # ordering is the lossless-drain contract: routing is
            # withdrawn FIRST (draining excludes the slot from BOTH
            # connect passes), THEN the supervisor SIGTERMs it — the
            # replica finishes its in-flight queries and cuts
            # keep-alives on its own graceful drain path, and clients
            # reconnect through the front to the survivors
            front.set_ready(slot, False)
            front.set_draining(slot, True)
            draining_slots.add(slot)
            sup.retire_worker(slot)

        def reap_drained() -> None:
            for i in sorted(draining_slots):
                if sup.worker_pid(i) is None and not sup.is_retiring(i):
                    # booked out by the supervisor: the slot is free
                    front.set_backend(i, None)
                    ports[i] = None
                    slots.discard(i)
                    draining_slots.discard(i)
                    prev_shed.pop(i, None)
                    log.info("elastic: slot %d released", i)

        async def elastic_loop() -> None:
            while True:
                try:
                    reap_drained()
                    samples = await scrape_samples()
                    decision = controller.observe(samples)
                    elastic_state["samples"] = [s.to_json()
                                                for s in samples]
                    elastic_state["lastDecision"] = decision.to_json()
                    if decision.direction == "up":
                        idx = do_scale_up()
                        entry = controller.record_action(decision)
                        entry["slot"] = idx
                        elastic_state["target"] = decision.target
                        coordinator.apply_scale(entry)
                        log.info("elastic: scale-up (%s) -> replica %d "
                                 "spawning, target %d", decision.reason,
                                 idx, decision.target)
                    elif decision.direction == "down":
                        do_scale_down(decision.slot)
                        entry = controller.record_action(decision)
                        elastic_state["target"] = decision.target
                        coordinator.apply_scale(entry)
                        log.info("elastic: scale-down (%s) -> replica "
                                 "%d draining, target %d",
                                 decision.reason, decision.slot,
                                 decision.target)
                except Exception:  # noqa: BLE001 — retried next tick
                    log.exception("elastic tick failed; retrying")
                await asyncio.sleep(ecfg.tick_ms / 1000.0)

    async def front_main() -> None:
        await front.start(host, port)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        import signal as _signal
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except (NotImplementedError, RuntimeError):  # pragma: no cover
                pass
        tasks = [loop.create_task(ready_loop()),
                 loop.create_task(coord_loop())]
        if ecfg is not None:
            tasks.append(loop.create_task(elastic_loop()))
        # the front lives exactly as long as its replicas: a supervisor
        # that gave up must take the front down rather than keep
        # accepting connections nothing can serve
        while not stop.is_set() and not sup_done.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=0.25)
            except asyncio.TimeoutError:
                pass
        for task in tasks:
            task.cancel()
        for task in tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        await front.stop()
        sup.request_stop()

    try:
        asyncio.run(front_main())
    finally:
        # runs on the crash path too (e.g. EADDRINUSE binding the
        # front): the supervisor was started BEFORE the front, so an
        # early exception must still drain the N replica processes —
        # a daemon thread dying with the CLI would orphan them all
        sup.request_stop()
        sup_done.wait(timeout=60)
        t.join(timeout=5)
    # outcome is only empty when the supervisor never reached a
    # terminal state within the wait — a wedge, not a clean drain
    state = outcome.get("state", "wedged")
    log.info("engine fleet stopped (%s)", state)
    return 0 if state in ("drained", "completed") else 1


def _die_with_parent() -> None:
    """A front that dies WITHOUT draining (SIGKILL, OOM kill) must not
    orphan N replicas serving forever on ports nothing routes to. Two
    layers: Linux ``PR_SET_PDEATHSIG`` has the kernel deliver SIGTERM
    (the normal drain path) the instant the supervising parent goes,
    and a 1 s-cadence watchdog thread catches kernels that fail to
    deliver it (observed on sandboxed/gVisor kernels) by watching for
    reparenting to init. Pdeathsig fires on the death of the spawning
    THREAD, which here is the supervisor thread — alive exactly as
    long as supervision is."""
    import signal as _signal

    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, _signal.SIGTERM, 0, 0, 0)  # PR_SET_PDEATHSIG
        # NO getppid()==1 "already orphaned" recheck here: sandboxed
        # kernels (gVisor) intermittently report ppid 1 for a freshly
        # spawned child whose parent is alive, and the misfire exits
        # the replica before its first-launch chaos/serving ever runs —
        # worse than the microsecond fork→prctl window it would close
    except (OSError, AttributeError):  # pragma: no cover - non-Linux
        pass

    # polling suspenders for the prctl belt: the same sandboxed
    # kernels intermittently fail to DELIVER pdeathsig at all, so a
    # daemon thread also watches for reparenting to init. Several
    # consecutive observations are required before acting — a single
    # getppid()==1 reading can be the spurious-at-spawn transient —
    # then SIGTERM ourselves, which is the replica's normal drain path
    def _watch() -> None:
        strikes = 0
        while True:
            time.sleep(1.0)
            strikes = strikes + 1 if os.getppid() == 1 else 0
            if strikes >= 3:
                log.warning("fleet front is gone (reparented to "
                            "init); draining this replica")
                os.kill(os.getpid(), _signal.SIGTERM)
                return

    threading.Thread(target=_watch, daemon=True,
                     name="fleet-orphan-watchdog").start()


def replica_worker_entry() -> int:
    """Entry body of one fleet replica process (`pio deploy
    --replica-worker` and the test harness land here after loading
    their engine): resolves the supervisor-assigned identity. Returns
    the replica's listen port. The ``fleet.spawn`` fault point fires
    here — first-launch chaos (``PIO_FLEET_WORKER_FAULT_SPEC``) proves
    a replica crashing at spawn is relaunched by the supervisor without
    client impact."""
    _die_with_parent()
    faultinject.fault_point("fleet.spawn")
    port = envknobs.env_int("PIO_QUERY_REPLICA_PORT", 0, lo=0)
    if port <= 0:
        print("[error] --replica-worker requires PIO_QUERY_REPLICA_PORT "
              "(set by the fleet supervisor — this flag is internal)",
              file=sys.stderr)
        return -1
    return port
