"""Mid-training checkpoint / resume — a strict upgrade over the reference.

The reference has NO mid-training checkpointing: a failed `pio train` Spark
job restarts from scratch, and only the finished model is persisted
(reference: core/.../workflow/CoreWorkflow.scala persists the final blob;
SURVEY.md §5.3-5.4 "No mid-training checkpointing — treat as new design
territory"). Here every N ALS iterations (or any algorithm-defined step
granularity) the live factor pytree is snapshotted with orbax, and
`pio train --resume` continues the most recent interrupted run from its
last snapshot instead of restarting.

Layout:
``$PIO_FS_BASEDIR/checkpoints/<engine-instance-id>/algo_<idx>_<name>/<step>/``
(Engine.train scopes each algorithm to its own subdirectory) —
keyed by the same EngineInstance id the metadata repository tracks, so a
crashed instance (status RUNNING/ABORTED) plus its checkpoint directory is
all the state needed to resume on a fresh process or a different host
(multi-host: orbax handles sharded arrays; each host writes its shards).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

log = logging.getLogger("pio.checkpoint")


class CheckpointIncompatibleError(ValueError):
    """A restored snapshot cannot continue the current run (shape, rank, or
    data-fingerprint mismatch). run_train catches this to discard the stale
    snapshots and fall back to training from scratch instead of leaving a
    permanently poisoned --resume candidate behind."""


def checkpoint_root() -> str:
    from ..data.storage.registry import base_dir

    return os.path.join(base_dir(), "checkpoints")


def instance_checkpoint_dir(instance_id: str) -> str:
    return os.path.join(checkpoint_root(), instance_id)


class CheckpointHook:
    """Orbax-backed snapshot hook handed to algorithms via WorkflowContext.

    ``every_n == 0`` disables saving (every ``maybe_save`` is a no-op) but
    restore still works, so a resumed run can read snapshots even when the
    operator turns further checkpointing off.
    """

    def __init__(self, directory: str, every_n: int = 0, max_to_keep: int = 2):
        self.directory = os.path.abspath(directory)
        self.every_n = int(every_n)
        self.max_to_keep = max_to_keep
        self._mgr = None

    # -- lazy manager ------------------------------------------------------

    def _manager(self):
        if self._mgr is None:
            import orbax.checkpoint as ocp

            os.makedirs(self.directory, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.max_to_keep, create=True
                ),
            )
        return self._mgr

    @property
    def enabled(self) -> bool:
        return self.every_n > 0

    def should_save(self, step: int) -> bool:
        return self.enabled and step > 0 and step % self.every_n == 0

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, pytree: Any) -> None:
        import jax
        import orbax.checkpoint as ocp

        pytree = jax.device_get(pytree)
        self._manager().save(int(step), args=ocp.args.StandardSave(pytree))
        log.info("checkpoint saved: step %d → %s", step, self.directory)

    def maybe_save(self, step: int, pytree: Any) -> bool:
        if not self.should_save(step):
            return False
        self.save(step, pytree)
        return True

    def latest_step(self) -> Optional[int]:
        if not os.path.isdir(self.directory):
            return None
        return self._manager().latest_step()

    def restore(self, step: Optional[int] = None) -> tuple[int, Any]:
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        tree = self._manager().restore(int(step), args=ocp.args.StandardRestore())
        log.info("checkpoint restored: step %d ← %s", step, self.directory)
        return int(step), tree

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None

    def delete_all(self) -> None:
        """Drop the instance's checkpoints (called after COMPLETED)."""
        import shutil

        self.close()
        shutil.rmtree(self.directory, ignore_errors=True)


def _train_still_alive(env: dict) -> bool:
    """True when a RUNNING instance may still have a live trainer process —
    resuming it would have two processes fighting over one checkpoint dir.
    On this host the recorded pid is probed directly (a SIGKILL'd train
    shows up as RUNNING with a dead pid — exactly the case --resume is
    for). A RUNNING row from ANOTHER host cannot be probed, so it fails
    closed: resume it from the host that owns it, or wait for it to abort.
    ABORTED rows are always resumable, from any host."""
    import socket

    if env.get("host") != socket.gethostname():
        return True  # unprobeable foreign trainer: assume alive
    try:
        pid = int(env.get("pid", ""))
    except ValueError:
        return False
    try:
        os.kill(pid, 0)
    except PermissionError:
        return True  # pid exists but belongs to another user: alive
    except OSError:
        return False
    return pid != os.getpid()


def find_resumable_instance(storage, engine_id: str, engine_version: str = "1",
                            engine_variant: str = "default",
                            data_source_params: Optional[str] = None,
                            preparator_params: Optional[str] = None):
    """Most recent non-COMPLETED EngineInstance that left checkpoints behind
    (the `pio train --resume` discovery path). When the params JSON strings
    are given, only instances reading the SAME data source match — several
    apps can share one engine template without ever seeing (or deleting)
    each other's interrupted runs."""
    instances = storage.get_meta_data_engine_instances()
    candidates = [
        i for i in instances.get_all()
        if i.engine_id == engine_id
        and i.engine_version == engine_version
        and i.engine_variant == engine_variant
        and (data_source_params is None or i.data_source_params == data_source_params)
        and (preparator_params is None or i.preparator_params == preparator_params)
        and i.status in ("RUNNING", "ABORTED")
        and os.path.isdir(instance_checkpoint_dir(i.id))
        and not (i.status == "RUNNING" and _train_still_alive(i.env or {}))
    ]
    if not candidates:
        return None
    return max(candidates, key=lambda i: i.start_time)
