"""Elastic topology: the fleet sizes itself under load.

The autoscaler closes the control loop the earlier PRs left open: PR 12
put N real engine replicas behind the splice front with per-replica
supervision and staged rollout, but N was fixed at launch while
production traffic is diurnal. This module is the *decision* half of
ROADMAP item 5 — the TensorFlow argument (arxiv 1605.08695) of one
dataflow spanning heterogeneous, changing resources, applied to the
serving tier:

- :func:`sample_status` scrapes one replica's ``/status`` (the same
  overload snapshot ``pio status`` reads): queue depth
  (``pending``/``pendingLimit``), shed counter, drain flag.
- :class:`ElasticController` folds a per-tick list of
  :class:`ReplicaSample` into a :class:`Decision` against the operator
  bounds (``PIO_FLEET_MIN/MAX_REPLICAS``) and thresholds
  (``PIO_SCALE_UP/DOWN_THRESHOLD``), with hysteresis
  (``PIO_SCALE_HYSTERESIS_TICKS`` consecutive ticks must agree) and a
  cooldown (``PIO_SCALE_COOLDOWN_MS``) so a noisy minute cannot flap
  the fleet.
- The controller only DECIDES. Acting stays where the machinery already
  lives: the fleet front (``workflow/fleet.py``) spawns through the
  supervisor (`spawn-confinement` — there is no new spawn path) and
  drains through the front's draining mark + the supervisor's
  retirement path; every acted decision is committed as a fenced
  directive payload by the :class:`~.fleet.FleetCoordinator`
  (`scale-directive-confinement` — only the elastic controller and the
  coordinator touch the scale entry points).

Scale-up reasons: ``floor`` (actual below the operator minimum — the
one decision that skips hysteresis, a fleet below floor is failing
now), ``shed`` (replicas refused work this tick), ``utilization``
(queue depth crossed ``PIO_SCALE_UP_THRESHOLD``). Scale-down has one
reason, ``quiet``, and always picks the least-loaded READY replica —
never the canary's slot 0 when avoidable, never a replica that is
already draining; while a spawned replica is still settling toward
ready the loop holds (``settling``) rather than drain the only ready
replica out from under the fleet.

Telemetry: ``pio_fleet_scale_events_total{direction,reason}`` counts
acted decisions; ``pio_fleet_replicas_target`` gauges the current
target. The decision log (last 16 acted decisions) rides in the fleet
directive's ``scale`` payload, observable via the front's ``/healthz``
and ``pio status --engine-url``.

Host-ceiling honesty: on the 2-core CI host more replicas do not mean
more throughput — the demonstrable axis is DECISION LATENCY
(detect→spawn→ready, drain-on-quiet→released), benched same-run with a
ceiling control (`tools/elastic_bench.py`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import time
from typing import Optional, Sequence

from ..common import envknobs, telemetry

__all__ = [
    "Decision", "ElasticConfig", "ElasticController", "ReplicaSample",
    "plan", "sample_status",
]


def _metrics():
    reg = telemetry.registry()
    return (
        reg.counter("pio_fleet_scale_events_total",
                    "Acted autoscaler decisions, by direction "
                    "(up/down) and reason (floor/shed/utilization/"
                    "quiet)", ("direction", "reason")),
        reg.gauge("pio_fleet_replicas_target",
                  "Replica count the autoscaler is currently driving "
                  "the fleet toward").labels(),
    )


# ---------------------------------------------------------------------------
# config + snapshot types
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ElasticConfig:
    """Operator bounds + loop damping (all overridable via env).

    - ``PIO_FLEET_MIN_REPLICAS`` / ``PIO_FLEET_MAX_REPLICAS`` — the
      envelope the fleet may size itself within
    - ``PIO_SCALE_UP_THRESHOLD`` — queue utilization
      (pending/pendingLimit, worst ready replica) at/above which a tick
      votes scale-up (default 0.8)
    - ``PIO_SCALE_DOWN_THRESHOLD`` — utilization at/below which a tick
      votes scale-down (default 0.2; sheds always veto down-votes)
    - ``PIO_SCALE_HYSTERESIS_TICKS`` — consecutive agreeing ticks
      before acting (default 3)
    - ``PIO_SCALE_COOLDOWN_MS`` — minimum spacing between acted
      decisions (default 5000)
    - ``PIO_SCALE_TICK_MS`` — scrape/decide cadence (default 500)
    """

    min_replicas: int = 1
    max_replicas: int = 1
    up_threshold: float = 0.8
    down_threshold: float = 0.2
    hysteresis_ticks: int = 3
    cooldown_ms: float = 5000.0
    tick_ms: float = 500.0

    @classmethod
    def from_env(cls, default_min: int = 1,
                 default_max: Optional[int] = None) -> "ElasticConfig":
        mn = envknobs.env_int("PIO_FLEET_MIN_REPLICAS", default_min, lo=1)
        mx = envknobs.env_int(
            "PIO_FLEET_MAX_REPLICAS",
            default_max if default_max is not None else max(mn, 2), lo=1)
        up = min(envknobs.env_float("PIO_SCALE_UP_THRESHOLD", 0.8,
                                    lo=0.01), 1.0)
        down = envknobs.env_float("PIO_SCALE_DOWN_THRESHOLD", 0.2, lo=0.0)
        return cls(
            min_replicas=mn,
            max_replicas=max(mx, mn),
            up_threshold=up,
            down_threshold=min(down, up),
            hysteresis_ticks=envknobs.env_int(
                "PIO_SCALE_HYSTERESIS_TICKS", 3, lo=1),
            cooldown_ms=envknobs.env_float(
                "PIO_SCALE_COOLDOWN_MS", 5000.0, lo=0.0),
            tick_ms=envknobs.env_float("PIO_SCALE_TICK_MS", 500.0,
                                       lo=50.0),
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ReplicaSample:
    """One replica's telemetry at one tick, as the front sees it."""

    slot: int
    alive: bool = False
    ready: bool = False
    draining: bool = False
    pending: int = 0
    pending_limit: int = 0
    #: sheds observed since the PREVIOUS tick (counter delta, not the
    #: process-lifetime total — a replica that shed once an hour ago
    #: must not vote scale-up forever)
    shed_delta: int = 0

    def utilization(self) -> float:
        if self.pending_limit <= 0:
            return 0.0
        return self.pending / float(self.pending_limit)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Decision:
    """One tick's verdict. ``direction`` is what the caller should DO
    (``hold`` when gated); ``gates`` names what held an up/down
    recommendation back (hysteresis, cooldown) so ``pio fleet plan``
    can show the difference between "nothing to do" and "waiting"."""

    direction: str  # "up" | "down" | "hold"
    reason: str
    target: int
    slot: Optional[int] = None  # the replica a scale-down drains
    utilization: float = 0.0
    shed_delta: int = 0
    actual: int = 0
    gates: tuple = ()

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["gates"] = list(self.gates)
        d["utilization"] = round(self.utilization, 4)
        return d


# ---------------------------------------------------------------------------
# the decision function (pure — `pio fleet plan` runs it driver-side)
# ---------------------------------------------------------------------------

def _signals(samples: Sequence[ReplicaSample]):
    active = [s for s in samples if s.alive and not s.draining]
    ready = [s for s in active if s.ready]
    util = max((s.utilization() for s in ready), default=0.0)
    shed = sum(max(0, s.shed_delta) for s in samples)
    return active, ready, util, shed


def _drain_candidate(ready: Sequence[ReplicaSample]) -> Optional[int]:
    """Least-loaded ready replica; ties break toward the HIGHEST slot
    so slot 0 (the canary seat) stays populated when load is equal."""
    if not ready:
        return None
    return min(ready, key=lambda s: (s.pending, -s.slot)).slot


def _recommend(samples: Sequence[ReplicaSample],
               cfg: ElasticConfig) -> Decision:
    """The un-damped recommendation for one snapshot."""
    active, ready, util, shed = _signals(samples)
    actual = len(active)
    if actual < cfg.min_replicas:
        return Decision("up", "floor", target=actual + 1,
                        utilization=util, shed_delta=shed, actual=actual)
    pressure = shed > 0 or util >= cfg.up_threshold
    if pressure:
        if actual < cfg.max_replicas:
            return Decision("up", "shed" if shed > 0 else "utilization",
                            target=actual + 1, utilization=util,
                            shed_delta=shed, actual=actual)
        return Decision("hold", "at-max", target=actual,
                        utilization=util, shed_delta=shed, actual=actual)
    if util <= cfg.down_threshold and shed == 0 \
            and actual > cfg.min_replicas:
        if len(ready) < len(active):
            # mid-scale: a spawned replica has not probed ready yet.
            # Draining now would pick the only READY replica (the
            # newcomer is ineligible), cancel the scale-up it is
            # settling, and leave a window with nothing routable —
            # the up/down flap this hold exists to break.
            return Decision("hold", "settling", target=actual,
                            utilization=util, shed_delta=shed,
                            actual=actual)
        slot = _drain_candidate(ready)
        if slot is not None:
            return Decision("down", "quiet", target=actual - 1,
                            slot=slot, utilization=util, shed_delta=shed,
                            actual=actual)
        return Decision("hold", "no-ready-candidate", target=actual,
                        utilization=util, shed_delta=shed, actual=actual)
    return Decision("hold", "steady", target=actual, utilization=util,
                    shed_delta=shed, actual=actual)


def plan(samples: Sequence[ReplicaSample],
         cfg: ElasticConfig) -> Decision:
    """What the scaler WOULD do from this snapshot with hysteresis and
    cooldown satisfied — the dry-run entry ``pio fleet plan`` prints.
    Pure: no state is read or written, nothing acts."""
    return _recommend(samples, cfg)


class ElasticController:
    """The damped loop: feed it one sample list per tick via
    :meth:`observe`, act on ``up``/``down`` decisions, and confirm each
    act with :meth:`record_action` (which starts the cooldown, resets
    the hysteresis counters, bumps the scale-events metric, and appends
    to the decision log the directive payload carries)."""

    def __init__(self, cfg: ElasticConfig):
        self.cfg = cfg
        self.decisions: list[dict] = []  # acted decisions, newest last
        self.last_decision: Optional[Decision] = None
        self.last_action_at: Optional[float] = None  # monotonic
        self._over = 0
        self._under = 0

    def observe(self, samples: Sequence[ReplicaSample],
                now: Optional[float] = None) -> Decision:
        now = time.monotonic() if now is None else now
        rec = _recommend(samples, self.cfg)
        gates: list[str] = []
        if rec.direction == "up":
            self._under = 0
            self._over += 1
            # a fleet below its floor is failing NOW — no hysteresis
            need = 1 if rec.reason == "floor" else self.cfg.hysteresis_ticks
            if self._over < need:
                gates.append("hysteresis")
        elif rec.direction == "down":
            self._over = 0
            self._under += 1
            if self._under < self.cfg.hysteresis_ticks:
                gates.append("hysteresis")
        else:
            self._over = self._under = 0
        if rec.direction != "hold" and self.last_action_at is not None \
                and (now - self.last_action_at) * 1000.0 \
                < self.cfg.cooldown_ms:
            gates.append("cooldown")
        if gates:
            rec = dataclasses.replace(rec, direction="hold",
                                      gates=tuple(gates))
        self.last_decision = rec
        return rec

    def record_action(self, decision: Decision,
                      now: Optional[float] = None) -> dict:
        """Confirm an acted up/down decision; returns the JSON payload
        the caller hands to the coordinator's fenced directive write."""
        now = time.monotonic() if now is None else now
        self.last_action_at = now
        self._over = self._under = 0
        events_c, target_g = _metrics()
        events_c.labels(decision.direction, decision.reason).inc()
        target_g.set(float(decision.target))
        entry = {**decision.to_json(), "at": time.time()}
        self.decisions.append(entry)
        del self.decisions[:-16]
        return entry


# ---------------------------------------------------------------------------
# the scraper (front-side; hand-rolled like splice.probe_ready so the
# front needs no HTTP client stack)
# ---------------------------------------------------------------------------

async def sample_status(host: str, port: int,
                        timeout: float = 2.0) -> Optional[dict]:
    """``GET /status`` against one replica, parsed JSON or None. The
    request carries ``Connection: close`` so the body is simply
    everything after the header block."""
    try:
        r, w = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout)
    except (OSError, asyncio.TimeoutError):
        return None
    try:
        w.write(b"GET /status HTTP/1.1\r\nHost: front\r\n"
                b"Connection: close\r\n\r\n")
        await w.drain()
        raw = await asyncio.wait_for(r.read(), timeout)
    except (ConnectionError, OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError):
        return None
    finally:
        try:
            w.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
    head, sep, body = raw.partition(b"\r\n\r\n")
    if not sep or b" 200" not in head.split(b"\r\n", 1)[0]:
        return None
    try:
        return json.loads(body.decode("utf-8", errors="replace"))
    except ValueError:
        return None
