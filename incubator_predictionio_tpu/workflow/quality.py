"""Continuous quality evaluation: the shadow scorer behind the
quality-triggered rollback.

ROADMAP item 1's open guardrail: PR 9 made model refresh safe against
ERRORS (validation gate, post-swap watch, rollback + pin) and PR 12/13
made publishing continuous (staged canary fleet, fold-in increments) —
but the watch judged a candidate only by error rate, so a model serving
200s of garbage, or a poisoned increment that silently degrades
rankings, survived indefinitely. This module closes it with online
relevance evaluation in the serving loop (MLlib's evaluator suite as
the metric catalog, arxiv 1505.06807, graded at ALX-style serving scale
points where per-query overhead matters, arxiv 2112.02194):

1. **Sample.** The engine server offers every answered query; a
   configurable slice (``PIO_QUALITY_SAMPLE``) is retained —
   (user, query, ranked items) only, so the hot path pays one RNG draw
   and, for sampled queries, one list comprehension.
2. **Shadow.** On the scorer's own loop (never the request path) each
   sampled query is replayed against the RETAINED last-good deployment
   by driving the DASE stages directly — the ``_validate_swap``
   precedent: no admission slots, no chaos ``query.*`` budgets, no
   per-query stage histograms polluted.
3. **Label.** Held-out *next events* tailed from the app's log
   partitions via PR 13's ``LogCursor`` (``data/api/holdout.py``,
   exactly-the-new-bytes reads): the user's subsequent actions are the
   relevance labels. A sample resolves once it has aged past the
   resolve window AND its user acted; unlabeled samples expire.
4. **Grade.** Batched MAP@k / NDCG@k / AUC on device (``ops/eval.py``),
   folded into per-window accumulators; the canary-vs-last-good NDCG
   delta with a minimum-sample gate is the breach verdict
   (``ops.eval.quality_verdict``) — thin traffic can't false-trigger.
5. **Roll back.** The engine server's quality loop feeds a breach into
   the SAME rollback path as an error-rate breach
   (``_rollback_to_previous``), with reason ``quality`` — the refresh
   loop, fold-in chain and fleet coordinator treat the pin identically.

Telemetry: ``pio_engine_quality_samples_total``,
``pio_engine_quality_scored_total``, ``pio_engine_quality_expired_total``,
``pio_engine_quality_breaches_total``, and the
``pio_engine_quality_metric``/``pio_engine_quality_delta`` gauges
(labelled by metric). All documented in docs/operations.md
"Continuous quality evaluation".
"""

from __future__ import annotations

import json
import logging
import random
import time
from collections import deque
from typing import Optional

from ..common import telemetry
from ..data.api.holdout import HoldoutTailer
from ..ops import eval as evalops

log = logging.getLogger("pio.quality")

__all__ = ["QualityShadow", "extract_ranking"]

_M_SAMPLES = telemetry.registry().counter(
    "pio_engine_quality_samples_total",
    "Live queries sampled by the shadow scorer").labels()
_M_SCORED = telemetry.registry().counter(
    "pio_engine_quality_scored_total",
    "Sampled queries that resolved against held-out next events and "
    "were graded").labels()
_M_EXPIRED = telemetry.registry().counter(
    "pio_engine_quality_expired_total",
    "Sampled queries dropped unresolved (the user never acted inside "
    "the expiry window, or the served model swapped)").labels()
_M_BREACHES = telemetry.registry().counter(
    "pio_engine_quality_breaches_total",
    "Quality-watch verdicts that crossed the canary-vs-last-good "
    "threshold (each arms one quality rollback)").labels()
_M_METRIC = telemetry.registry().gauge(
    "pio_engine_quality_metric",
    "Windowed mean ranking quality of the LIVE model against held-out "
    "next events", ("metric",))
_M_DELTA = telemetry.registry().gauge(
    "pio_engine_quality_delta",
    "Windowed last-good-minus-live quality delta (positive = the live "
    "model is worse)", ("metric",))


def extract_ranking(prediction) -> Optional[list]:
    """The ranked item-id list of a prediction, or None when the
    engine's answer shape carries no ranking (scalar predictions are
    simply not sampled — quality evaluation grades rankings)."""
    if not isinstance(prediction, dict):
        return None
    scores = prediction.get("itemScores")
    if not isinstance(scores, list) or not scores:
        return None
    items = []
    for s in scores:
        item = s.get("item") if isinstance(s, dict) else None
        if item is None:
            return None
        items.append(str(item))
    return items


class _Sample:
    __slots__ = ("user", "query", "live", "shadow", "t")

    def __init__(self, user: str, query: dict, live: list, t: float):
        self.user = user
        self.query = query
        self.live = live
        self.shadow: Optional[list] = None
        self.t = t


class QualityShadow:
    """One app's shadow scorer. Owned by the engine server's quality
    loop and driven from a worker thread (``asyncio.to_thread``) —
    single-flight by construction, so scoring state needs no lock; the
    intake deque is the only cross-thread surface (atomic appends from
    the request path, drained by the tick)."""

    # unlabeled samples are held this many resolve-windows before
    # expiring: long enough for slow actors, bounded so a quiet user
    # can't pin memory
    _EXPIRE_FACTOR = 4.0

    def __init__(self, storage, *, sample: float, k: int,
                 min_samples: int, max_drop: float, resolve_ms: float,
                 max_pending: int = 512):
        self.storage = storage
        self.sample = min(1.0, max(0.0, float(sample)))
        self.k = max(1, int(k))
        self.min_samples = max(1, int(min_samples))
        self.max_drop = float(max_drop)
        self.resolve_s = max(0.0, float(resolve_ms)) / 1e3
        self.max_pending = max(1, int(max_pending))
        self._rng = random.Random()
        self._intake: deque = deque(maxlen=self.max_pending)
        self._pending: "deque[_Sample]" = deque()
        self._tailer: Optional[HoldoutTailer] = None
        self._app_id: Optional[int] = None
        self._app_name: Optional[str] = None
        self._disabled: Optional[str] = None
        self._instance_id: Optional[str] = None
        self._live = evalops.MetricWindow()
        self._shadow = evalops.MetricWindow()
        self._deltas = {"map": 0.0, "ndcg": 0.0, "auc": 0.0}
        self._breached = False
        self._sampled = 0
        self._scored = 0
        self._expired = 0
        self._last_error: Optional[str] = None

    # -- request-path hook (event loop; must stay cheap) -------------------
    def offer(self, query, prediction) -> None:
        """Called with every successfully answered live query. One RNG
        draw decides; sampled queries cost one ranking extraction and
        an atomic deque append (drop-oldest when the scorer lags —
        sampling is best-effort by definition)."""
        if self.sample <= 0.0 or self._rng.random() >= self.sample:
            return
        if not isinstance(query, dict):
            return
        user = query.get("user")
        if user is None:
            return
        items = extract_ranking(prediction)
        if not items:
            return
        self._intake.append(_Sample(str(user), dict(query), items,
                                    time.time()))
        self._sampled += 1
        _M_SAMPLES.inc()

    # -- bootstrap ---------------------------------------------------------
    def _arm(self, instance) -> bool:
        """Resolve the app + events dir once (and again whenever the
        served instance's app changes). False = quality evaluation
        structurally unavailable here; the reason lands on /status
        instead of a crash-looping tick."""
        le = self.storage.get_l_events()
        events_dir = getattr(le, "events_dir", None)
        if not events_dir:
            self._disabled = ("event store is not a JSONL event log "
                              "(the holdout tailer reads log "
                              "partitions; TYPE=JSONL)")
            return False
        app_name = ((instance.env or {}).get("appName")
                    or self._ds_params(instance).get("app_name")
                    or self._ds_params(instance).get("appName") or "")
        if not app_name:
            self._disabled = ("deployed instance names no app "
                              "(env.appName / data-source appName)")
            return False
        app = self.storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            self._disabled = f"app {app_name!r} is not registered"
            return False
        if self._app_id == app.id and self._tailer is not None:
            return True
        self._app_id, self._app_name = app.id, app_name
        # armed at the log end: everything already written predates the
        # queries this scorer will grade
        self._tailer = HoldoutTailer(events_dir, app.id)
        self._disabled = None
        log.info("quality: holdout tailer armed for app %r at the "
                 "current log end", app_name)
        return True

    @staticmethod
    def _ds_params(instance) -> dict:
        try:
            doc = json.loads(instance.data_source_params or "{}")
            return doc if isinstance(doc, dict) else {}
        except ValueError:
            return {}

    # -- one tick ----------------------------------------------------------
    def run_once(self, deployment, instance, prev_deployment) -> dict:
        """Worker-thread tick: poll labels → shadow-replay fresh
        samples → resolve aged ones → grade both windows → verdict.
        Returns the /status view (``"breach"`` True when this window
        crossed the threshold). Raises on tailer/storage faults — the
        loop logs and retries next tick."""
        try:
            if not self._arm(instance):
                return self.view()
            if instance.id != self._instance_id:
                # new model serving: old samples graded a model that no
                # longer serves, and the windows compare per-instance
                self._reset_window(instance.id)
            self._tailer.poll()
            now = time.time()
            while True:
                try:
                    s = self._intake.popleft()
                except IndexError:
                    break
                # replay NOW, while the last-good models are resident:
                # by resolve time the previous slot may have turned over
                if prev_deployment is not None:
                    s.shadow = self._replay(prev_deployment, s.query)
                self._pending.append(s)
            self._resolve(now)
            breach = self._verdict()
            self._last_error = None
            out = self.view()
            out["breach"] = breach
            return out
        except Exception as e:
            self._last_error = str(e)
            raise

    def _reset_window(self, instance_id) -> None:
        dropped = len(self._pending)
        if dropped:
            self._expired += dropped
            _M_EXPIRED.inc(dropped)
        self._pending.clear()
        self._live.reset()
        self._shadow.reset()
        self._deltas = {"map": 0.0, "ndcg": 0.0, "auc": 0.0}
        self._breached = False
        self._instance_id = instance_id

    def _replay(self, deployment, query) -> Optional[list]:
        try:
            q = deployment.serving.supplement(dict(query))
            predictions = [
                algo.predict(model, q)
                for (_name, algo), model in zip(deployment.algo_list,
                                                deployment.models)
            ]
            return extract_ranking(deployment.serving.serve(q, predictions))
        except Exception:  # noqa: BLE001 — a failing shadow replay is
            # not a serving error; the sample just carries no shadow leg
            return None

    def _resolve(self, now: float) -> None:
        expire_s = self.resolve_s * self._EXPIRE_FACTOR
        live_lists, live_labels = [], []
        shadow_lists, shadow_labels = [], []
        keep: "deque[_Sample]" = deque()
        while self._pending:
            s = self._pending.popleft()
            age = now - s.t
            if age < self.resolve_s:
                keep.append(s)
                continue
            labels = self._tailer.labels_for(s.user)
            if not labels:
                if age >= expire_s:
                    self._expired += 1
                    _M_EXPIRED.inc()
                else:
                    keep.append(s)
                continue
            live_lists.append(s.live)
            live_labels.append(labels)
            if s.shadow:
                shadow_lists.append(s.shadow)
                shadow_labels.append(labels)
        self._pending = keep
        if not live_lists:
            return
        self._live.add(evalops.ranking_metrics(live_lists, live_labels,
                                               self.k))
        if shadow_lists:
            self._shadow.add(evalops.ranking_metrics(
                shadow_lists, shadow_labels, self.k))
        self._scored += len(live_lists)
        _M_SCORED.inc(len(live_lists))
        means = self._live.means()
        for m in ("map", "ndcg", "auc"):
            _M_METRIC.labels(m).set(round(means[m], 6))

    def _verdict(self) -> bool:
        breach, deltas = evalops.quality_verdict(
            self._live.means(), self._shadow.means(),
            min_samples=self.min_samples, max_drop=self.max_drop)
        self._deltas = deltas
        for m, d in deltas.items():
            _M_DELTA.labels(m).set(d)
        if breach and not self._breached:
            # latch: one breach verdict per window — the server rolls
            # back once, and the window resets with the swap
            self._breached = True
            _M_BREACHES.inc()
            return True
        return False

    # -- status surface ----------------------------------------------------
    def view(self) -> dict:
        out = {
            "enabled": self._disabled is None,
            "disabledReason": self._disabled,
            "sample": self.sample,
            "k": self.k,
            "minSamples": self.min_samples,
            "maxDrop": self.max_drop,
            "resolveMs": self.resolve_s * 1e3,
            "app": self._app_name,
            "appId": self._app_id,
            "instance": self._instance_id,
            "sampled": self._sampled,
            "scored": self._scored,
            "expired": self._expired,
            "pending": len(self._pending) + len(self._intake),
            "live": {k: round(v, 6) if isinstance(v, float) else v
                     for k, v in self._live.means().items()},
            "shadow": {k: round(v, 6) if isinstance(v, float) else v
                       for k, v in self._shadow.means().items()},
            "deltas": self._deltas,
            "breached": self._breached,
            "lastError": self._last_error,
        }
        if self._tailer is not None:
            out["holdout"] = self._tailer.view()
        return out
