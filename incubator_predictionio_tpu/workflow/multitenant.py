"""Multi-tenant engine serving — one process, hundreds of apps.

ROADMAP item 3, the upstream premise (PAPER.md §0: one ML *server*,
per-app access keys) applied to the serving side: every engine process
here used to load exactly ONE engine instance, so "hundreds of apps"
meant hundreds of fleets. :class:`TenantMux` lets one engine server
(and the PR 12 replica fleet in front of it) serve N apps:

- **Routing** — a query names its tenant by app (``X-Pio-App`` header /
  ``app`` query param) or by access key (``accessKey`` query param /
  ``X-Pio-Access-Key`` header, resolved through the SAME AccessKeys
  repository the event server authorizes against, TTL-cached). An
  anonymous query falls through to the process's default app, so a
  single-tenant deploy behaves exactly as before.
- **Resident-model cache** — tenants' deployments live in an LRU
  bounded by ``PIO_TENANT_MAX_RESIDENT``. A tenant's first query lazily
  loads its newest COMPLETED instance through the PR 9 verified-read
  (checksum walk-back) + validation-gate path, warmed up like any other
  swap. Eviction NEVER drops a tenant mid-query: every in-flight query
  holds a refcount, the victim scan skips busy tenants, and the debt is
  collected at release time. An evicted tenant keeps its (tiny)
  lifecycle state — pins survive eviction, so a poisoned artifact is
  not re-picked on reload — and answers again after one lazy reload.
- **Per-tenant lifecycle** — each tenant owns its own post-swap watch,
  pin set and retained-previous deployment: a poisoned tenant's
  watch-breach pins/rolls back THAT app alone (instant swap to its
  resident previous, or pin + walk-back when none is resident) — never
  the process, never a neighbor.
- **Per-tenant fold-in** — each resident tenant gets its own
  :class:`~.online.FoldInRunner` (the PR 13 per-app ``LogCursor`` rows
  already key on app id), ticked by the server's fold-in loop, and its
  increments publish through the tenant's own gate + watch.
- **Per-tenant admission budgets** — ``PIO_TENANT_MAX_PENDING`` bounds
  one app's in-flight + queued queries BELOW the process cap, so a hot
  app sheds 503s while cold tenants keep serving (the PR 6 admission
  machinery, extended per access key).

Confinement (lint rule ``tenant-confinement``): the resident-cache
internals — the ``_resident_lru`` ordered dict and the
``_evict_victim`` scan — are touched ONLY by this module. Everyone
else (the engine server, the status CLI, tests) goes through the
public surface: ``resolve_app`` / ``admit`` / ``ensure_loaded`` /
``note_result`` / ``rollback_tenant`` / ``release`` / ``foldin_tick``
/ ``snapshot``.

Telemetry (docs/operations.md "Multi-tenant serving"):
``pio_tenant_queries_total{app}``, ``pio_tenant_shed_total{app}``,
``pio_tenant_rollbacks_total{app}``, ``pio_tenant_loads_total``,
``pio_tenant_evictions_total`` and the ``pio_tenant_resident`` gauge.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Optional

from ..common import envknobs, telemetry
from .context import WorkflowContext
from .core_workflow import load_deployment
from . import model_artifact

log = logging.getLogger("pio.multitenant")

_M_QUERIES = telemetry.registry().counter(
    "pio_tenant_queries_total",
    "Queries admitted to a non-default tenant, per app", ("app",))
_M_SHED = telemetry.registry().counter(
    "pio_tenant_shed_total",
    "Queries refused 503 by a tenant's OWN admission budget "
    "(PIO_TENANT_MAX_PENDING) — the process-level gate counts "
    "separately", ("app",))
_M_ROLLBACKS = telemetry.registry().counter(
    "pio_tenant_rollbacks_total",
    "Per-tenant rollbacks (watch breach or validation refusal pinning "
    "that app's instance alone), per app", ("app",))
_M_LOADS = telemetry.registry().counter(
    "pio_tenant_loads_total",
    "Tenant model loads: lazy first-query loads, post-eviction "
    "reloads, rollback walk-backs and fold-in publishes").labels()
_M_EVICTIONS = telemetry.registry().counter(
    "pio_tenant_evictions_total",
    "Tenant deployments evicted from the resident LRU "
    "(PIO_TENANT_MAX_RESIDENT)").labels()
_M_RESIDENT = telemetry.registry().gauge(
    "pio_tenant_resident",
    "Tenant deployments currently resident in the multi-tenant LRU "
    "cache").labels()


class UnknownTenant(Exception):
    """The request named a tenant this deployment cannot serve: an
    access key no AccessKeys row matches, or an app name the metadata
    store does not know. Maps to 401/404 — never a fallthrough to the
    default tenant (serving app A's model to app B's key would be a
    cross-tenant leak)."""


class TenantState:
    """One app's serving state. The deployment/instance pair is the
    heavy part (device-resident models) and the only part eviction
    drops; everything else — pins, counters, the admission ledger —
    is a few hundred bytes and survives eviction."""

    def __init__(self, name: str, app_id: int):
        self.name = name
        self.app_id = app_id
        # serializes loads / swaps / watch accounting for THIS tenant
        # only — tenant A's cold load never blocks tenant B's queries
        self.lock = threading.Lock()
        self.deployment = None
        self.instance = None
        self.previous: Optional[tuple] = None   # (deployment, instance)
        self.pinned: dict[str, str] = {}        # instance id → reason
        self.watch: Optional[dict] = None       # per-tenant post-swap watch
        self.degraded: Optional[str] = None
        self.inflight = 0       # refcount: queries between admit/release
        self.pending = 0        # admission ledger (inflight incl. queued)
        self.shed = 0
        self.queries = 0
        self.loads = 0
        self.swaps = 0
        self.rollbacks: dict[str, int] = {}
        self.last_used = time.monotonic()
        self.foldin = None                      # per-tenant FoldInRunner
        self.foldin_view: Optional[dict] = None

    def row(self, resident: bool) -> dict:
        """Status row for /status "tenants" and `pio status`."""
        w = self.watch
        fv = self.foldin_view or {}
        return {
            "app": self.name,
            "appId": self.app_id,
            "resident": resident,
            "instance": self.instance.id if self.instance else None,
            "previous": self.previous[1].id if self.previous else None,
            "pinned": dict(self.pinned),
            "watch": ({"total": w["total"], "errors": w["errors"]}
                      if w is not None else None),
            "degraded": self.degraded,
            "inflight": self.inflight,
            "pending": self.pending,
            "shed": self.shed,
            "queries": self.queries,
            "loads": self.loads,
            "swaps": self.swaps,
            "rollbacks": dict(self.rollbacks),
            "idleS": round(max(0.0, time.monotonic() - self.last_used),
                           1),
            "cursorLagS": fv.get("lagSeconds"),
            "foldinEvents": fv.get("events"),
            "foldinPublishes": fv.get("publishes"),
        }


class TenantMux:
    """The tenant multiplexer an engine server owns when
    ``PIO_TENANT_MAX_RESIDENT`` > 0. Thread model: ``_lock`` guards the
    resident LRU, the parked map and the mux-level counters (touched
    from the event loop AND loader worker threads); each tenant's own
    ``state.lock`` serializes that tenant's loads and watch accounting.
    Lock order: mux lock is never held while a tenant lock is taken
    with storage I/O inside — loads run under the tenant lock only."""

    def __init__(self, server, max_resident: int, max_pending: int):
        self._server = server
        self.max_resident = max(1, int(max_resident))
        self.max_pending = max(1, int(max_pending))
        self._lock = threading.Lock()
        # app name → TenantState WITH a loaded deployment; insertion
        # order doubles as LRU order (move_to_end on every admit)
        self._resident_lru: "collections.OrderedDict[str, TenantState]" \
            = collections.OrderedDict()
        # evicted / not-yet-loaded tenants: lifecycle state without the
        # deployment (pins survive eviction here)
        self._parked: dict[str, TenantState] = {}
        self._evictions = 0
        self._cold_loads = 0
        # access-key → (expires_monotonic, app name) — the event
        # server's TTL key-cache idiom; a deleted key stops resolving
        # within the TTL
        self._key_ttl_s = envknobs.env_ms(
            "PIO_TENANT_KEY_TTL_MS", 30_000.0)
        self._keys: dict[str, tuple[float, Optional[str]]] = {}

    # -- routing -----------------------------------------------------------
    def resolve_app(self, request) -> Optional[str]:
        """The tenant a request names, or None for anonymous requests
        (→ the process's default app). Raises :class:`UnknownTenant`
        for a key/app nothing resolves — never falls through to the
        default tenant on a BAD credential."""
        app = (request.headers.get("X-Pio-App")
               or request.query.get("app"))
        if app:
            return str(app)
        key = (request.query.get("accessKey")
               or request.headers.get("X-Pio-Access-Key"))
        if not key:
            return None
        app = self._app_for_key(str(key))
        if app is None:
            raise UnknownTenant("access key does not match any app")
        return app

    def _app_for_key(self, key: str) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            hit = self._keys.get(key)
            if hit is not None and hit[0] > now:
                return hit[1]
        name: Optional[str] = None
        try:
            row = self._server.storage.get_meta_data_access_keys().get(
                key)
            if row is not None:
                app = self._server.storage.get_meta_data_apps().get(
                    row.appid)
                name = app.name if app is not None else None
        except Exception:  # noqa: BLE001 — storage flake ≠ bad key
            log.exception("access-key resolution failed")
            return None
        with self._lock:
            self._keys[key] = (now + self._key_ttl_s, name)
            if len(self._keys) > 4096:   # bound a key-scan's footprint
                self._keys.pop(next(iter(self._keys)))
        return name

    # -- admission ---------------------------------------------------------
    def admit(self, app: str) -> TenantState:
        """Take one slot in ``app``'s admission budget (and pin the
        tenant against eviction) or refuse. Raises
        :class:`UnknownTenant` (→ 404) for unregistered apps and the
        server's AdmissionShed (→ 503 + Retry-After) past the budget.
        Every successful admit MUST be paired with :meth:`release`."""
        from .create_server import AdmissionShed

        state = self._state_for(app)
        with self._lock:
            if state.pending >= self.max_pending:
                state.shed += 1
                _M_SHED.labels(app).inc()
                raise AdmissionShed(
                    f"tenant {app!r} admission budget full "
                    f"({state.pending}/{self.max_pending})", 1.0,
                    "tenant")
            state.pending += 1
            state.inflight += 1
            state.queries += 1
            state.last_used = time.monotonic()
            if app in self._resident_lru:
                self._resident_lru.move_to_end(app)
        _M_QUERIES.labels(app).inc()
        return state

    def release(self, state: TenantState) -> None:
        """Drop the admit refcount and collect any eviction debt a
        busy victim deferred."""
        with self._lock:
            state.pending = max(0, state.pending - 1)
            state.inflight = max(0, state.inflight - 1)
            self._shrink_locked()

    def _state_for(self, app: str) -> TenantState:
        with self._lock:
            state = self._resident_lru.get(app) or self._parked.get(app)
            if state is not None:
                return state
        # registration check outside the mux lock (storage I/O)
        row = self._server.storage.get_meta_data_apps().get_by_name(app)
        if row is None:
            raise UnknownTenant(f"app {app!r} is not registered")
        with self._lock:
            state = self._resident_lru.get(app) or self._parked.get(app)
            if state is None:
                state = self._parked[app] = TenantState(app, row.id)
            return state

    # -- resident cache (the confined internals) ---------------------------
    def ensure_loaded(self, state: TenantState) -> TenantState:
        """Worker-thread lazy load: make ``state``'s deployment
        resident (verified read + validation gate + warm-up), evicting
        the least-recently-used idle tenant past the bound. No-op when
        already resident."""
        with state.lock:
            if state.deployment is None:
                self._load_tenant_locked(state)
                with self._lock:
                    self._cold_loads += 1
        with self._lock:
            if state.name not in self._resident_lru:
                self._parked.pop(state.name, None)
                self._resident_lru[state.name] = state
            self._resident_lru.move_to_end(state.name)
            self._shrink_locked()
            _M_RESIDENT.set(len(self._resident_lru))
        return state

    def _shrink_locked(self) -> None:
        """Evict past the bound (mux lock held). Busy tenants
        (inflight > 0) are skipped — eviction never drops a tenant
        mid-query — and the debt is collected at the next release."""
        while len(self._resident_lru) > self.max_resident:
            victim = self._evict_victim()
            if victim is None:
                return          # everyone busy: collect at release time
            self._resident_lru.pop(victim.name, None)
            self._parked[victim.name] = victim
            # drop ONLY the heavy halves; pins/counters survive so a
            # reload cannot re-pick a poisoned artifact
            victim.deployment = None
            victim.instance = None
            victim.previous = None
            victim.watch = None
            victim.foldin = None
            self._evictions += 1
            _M_EVICTIONS.inc()
            log.info("tenant %r evicted from the resident cache "
                     "(%d/%d resident)", victim.name,
                     len(self._resident_lru), self.max_resident)
        _M_RESIDENT.set(len(self._resident_lru))

    def _evict_victim(self) -> Optional[TenantState]:
        """LRU-order scan for the first idle (refcount-zero) tenant."""
        for state in self._resident_lru.values():
            if state.inflight <= 0:
                return state
        return None

    # -- per-tenant lifecycle ----------------------------------------------
    def _load_tenant_locked(self, state: TenantState,
                            instance_id: Optional[str] = None) -> None:
        """Load ``state``'s newest deployable instance (or an explicit
        ``instance_id``) through the verified-read walk-back + the
        validation gate, pinning refused candidates per tenant. Holds
        ``state.lock`` (caller takes it). Raises when nothing for this
        app is deployable."""
        from .create_server import SwapValidationError

        srv = self._server
        while True:
            ctx = WorkflowContext(storage=srv.storage,
                                  app_name=state.name)
            deployment, instance, _ = load_deployment(
                srv.engine, instance_id, ctx,
                engine_factory_name=srv.engine_factory_name,
                engine_variant=srv.engine_variant,
                exclude_ids=tuple(state.pinned),
                on_reject=lambda iid, kind: state.pinned.setdefault(
                    iid, f"integrity:{kind}"),
                app_name=state.name,
            )
            try:
                for model in deployment.models:
                    warm = getattr(model, "warm_up", None)
                    if callable(warm):
                        warm()
                srv._validate_swap(deployment, instance)
            except SwapValidationError as e:
                state.pinned.setdefault(e.instance_id, "validate")
                if instance_id is not None:
                    raise
                log.warning("tenant %r: %s; pinned, walking back",
                            state.name, e)
                continue
            break
        prev_dep, prev_inst = state.deployment, state.instance
        if prev_inst is not None and prev_inst.id != instance.id:
            state.previous = (prev_dep, prev_inst)
            state.swaps += 1
        state.deployment = deployment
        state.instance = instance
        state.loads += 1
        state.degraded = None
        _M_LOADS.inc()
        # EVERY tenant load arms the watch (not just swaps): a lazily
        # loaded model is unvetted in this process, and the watch is
        # what turns a poisoned tenant into a pin + walk-back instead
        # of an unbounded 500 stream
        if srv.swap_watch_ms > 0:
            state.watch = {
                "until": time.monotonic() + srv.swap_watch_ms / 1e3,
                "total": 0, "errors": 0, "instance": instance.id,
            }
        if srv.foldin_ms > 0 and state.foldin is None:
            from . import online

            state.foldin = online.FoldInRunner(
                srv.storage, srv.engine_factory_name,
                srv.engine_variant, interval_ms=srv.foldin_ms,
                app_name=state.name)
            try:
                state.foldin.arm(instance)
            except Exception:  # noqa: BLE001 — first tick retries
                log.exception("tenant %r: fold-in arm failed; first "
                              "tick retries", state.name)
            state.foldin_view = state.foldin.view()
        log.info("tenant %r: deployed engine instance %s", state.name,
                 instance.id)

    def note_result(self, state: TenantState, ok: bool) -> bool:
        """Record one query outcome against the tenant's watch window.
        Returns True when the error rate tripped the rollback threshold
        (same rules as the process watch: ≥ 2 failures AND a failure
        fraction above PIO_SWAP_MAX_ERROR_RATE) — the caller then runs
        :meth:`rollback_tenant` off-loop."""
        with state.lock:
            w = state.watch
            cur = state.instance
            if w is None or cur is None or w["instance"] != cur.id:
                return False
            if time.monotonic() > w["until"]:
                log.info("tenant %r: watch for %s closed clean (%d "
                         "queries, %d errors)", state.name,
                         w["instance"], w["total"], w["errors"])
                state.watch = None
                return False
            w["total"] += 1
            if not ok:
                w["errors"] += 1
                srv = self._server
                if (w["errors"] >= 2 and w["total"] > 0
                        and w["errors"] / w["total"]
                        > srv.swap_max_error_rate):
                    return True
            return False

    def rollback_tenant(self, state: TenantState, reason: str):
        """Worker-thread per-tenant rollback: pin the bad instance and
        restore service for THIS app alone — instant swap to its
        resident previous deployment, else pin + walk-back reload.
        Returns the restored deployment (for an immediate retry of the
        triggering query), or None when nothing older is deployable
        (the tenant goes degraded; every other tenant is untouched)."""
        with state.lock:
            bad = state.instance
            if bad is None:
                return None
            if state.watch is not None \
                    and state.watch.get("instance") != bad.id:
                return state.deployment   # a concurrent swap won
            state.pinned.setdefault(bad.id, reason)
            state.watch = None
            state.rollbacks[reason] = state.rollbacks.get(reason, 0) + 1
            _M_ROLLBACKS.labels(state.name).inc()
            if state.previous is not None:
                state.deployment, state.instance = state.previous
                state.previous = None
                log.warning("tenant %r: rolled back %s → %s (%s); %s "
                            "pinned", state.name, bad.id,
                            state.instance.id, reason, bad.id)
            else:
                state.deployment = state.instance = None
                try:
                    self._load_tenant_locked(state)
                except Exception as e:  # noqa: BLE001 — tenant-degraded
                    state.degraded = (
                        f"rollback ({reason}) found nothing older "
                        f"deployable: {e}")
                    log.warning("tenant %r: %s", state.name,
                                state.degraded)
                    self._untrack(state)
                    return None
            self._note_foldin_pin(bad, reason)
            self._server._tenant_cache_invalidate(state.name, None)
            return state.deployment

    def _untrack(self, state: TenantState) -> None:
        """A tenant whose deployment went away (failed rollback
        reload) must leave the resident LRU — it holds no model."""
        with self._lock:
            if self._resident_lru.pop(state.name, None) is not None:
                self._parked[state.name] = state
            _M_RESIDENT.set(len(self._resident_lru))

    def _note_foldin_pin(self, instance, reason: str) -> None:
        try:
            from . import online

            if online.is_foldin_instance(instance):
                online.note_rollback(reason)
        except Exception:  # noqa: BLE001 — accounting only
            pass

    # -- per-tenant fold-in ------------------------------------------------
    def foldin_tick(self) -> None:
        """One fold-in pass over every resident tenant (worker thread,
        driven by the server's fold-in loop). Each tenant's tick runs
        under its own lock and failures stay per-tenant — one app's
        storage flake must not starve its neighbors' increments."""
        with self._lock:
            states = list(self._resident_lru.values())
        for state in states:
            try:
                self._foldin_tick_one(state)
            except Exception:  # noqa: BLE001 — next tick retries
                log.exception("tenant %r: fold-in tick failed; "
                              "retrying next tick", state.name)

    def _foldin_tick_one(self, state: TenantState) -> None:
        with state.lock:
            runner = state.foldin
            deployment, instance = state.deployment, state.instance
            pinned = tuple(state.pinned)
        if runner is None or deployment is None or instance is None:
            return
        try:
            view = runner.run_once(deployment, instance, pinned)
        finally:
            state.foldin_view = runner.view()
        if view.get("instance") or view.get("pendingInstance"):
            self._publish_tenant(state)
            state.foldin_view = runner.view()

    def _publish_tenant(self, state: TenantState) -> None:
        """Publish a newer COMPLETED instance of THIS app through the
        tenant's own gate + watch (the per-tenant analogue of the
        server's ``_publish_once``): validation refusal pins per
        tenant, a clean swap retains the previous deployment for the
        watch's instant rollback, and the app-scoped query-cache
        entries are invalidated by the increment's freshness
        footprint."""
        from .create_server import EngineServer, SwapValidationError

        srv = self._server
        with state.lock:
            cur = state.instance
            if cur is None:
                return
            cand = model_artifact.newer_completed_instance(
                srv.storage.get_meta_data_engine_instances(),
                srv.engine_factory_name, srv.engine_variant, cur,
                exclude=set(state.pinned), app_name=state.name)
            if cand is None:
                return
            prev_inst = state.instance
            try:
                self._load_tenant_locked(state, cand.id)
            except SwapValidationError as e:
                state.degraded = (f"fold-in publish refused: {e}; "
                                  f"{e.instance_id} pinned")
                self._note_foldin_pin(cand, "validate")
                log.warning("tenant %r: %s", state.name, state.degraded)
                return
            except Exception as e:  # noqa: BLE001 — next tick retries
                state.degraded = f"fold-in publish failed: {e}"
                log.exception("tenant %r: fold-in publish failed",
                              state.name)
                return
            users = EngineServer._foldin_footprint(state.instance,
                                                   prev_inst)
        srv._tenant_cache_invalidate(state.name, users)

    # -- status surface ----------------------------------------------------
    def snapshot(self) -> dict:
        """The /status "tenants" document (`pio status --engine-url`
        prints the per-tenant table off this)."""
        with self._lock:
            resident = list(self._resident_lru.values())
            parked = [s for s in self._parked.values()
                      if s.queries or s.pinned]
            evictions, cold = self._evictions, self._cold_loads
        rows = ([s.row(True) for s in resident]
                + [s.row(False) for s in parked])
        rows.sort(key=lambda r: r["app"])
        return {
            "maxResident": self.max_resident,
            "maxPending": self.max_pending,
            "resident": len(resident),
            "known": len(rows),
            "evictions": evictions,
            "coldLoads": cold,
            "tenants": rows,
        }
