"""Verified model artifacts — the ONE path between workflow code and the
Models DAO.

Every model blob written by `run_train` is wrapped in a self-describing
envelope (magic + JSON header carrying sha256, payload size and a format
version) and every read re-verifies it, so a truncated, bit-flipped or
half-written artifact is detected at LOAD time instead of surfacing as a
garbage model in production serving. The envelope lives inside the
``Model.models`` bytes, so it round-trips identically through every
Models backend (sqlite blob column, memory dict, localfs file, the HTTP
blob routes, S3/HDFS objects) with no schema migration.

Rules of the house (guard-tested in tests/test_model_lifecycle.py):

- Nothing under ``workflow/`` may call ``get_model_data_models`` except
  this module — all reads go through :func:`read_model` so the
  verification cannot be bypassed (the PR 3/6/8 single-path pattern).
- A blob that fails verification is NEVER deleted (PR 8 quarantine
  discipline: keep the evidence); callers walk back to an older
  COMPLETED instance instead.
- Pre-upgrade blobs (bare pickle, no envelope) are accepted with a
  warning counter — an in-place upgrade must not brick existing
  deployments — but anything that is neither a valid envelope nor a
  pickle is an integrity failure, so a bit-flip inside the envelope
  header can not demote a checksummed artifact to "legacy".

Failure kinds (``pio_model_integrity_failures_total{kind}``):
``missing`` (COMPLETED row without a model — the crash-mid-persist
window), ``header`` (envelope magic/structure damaged), ``version``
(written by a newer format), ``size`` (payload length mismatch —
truncation), ``checksum`` (sha256 mismatch — corruption), and
``deserialize`` (payload verified but unpicklable; counted by the
caller via :func:`count_integrity_failure`).
"""

from __future__ import annotations

import hashlib
import json
import logging
import struct
from typing import Optional

from ..common import faultinject, telemetry
from ..data.storage.base import Model

log = logging.getLogger("pio.model_artifact")

#: Envelope magic. Pickled payloads (protocol 2+) always start with
#: b"\x80", so a stored blob is unambiguously an envelope, a legacy
#: pickle, or damaged.
MAGIC = b"PIOM"
FORMAT_VERSION = 1
_LEN = struct.Struct(">I")

_INTEGRITY_FAILURES = telemetry.registry().counter(
    "pio_model_integrity_failures_total",
    "Model blobs refused by the verifying loader, by failure kind "
    "(missing/header/version/size/checksum/deserialize)",
    ("kind",))
_LEGACY_LOADS = telemetry.registry().counter(
    "pio_model_legacy_loads_total",
    "Pre-checksum model blobs accepted without verification (written "
    "before the envelope format; re-train to upgrade)")


class ModelIntegrityError(RuntimeError):
    """This instance's stored model is not deployable (and why)."""

    def __init__(self, instance_id: str, kind: str, detail: str):
        super().__init__(
            f"model for engine instance {instance_id} is not deployable "
            f"({kind}): {detail}")
        self.instance_id = instance_id
        self.kind = kind


def count_integrity_failure(kind: str) -> None:
    _INTEGRITY_FAILURES.labels(kind).inc()


def integrity_failure_counts() -> dict[str, int]:
    """Process-wide loader refusals by kind (the /status lifecycle
    surface; `pio status --engine-url` prints it without scraping)."""
    return {labels[0]: child.value()
            for labels, child in _INTEGRITY_FAILURES.samples()}


def _fail(instance_id: str, kind: str, detail: str) -> ModelIntegrityError:
    count_integrity_failure(kind)
    return ModelIntegrityError(instance_id, kind, detail)


def compute_sha256(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def wrap(payload: bytes, sha256: Optional[str] = None) -> bytes:
    """Serialized-models payload → checksummed envelope bytes.
    ``sha256`` may be passed when the caller already computed it (big
    blobs: hashing a multi-GB factor matrix twice doubles the
    persistence window's checksum cost)."""
    header = json.dumps({
        "v": FORMAT_VERSION,
        "sha256": sha256 or compute_sha256(payload),
        "size": len(payload),
    }, sort_keys=True).encode()
    return MAGIC + _LEN.pack(len(header)) + header + payload


def describe(blob: Optional[bytes]) -> dict:
    """Non-raising inspection for the `pio models` CLI: classify a
    stored blob without loading it. Returns ``format`` ("v<N>" /
    "legacy" / "invalid"), declared + actual metadata, ``ok`` and the
    failure ``kind`` (None when verified or legacy)."""
    if blob is None:
        return {"format": "missing", "ok": False, "kind": "missing",
                "size": 0, "sha256": None}
    blob = bytes(blob)
    if not blob.startswith(MAGIC):
        if blob[:1] == b"\x80":
            return {"format": "legacy", "ok": True, "kind": None,
                    "size": len(blob), "sha256": None}
        return {"format": "invalid", "ok": False, "kind": "header",
                "size": len(blob), "sha256": None}
    try:
        header, payload = _split(blob)
    except ValueError as e:
        return {"format": "invalid", "ok": False, "kind": "header",
                "size": len(blob), "sha256": None, "detail": str(e)}
    v = header.get("v")
    out = {"format": f"v{v}", "size": header.get("size"),
           "sha256": header.get("sha256"), "ok": True, "kind": None}
    # same classification as unwrap_verified, so `pio models` verdicts,
    # pin reasons, and the per-kind counter all name one kind per blob
    if not isinstance(v, int) or v < 1:
        out.update(ok=False, kind="header")
    elif v > FORMAT_VERSION:
        out.update(ok=False, kind="version")
    elif len(payload) != header.get("size"):
        out.update(ok=False, kind="size", actual_size=len(payload))
    elif compute_sha256(payload) != header.get("sha256"):
        out.update(ok=False, kind="checksum")
    return out


def _split(blob: bytes) -> tuple[dict, bytes]:
    """Envelope bytes → (header dict, payload). Raises ValueError on any
    structural damage."""
    if len(blob) < len(MAGIC) + _LEN.size:
        raise ValueError("envelope shorter than its fixed header")
    (hlen,) = _LEN.unpack_from(blob, len(MAGIC))
    start = len(MAGIC) + _LEN.size
    if hlen <= 0 or start + hlen > len(blob):
        raise ValueError(f"envelope header length {hlen} out of range")
    try:
        header = json.loads(blob[start:start + hlen])
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"envelope header is not JSON: {e}") from None
    if not isinstance(header, dict):
        raise ValueError("envelope header is not an object")
    return header, blob[start + hlen:]


def unwrap_verified(blob: bytes, instance_id: str) -> bytes:
    """Envelope bytes → verified payload. Legacy (pre-envelope) pickles
    are accepted with a warning counter; everything else must verify.
    Raises :class:`ModelIntegrityError` (and counts the kind) on any
    mismatch. Never mutates or deletes the stored blob."""
    blob = bytes(blob)
    if not blob.startswith(MAGIC):
        if blob[:1] == b"\x80":
            # Pre-upgrade artifact: no metadata to verify. Accepted —
            # refusing would brick every deployment on upgrade day —
            # but counted, so operators can see unverifiable models.
            _LEGACY_LOADS.labels().inc()
            log.warning(
                "model for engine instance %s predates checksummed "
                "artifacts; loading unverified (re-train to upgrade)",
                instance_id)
            return blob
        raise _fail(instance_id, "header",
                    f"blob is neither an envelope nor a pickle "
                    f"(first bytes {blob[:8]!r})")
    try:
        header, payload = _split(blob)
    except ValueError as e:
        raise _fail(instance_id, "header", str(e)) from None
    v = header.get("v")
    if not isinstance(v, int) or v < 1:
        raise _fail(instance_id, "header", f"bad format version {v!r}")
    if v > FORMAT_VERSION:
        raise _fail(instance_id, "version",
                    f"written by format v{v}, this build reads up to "
                    f"v{FORMAT_VERSION}")
    if len(payload) != header.get("size"):
        raise _fail(instance_id, "size",
                    f"payload is {len(payload)} bytes, header declares "
                    f"{header.get('size')} (truncated or overwritten)")
    actual = compute_sha256(payload)
    if actual != header.get("sha256"):
        raise _fail(instance_id, "checksum",
                    f"sha256 {actual[:12]}… does not match declared "
                    f"{str(header.get('sha256'))[:12]}… (corruption)")
    return payload


# ---------------------------------------------------------------------------
# The DAO chokepoints (guard: the only Models access under workflow/)
# ---------------------------------------------------------------------------


def write_model(storage, instance_id: str, payload: bytes) -> str:
    """Persist a trained payload as a checksummed artifact; returns the
    payload's sha256 hex (computed exactly once) so callers can log it.
    The ``model.insert`` fault point sits in front of the DAO write —
    the crash harness uses it to SIGKILL a train inside the persistence
    window."""
    sha = compute_sha256(payload)
    faultinject.fault_point("model.insert")
    storage.get_model_data_models().insert(
        Model(instance_id, wrap(payload, sha)))
    return sha


def read_model(storage, instance_id: str) -> bytes:
    """Fetch + verify the stored model payload for an instance.
    Raises :class:`ModelIntegrityError` (kind="missing") when the row
    does not exist — a COMPLETED instance without a model is exactly
    the crash-mid-persist state the loader must skip, not serve."""
    row = storage.get_model_data_models().get(instance_id)
    if row is None:
        raise _fail(instance_id, "missing",
                    "no model row (crash between train and persistence, "
                    "or GC'd)")
    return unwrap_verified(row.models, instance_id)


def get_model_row(storage, instance_id: str) -> Optional[Model]:
    """Raw row fetch for inspection tooling (`pio models`): no
    verification, no counters."""
    return storage.get_model_data_models().get(instance_id)


def model_exists(storage, instance_id: str) -> bool:
    """Row-existence probe (no blob transfer on backends that can
    check metadata) — `pio models gc` ranks with this instead of
    reading every artifact."""
    return storage.get_model_data_models().exists(instance_id)


def delete_model(storage, instance_id: str) -> None:
    """GC chokepoint (`pio models gc`). Deliberately NOT called by any
    failure path — corrupt blobs are kept for forensics."""
    storage.get_model_data_models().delete(instance_id)


# ---------------------------------------------------------------------------
# fleet coordination records (workflow/fleet.py + the fleet-aware
# engine server). The replica fleet coordinates its staged rollout
# through the SAME artifact store the models live in — no new
# coordination service — as small JSON rows in the Models DAO under
# reserved ids that can never collide with engine-instance ids. Every
# row has exactly ONE writer (the front owns the directive record, each
# replica owns its own status row — the single-writer half of the
# event-log lease idiom), and the directive carries a monotonically
# bumped epoch so readers can order observations and a superseded
# coordinator can detect it has been overtaken.
# ---------------------------------------------------------------------------

#: Reserved id prefix. Engine-instance ids are event-id hex strings, so
#: a dunder prefix cannot collide; `pio models list|verify|gc` iterate
#: ENGINE INSTANCES and never see these rows.
FLEET_ROW_PREFIX = "__pio_fleet__"

#: Reserved id prefix of the streaming fold-in cursor records
#: (workflow/online.py): one row per (fleet group, app), single writer
#: (the fold-in producer), same plain-JSON envelope-free shape as the
#: fleet records above.
FOLDIN_ROW_PREFIX = "__pio_foldin__"


def foldin_row_id(group: str, app_id: int) -> str:
    """Storage row id of one fold-in cursor record: the durable
    LSN/byte cursor (plus freshness bookkeeping) the online-learning
    tailer resumes from after a restart."""
    return f"{FOLDIN_ROW_PREFIX}{group}__a{int(app_id)}"


def instance_app_name(instance) -> str:
    """The app an engine-instance row is bound to, or "". The ONE
    app-binding rule the multi-tenant walk-back, the fold-in tailer and
    the per-app fleet all share: ``env["appName"]`` (stamped by
    ``run_train`` from the training context) wins; the data-source
    params' ``appName``/``app_name`` is the fallback for rows trained
    before the env stamp existed."""
    try:
        name = (instance.env or {}).get("appName")
        if name:
            return str(name)
        doc = json.loads(instance.data_source_params or "{}")
        if isinstance(doc, dict):
            return str(doc.get("appName") or doc.get("app_name") or "")
    except Exception:  # noqa: BLE001 — unparseable row binds nowhere
        pass
    return ""


def newer_completed_instance(instances, engine_factory_name: str,
                             engine_variant: str, current,
                             exclude=(), app_name: Optional[str] = None):
    """Newest COMPLETED instance not in ``exclude`` and strictly newer
    than ``current`` (an instance row, an instance id, or None), else
    None. The ONE definition of "a newer deployable candidate" — the
    fleet coordinator's rollout staging and the engine server's refresh
    poll must never disagree about what "newer" means (an instances-DAO
    helper, but it lives here with the other fleet/lifecycle protocol
    pieces both sides already import). With ``app_name`` the candidate
    walk is confined to ONE app's instances — the instances namespace
    is (factory, version, variant), NOT app-keyed, so a multi-tenant
    store interleaves every app's rows in one completed list."""
    done = instances.get_completed(
        engine_factory_name or "engine", "1", engine_variant)
    cur_row = (instances.get(current) if isinstance(current, str)
               else current)
    for c in done:
        if app_name is not None and instance_app_name(c) != app_name:
            continue
        if c.id in exclude:
            continue
        if cur_row is not None and (
                c.id == cur_row.id
                or c.start_time <= cur_row.start_time):
            return None
        return c
    return None


def fleet_fresh_s(sync_ms: float) -> float:
    """Staleness horizon for a replica status row: rows older than this
    are a dead/wedged replica's. The ONE definition — the coordinator's
    promote/adoption votes and `pio status`'s STALE warn-marker must
    agree on what "fresh" means (5 sync ticks, floored at 10 s)."""
    return max(10.0, float(sync_ms) / 1000.0 * 5)


def fleet_group(engine_factory_name: str, engine_variant: str,
                app_name: Optional[str] = None) -> str:
    """Canonical fleet group id — the ONE definition both sides of the
    store protocol derive row keys from. A coordinator and its replicas
    computing this independently (and drifting) would silently split
    the fleet: directives written under one key, polled under another,
    with no error anywhere (missing rows read as None). An app-scoped
    coordinator (multi-tenant serving) appends its app dimension so
    per-app directive/cursor rows can never collide with the default
    group's — "::" cannot appear in a registered app name's slot
    without changing the key, and the bare group never ends in the
    ``::app=`` marker."""
    group = f"{engine_factory_name or 'engine'}::{engine_variant}"
    return group if not app_name else f"{group}::app={app_name}"


def fleet_row_id(group: str, replica: Optional[int] = None) -> str:
    """Storage row id of a fleet record: the group's directive record
    (``replica=None``, written only by the coordinator) or one
    replica's status row (written only by that replica)."""
    base = f"{FLEET_ROW_PREFIX}{group}"
    return base if replica is None else f"{base}__r{int(replica)}"


def read_fleet_doc(storage, row_id: str) -> Optional[dict]:
    """Fetch one fleet record. Any damage (unreadable row, non-JSON
    bytes) degrades to None — fleet coordination must converge through
    the next write, never crash serving on a torn record."""
    try:
        row = storage.get_model_data_models().get(row_id)
        if row is None:
            return None
        doc = json.loads(bytes(row.models).decode("utf-8"))
        return doc if isinstance(doc, dict) else None
    except Exception:  # noqa: BLE001 — degraded read, next write heals
        log.warning("fleet record %s unreadable; treating as absent",
                    row_id, exc_info=True)
        return None


def write_fleet_doc(storage, row_id: str, doc: dict,
                    fault: bool = False) -> None:
    """Persist one fleet record (plain JSON bytes — these rows are
    coordination state, not model artifacts, so they skip the envelope
    and its integrity counters). ``fault=True`` (the coordinator's
    DIRECTIVE writes) arms the ``fleet.record`` fault point so the
    chaos harness can fail a directive commit and prove the state
    machine retries; replica status writes skip it so an injected
    coordinator fault cannot leak onto replica processes."""
    if fault:
        faultinject.fault_point("fleet.record")
    storage.get_model_data_models().insert(
        Model(row_id, json.dumps(doc, sort_keys=True).encode("utf-8")))
