"""Streaming double-buffered host→device input pipeline.

BASELINE.json's decomposition shows the on-chip NB pass at ~420M
events/sec while end-to-end ``Engine.train`` delivers 43M — and at the
16M×32 sweep point the TPU path collapses to ~545k events/sec — because
host featurization, event decode, and device upload run SERIALLY before
any compute starts. This module overlaps the three stages:

- **featurize** — background worker threads (``prefetch``) pull work
  items from a batch iterator (the event store's chunked scans, a slice
  schedule over a materialized matrix, a document corpus) and produce
  fixed-size host chunks, with optional lossless bf16/int narrowing on
  the wire;
- **upload** — async ``jax.device_put`` of each chunk into a small ring
  of device buffers (``run_pipeline`` bounds the in-flight count, and
  consumers donate the chunk buffers so steady-state HBM stays at
  ``depth`` chunks + the accumulator);
- **compute** — the consume callback dispatches the per-chunk device
  program for chunk N while chunk N+1 uploads and chunk N+2 featurizes.

The design follows the overlapped-transfer lesson of the ALX and
TensorFlow system papers (arxiv 2112.02194, 1605.08695): an accelerator
that waits for its input pipeline is idle silicon, and the fix is a
bounded producer/consumer ring, not a bigger batch.

Knobs (env, overridable per-call via ``PipelineConfig``):

- ``PIO_PIPELINE``        — ``auto`` (default: stream when the input is
  at least two chunks long), ``1``/``on`` (force), ``0``/``off``
  (single-shot fallback — the guard-tested exact path).
- ``PIO_PIPELINE_CHUNK``  — rows per chunk (default 1_000_000).
- ``PIO_PIPELINE_DEPTH``  — device buffer ring depth (default 2:
  double-buffered).
- ``PIO_PIPELINE_WORKERS``— host featurize worker threads (default 2).

Multi-process (multi-controller) runs fall back to single-shot: their
arrays are built with ``jax.make_array_from_callback`` and every process
must agree on the layout, which a per-process stream cannot guarantee.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from ..common import envknobs, telemetry

# Last-run pipeline stage gauges (training is episodic, so the natural
# exposition is "the most recent run's decomposition", not a histogram
# of runs): per-stage busy seconds plus the overlap-efficiency ratio
# the bench derives from the same PipelineStats fields.
_M_STAGE = telemetry.registry().gauge(
    "pio_pipeline_stage_seconds",
    "Input-pipeline stage busy seconds for the most recent streamed "
    "train (featurize/upload/consume are per-stage sums, wall is "
    "end-to-end)", ("stage",))
_M_CHUNKS = telemetry.registry().gauge(
    "pio_pipeline_chunks",
    "Chunks streamed by the most recent pipelined train")
_M_EFFICIENCY = telemetry.registry().gauge(
    "pio_pipeline_overlap_efficiency",
    "wall / max(stage) for the most recent streamed train (1.0 = "
    "perfect stage overlap, higher = serialization waste)")

__all__ = [
    "PipelineConfig",
    "PipelineStats",
    "PipelineWorkerError",
    "pipeline_of",
    "prefetch",
    "run_pipeline",
    "host_parallel",
    "chunk_ranges",
]


def pipeline_of(ctx) -> Optional["PipelineConfig"]:
    """Streaming-input config from a workflow context (None → callers
    resolve from env); tolerates the bare test contexts that predate
    WorkflowContext.get_input_pipeline."""
    getter = getattr(ctx, "get_input_pipeline", None) if ctx else None
    return getter() if callable(getter) else None


DEFAULT_CHUNK_ROWS = 1_000_000
DEFAULT_CHUNK_DOCS = 16_384
DEFAULT_DEPTH = 2
DEFAULT_WORKERS = 2


class PipelineWorkerError(RuntimeError):
    """A featurize worker raised; the original exception is __cause__."""


def _env_int(name: str, default: int, lo: int = 1, hi: int = 1 << 30) -> int:
    # Warn-and-clamp semantics; one shared implementation: common/envknobs.
    return envknobs.env_int(name, default, lo=lo, hi=hi, warn=True)


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Resolved streaming knobs. ``mode`` ∈ {'auto', 'on', 'off'}."""

    mode: str = "auto"
    chunk_rows: int = DEFAULT_CHUNK_ROWS
    #: chunk size when a "row" is a document (text featurize: the host
    #: cost per row is ~3 orders of magnitude higher than an attribute
    #: row, so chunks are correspondingly smaller)
    chunk_docs: int = DEFAULT_CHUNK_DOCS
    depth: int = DEFAULT_DEPTH
    workers: int = DEFAULT_WORKERS

    @classmethod
    def from_env(cls, mode: Optional[str] = None) -> "PipelineConfig":
        raw = (mode or envknobs.env_str("PIO_PIPELINE", "auto")).strip().lower()
        if raw in ("1", "on", "true", "yes"):
            raw = "on"
        elif raw in ("0", "off", "false", "no"):
            raw = "off"
        elif raw != "auto":
            import warnings

            warnings.warn(
                f"PIO_PIPELINE={raw!r}: expected auto/on/off; using auto",
                stacklevel=2)
            raw = "auto"
        return cls(
            mode=raw,
            chunk_rows=_env_int("PIO_PIPELINE_CHUNK", DEFAULT_CHUNK_ROWS),
            chunk_docs=_env_int("PIO_PIPELINE_CHUNK_DOCS",
                                DEFAULT_CHUNK_DOCS),
            depth=_env_int("PIO_PIPELINE_DEPTH", DEFAULT_DEPTH, lo=1, hi=64),
            workers=_env_int("PIO_PIPELINE_WORKERS", DEFAULT_WORKERS,
                             lo=1, hi=64),
        )

    def enabled_for(self, n_rows: int, chunk: Optional[int] = None) -> bool:
        """Should this input stream? ``auto`` streams only on an
        accelerator backend (on CPU there is no host→device transfer to
        overlap — same gate as the wire-narrowing casts) and only when
        there are at least two full chunks (below that the single-shot
        path's one put is already optimal); never under multi-controller
        jax (see module docstring). ``mode='on'`` forces streaming
        anywhere — the CPU bit-identity guard tests rely on it.
        ``chunk`` overrides the row chunk size for inputs measured in
        other units (documents)."""
        if self.mode == "off":
            return False
        try:
            import jax

            if jax.process_count() > 1:
                return False
            if self.mode == "on":
                return n_rows > 0
            if jax.default_backend() == "cpu":
                return False
        except Exception:  # noqa: BLE001 - no jax → nothing to stream to
            return False
        return n_rows >= 2 * (self.chunk_rows if chunk is None else chunk)


@dataclasses.dataclass
class PipelineStats:
    """Per-run stage accounting (bench-grade, best effort).

    ``featurize_seconds`` sums time INSIDE worker featurize calls (the
    host-stage busy time, not wall); ``upload_seconds`` sums the
    device_put enqueue calls; ``consume_seconds`` sums the compute
    dispatch calls; ``wall_seconds`` is end-to-end. With perfect overlap
    ``wall ≈ max(stage)``; the bench derives its overlap-efficiency
    ratio from exactly these numbers."""

    n_chunks: int = 0
    featurize_seconds: float = 0.0
    upload_seconds: float = 0.0
    consume_seconds: float = 0.0
    wall_seconds: float = 0.0
    max_inflight: int = 0

    def _add_featurize(self, dt: float) -> None:
        # workers call this concurrently; += on a float is not atomic
        with self._lock:
            self.featurize_seconds += dt

    def __post_init__(self):
        self._lock = threading.Lock()

    def publish(self) -> None:
        """Export this run's decomposition to the telemetry registry
        (gauges — last run wins; see the family docstrings)."""
        _M_STAGE.labels("featurize").set(self.featurize_seconds)
        _M_STAGE.labels("upload").set(self.upload_seconds)
        _M_STAGE.labels("consume").set(self.consume_seconds)
        _M_STAGE.labels("wall").set(self.wall_seconds)
        _M_CHUNKS.labels().set(self.n_chunks)
        max_stage = max(self.featurize_seconds, self.upload_seconds,
                        self.consume_seconds)
        if max_stage > 0:
            _M_EFFICIENCY.labels().set(self.wall_seconds / max_stage)


def chunk_ranges(n_rows: int, chunk_rows: int) -> list[tuple[int, int]]:
    """[(start, stop), ...] covering [0, n_rows) in chunk_rows steps."""
    if n_rows <= 0:
        return []
    step = max(1, int(chunk_rows))
    return [(s, min(s + step, n_rows)) for s in range(0, n_rows, step)]


def prefetch(
    items: Iterable[Any],
    fn: Callable[[Any], Any],
    workers: int = DEFAULT_WORKERS,
    lookahead: int = DEFAULT_DEPTH,
    stats: Optional[PipelineStats] = None,
) -> Iterator[Any]:
    """Yield ``fn(item)`` in order, computed by background threads.

    At most ``lookahead`` results are completed-or-running ahead of the
    consumer (backpressure: a slow consumer stalls the workers instead
    of accumulating unbounded host chunks). A worker exception is
    re-raised at the corresponding yield point as PipelineWorkerError
    (original as ``__cause__``); remaining work is cancelled. Closing
    the generator mid-stream (``gen.close()`` / loop break) cancels
    pending work and joins the pool — no leaked threads.

    Worker threads genuinely overlap featurize with upload/compute when
    the featurize body releases the GIL (large-array numpy casts, the
    ctypes calls into the native tokenizer/codec).
    """
    from concurrent.futures import ThreadPoolExecutor

    items = iter(items)
    bound = max(1, int(lookahead))

    def timed_fn(item):
        t0 = time.perf_counter()
        out = fn(item)
        if stats is not None:
            stats._add_featurize(time.perf_counter() - t0)
        return out

    pool = ThreadPoolExecutor(max_workers=max(1, int(workers)),
                              thread_name_prefix="pio-pipeline")
    pending: collections.deque = collections.deque()
    try:
        exhausted = False
        while True:
            while not exhausted and len(pending) < bound:
                try:
                    item = next(items)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(pool.submit(timed_fn, item))
            if not pending:
                break
            fut = pending.popleft()
            try:
                result = fut.result()
            except Exception as e:  # noqa: BLE001 - re-raise with context
                raise PipelineWorkerError(
                    f"input-pipeline featurize worker failed: {e}") from e
            yield result
    finally:
        for fut in pending:
            fut.cancel()
        pool.shutdown(wait=True, cancel_futures=True)


def run_pipeline(
    host_chunks: Iterable[Any],
    upload: Callable[[Any], Any],
    consume: Callable[[Any], Any],
    depth: int = DEFAULT_DEPTH,
    stats: Optional[PipelineStats] = None,
) -> int:
    """Drive the double-buffered upload/consume loop; returns #chunks.

    ``upload(host_chunk)`` starts the async host→device transfer and
    returns the device chunk; ``consume(dev_chunk)`` dispatches the
    per-chunk device program and returns a *token* (any jax array of the
    dispatch, e.g. the running accumulator). Both return immediately —
    jax transfers and dispatches are async — so the loop's only blocking
    point is the ring bound: before uploading chunk N, it blocks on the
    token of chunk N−depth. Combined with consume donating its chunk
    buffers, that caps live HBM at ~``depth + 1`` chunks plus
    accumulator regardless of stream length.

    Exceptions (from the chunk iterator, upload, or consume) propagate
    to the caller after in-flight tokens are drained best-effort; the
    ``host_chunks`` generator is closed either way, which is what stops
    ``prefetch`` workers mid-stream.
    """
    inflight: collections.deque = collections.deque()
    bound = max(1, int(depth))
    n = 0
    t_start = time.perf_counter()
    try:
        for hc in host_chunks:
            if len(inflight) >= bound:
                _block_on(inflight.popleft())
            t0 = time.perf_counter()
            dev = upload(hc)
            if stats is not None:
                stats.upload_seconds += time.perf_counter() - t0
            del hc  # the host buffer is the transfer's source; drop our ref
            t0 = time.perf_counter()
            token = consume(dev)
            if stats is not None:
                stats.consume_seconds += time.perf_counter() - t0
            del dev
            inflight.append(token)
            n += 1
            if stats is not None:
                stats.n_chunks = n
                stats.max_inflight = max(stats.max_inflight, len(inflight))
        while inflight:
            _block_on(inflight.popleft())
    finally:
        close = getattr(host_chunks, "close", None)
        if callable(close):
            close()
        if stats is not None:
            stats.wall_seconds = time.perf_counter() - t_start
            stats.publish()
    return n


def _block_on(token) -> None:
    if token is None:
        return
    import jax

    jax.block_until_ready(token)


def host_parallel(*thunks: Callable[[], Any]) -> list:
    """Run independent host-side thunks on worker threads, return their
    results in order. Used for coarse-grained overlap where a stream
    does not fit — e.g. ALS filling the user-side and item-side bucket
    slabs concurrently (the native fill and numpy argsort both release
    the GIL). Exceptions propagate (first failure wins); all threads are
    joined before returning either way."""
    if len(thunks) <= 1:
        return [t() for t in thunks]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=len(thunks),
                            thread_name_prefix="pio-hostpar") as pool:
        futs = [pool.submit(t) for t in thunks]
        return [f.result() for f in futs]
