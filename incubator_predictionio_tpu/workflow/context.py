"""WorkflowContext — what `ctx` means inside DASE components.

The reference passes a SparkContext through every DASE call
(reference: core/.../workflow/WorkflowContext.scala). The TPU-native
context carries the device mesh + storage registry + app binding instead:
everything a component needs to read events and place arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ..data.storage.registry import Storage
from ..workflow.workflow_params import WorkflowParams

_cache_enabled = False


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache under $PIO_FS_BASEDIR/xla_cache.

    Every `pio` verb is its own process; without this each train/deploy
    re-pays the full XLA compile (tens of seconds on TPU) for programs
    compiled identically last run. Wired here — every compiling verb
    builds a WorkflowContext, and jax is already imported by then —
    because this jax version ignores the JAX_COMPILATION_CACHE_DIR env
    var, so the config call is required and metadata-only verbs should
    not import jax just to make it. PIO_COMPILATION_CACHE=0 opts out;
    sub-second compiles are skipped by JAX's default
    jax_persistent_cache_min_compile_time_secs=1.
    """
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True
    from ..common import envknobs

    if not envknobs.env_flag("PIO_COMPILATION_CACHE", True):
        return
    try:
        import jax

        from ..data.storage.registry import base_dir

        cache_dir = os.path.join(base_dir(), "xla_cache")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:  # noqa: BLE001 - cache is an optimization only
        pass


@dataclasses.dataclass
class WorkflowContext:
    app_name: str = ""
    channel_name: Optional[str] = None
    storage: Optional[Storage] = None
    mesh: Any = None  # jax.sharding.Mesh; lazily built to keep import light
    workflow_params: WorkflowParams = dataclasses.field(default_factory=WorkflowParams)
    engine_instance_id: Optional[str] = None
    # workflow.checkpoint.CheckpointHook when `pio train --checkpoint-every`
    # / `--resume` is active; algorithms with iterative loops snapshot
    # through it (see ops/als.py train_als).
    checkpoint_hook: Any = None
    # workflow.input_pipeline.PipelineConfig — resolved lazily from
    # WorkflowParams + the PIO_PIPELINE_* envs (get_input_pipeline);
    # algorithms pass it to the streaming trainers.
    input_pipeline: Any = None

    def __post_init__(self):
        _enable_compilation_cache()

    def get_storage(self) -> Storage:
        return self.storage or Storage.instance()

    def get_mesh(self):
        if self.mesh is None:
            from ..parallel.mesh import default_mesh

            self.mesh = default_mesh()
        return self.mesh

    def get_input_pipeline(self):
        """Resolved streaming-input config: WorkflowParams fields win
        over the PIO_PIPELINE_* envs, envs over built-in defaults."""
        if self.input_pipeline is None:
            import dataclasses as _dc

            from .input_pipeline import PipelineConfig

            wp = self.workflow_params
            cfg = PipelineConfig.from_env(mode=wp.pipeline or None)
            over = {}
            if wp.pipeline_chunk > 0:
                over["chunk_rows"] = wp.pipeline_chunk
            if wp.pipeline_depth > 0:
                over["depth"] = wp.pipeline_depth
            if wp.pipeline_workers > 0:
                over["workers"] = wp.pipeline_workers
            self.input_pipeline = _dc.replace(cfg, **over) if over else cfg
        return self.input_pipeline
