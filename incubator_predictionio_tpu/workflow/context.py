"""WorkflowContext — what `ctx` means inside DASE components.

The reference passes a SparkContext through every DASE call
(reference: core/.../workflow/WorkflowContext.scala). The TPU-native
context carries the device mesh + storage registry + app binding instead:
everything a component needs to read events and place arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ..data.storage.registry import Storage
from ..workflow.workflow_params import WorkflowParams


@dataclasses.dataclass
class WorkflowContext:
    app_name: str = ""
    channel_name: Optional[str] = None
    storage: Optional[Storage] = None
    mesh: Any = None  # jax.sharding.Mesh; lazily built to keep import light
    workflow_params: WorkflowParams = dataclasses.field(default_factory=WorkflowParams)
    engine_instance_id: Optional[str] = None
    # workflow.checkpoint.CheckpointHook when `pio train --checkpoint-every`
    # / `--resume` is active; algorithms with iterative loops snapshot
    # through it (see ops/als.py train_als).
    checkpoint_hook: Any = None

    def get_storage(self) -> Storage:
        return self.storage or Storage.instance()

    def get_mesh(self):
        if self.mesh is None:
            from ..parallel.mesh import default_mesh

            self.mesh = default_mesh()
        return self.mesh
