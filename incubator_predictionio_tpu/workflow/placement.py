"""Cost-based device placement for training (`pio train --device`).

Reference contract: tools/.../tools/Runner.scala — "run where configured
to be fastest" (the reference delegates the choice to deploy-time Spark
configuration). TPU-native version: the choice is MEASURED, per workload,
at train time. Through a remote-PJRT tunnel the host→device put rate can
be ~35 MB/s while host RAM streams at GB/s, so a single-pass,
transfer-bound train (NB sufficient stats, TF-IDF featurize) can lose to
the host CPU by 10x+ — dispatching it to the accelerator anyway is
"run where configured", not "run where fastest" (BASELINE.md crossover
tables, VERDICT r4 missing #2).

Model: an algorithm describes its workload as a StageModel (bytes that
must reach the device, number of algorithmic passes over them there,
bytes the CPU path would stream instead); this module prices both
placements with rates MEASURED ONCE per process (a timed device_put for
the link, a timed numpy pass for host bandwidth) and picks the cheaper,
logged and overridable (--device=tpu|cpu|auto).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

from ..common import envknobs

log = logging.getLogger("pio.placement")

#: Sustained on-device bandwidth assumed for pass pricing when the
#: accelerator is real (HBM-class); deliberately conservative — the
#: decision is dominated by the measured link rate, this term only keeps
#: many-pass workloads (ALS, CCO) priced sub-linearly on device.
_DEVICE_PASS_BPS = 200e9
_PROBE_BYTES = 8 * 1024 * 1024


@dataclasses.dataclass(frozen=True)
class StageModel:
    """What a train stage would move and touch, in bytes.

    bytes_to_device: one-time upload the accelerator path needs.
    device_passes:  algorithmic passes over those bytes on device.
    host_bytes:     bytes the CPU path streams instead (usually the same
                    data, possibly wider/narrower).
    cpu_passes:     passes over host_bytes on the CPU path.
    """

    bytes_to_device: int
    device_passes: float = 1.0
    host_bytes: Optional[int] = None
    cpu_passes: float = 1.0

    @property
    def effective_host_bytes(self) -> int:
        return self.bytes_to_device if self.host_bytes is None else self.host_bytes


_rates: dict = {}


def _measured_put_bps() -> float:
    """Host→default-device transfer rate, measured once per process
    (8 MB put + block). Through the sandbox tunnel this lands ~35 MB/s;
    host-attached chips measure GB/s — the decision flips with it."""
    if "put" not in _rates:
        import jax
        import jax.numpy as jnp
        import numpy as np

        try:
            dev = jax.devices()[0]
            # Run ONE trivial executable first: remote-PJRT tunnels serve
            # a fast transfer mode only until the first executable runs
            # (measured 1.5 GB/s before vs 4–53 MB/s after on this
            # sandbox), and every real train runs executables — probing
            # the pre-executable mode would overstate the link ~50x and
            # mis-place every transfer-bound stage onto the accelerator.
            jax.block_until_ready(
                jax.jit(lambda v: v + 1)(jnp.zeros(8, jnp.float32)))
            buf = np.empty(_PROBE_BYTES, np.uint8)
            # warm BOTH the put path and the x[:1] barrier executable —
            # a first-time slice compile inside the timed window would
            # bill a compile round-trip to the link rate
            warm = jax.device_put(buf, dev)
            _ = jax.device_get(warm[:1])
            t0 = time.perf_counter()
            x = jax.device_put(buf, dev)
            # device_get is the only true completion barrier through the
            # tunnel (block_until_ready can return early)
            _ = jax.device_get(x[:1])
            dt = max(time.perf_counter() - t0, 1e-6)
            _rates["put"] = _PROBE_BYTES / dt
        except Exception:  # noqa: BLE001 - no usable device → pessimal link
            _rates["put"] = 1.0
    return _rates["put"]


def _measured_cpu_bps() -> float:
    """Host streaming rate, measured once (one numpy reduction pass)."""
    if "cpu" not in _rates:
        import numpy as np

        buf = np.empty(_PROBE_BYTES // 4, np.float32)
        buf.sum()  # touch/fault pages
        t0 = time.perf_counter()
        buf.sum()
        dt = max(time.perf_counter() - t0, 1e-6)
        _rates["cpu"] = _PROBE_BYTES / dt
    return _rates["cpu"]


def _default_is_cpu() -> bool:
    import jax

    return jax.devices()[0].platform == "cpu"


def validate_device_mode(mode: str) -> str:
    if mode not in ("tpu", "cpu", "auto"):
        raise ValueError(f"--device={mode!r}: expected tpu|cpu|auto")
    return mode


def choose(model: Optional[StageModel], mode: str, stage: str = "") -> str:
    """"cpu" or "device" for this stage. mode: tpu|cpu|auto."""
    validate_device_mode(mode)
    if mode == "tpu":
        return "device"
    if mode == "cpu":
        return "cpu"
    if model is None or _default_is_cpu():
        return "device"  # nothing to compare (or default IS the cpu)
    put = _measured_put_bps()
    cpu = _measured_cpu_bps()
    t_dev = (model.bytes_to_device / put
             + model.device_passes * model.bytes_to_device / _DEVICE_PASS_BPS)
    t_cpu = model.cpu_passes * model.effective_host_bytes / cpu
    pick = "device" if t_dev <= t_cpu else "cpu"
    log.info(
        "placement%s: %s (est device %.3fs [link %.0f MB/s] vs cpu %.3fs "
        "[%.1f GB/s], %.1f MB to move)",
        f" {stage}" if stage else "", pick, t_dev, put / 1e6, t_cpu,
        cpu / 1e9, model.bytes_to_device / 1e6)
    return pick


def cpu_mesh():
    """1-D mesh over the host CPU devices (the forced/auto-CPU target)."""
    import jax

    from ..parallel.mesh import mesh_from_devices

    return mesh_from_devices(devices=jax.devices("cpu"))


def mesh_for_stage(ctx, model: Optional[StageModel], mode: str, stage: str):
    """The mesh an algorithm should train on under the given --device
    mode. Multi-process runs always use the configured mesh — every
    process must join the same collectives, so per-stage re-placement
    would wedge the job."""
    import jax

    validate_device_mode(mode)
    if jax.process_count() > 1:
        if mode != "tpu":
            # NOT silent: the user asked for cpu/auto but multi-process
            # collectives require every process on the configured mesh
            log.warning(
                "placement%s: --device=%s ignored in a %d-process run — "
                "all processes must join the configured mesh's collectives",
                f" {stage}" if stage else "", mode, jax.process_count())
        return ctx.get_mesh()
    if mode == "tpu":
        return ctx.get_mesh()
    if choose(model, mode, stage) == "cpu":
        return cpu_mesh()
    return ctx.get_mesh()


def device_mode_from_env(default: str = "auto") -> str:
    """PIO_TRAIN_DEVICE env tier (engine.json/CLI win over it). An
    invalid env value warns and falls back — a typo must not surface as
    a mid-training crash minutes later."""
    v = envknobs.env_str("PIO_TRAIN_DEVICE", default) or default
    try:
        return validate_device_mode(v)
    except ValueError:
        log.warning("PIO_TRAIN_DEVICE=%r is not tpu|cpu|auto; using %r",
                    v, default)
        return default
