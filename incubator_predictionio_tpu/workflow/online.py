"""Streaming online learning: log-tailing fold-in with gated publish.

ROADMAP item 2, the TensorFlow unified-train/serve argument (arxiv
1605.08695) applied to this stack: PR 9 made model refresh *safe*
(validation gate, post-swap watch, rollback + pin) and PR 12 made it
*fleet-aware* (staged canary), but what refreshed was still a full
retrain — a new user's first events did nothing until the next
`pio train`. This module closes the gap incrementally:

1. **Tail** the deployed app's partitioned event log through a durable
   byte cursor (``data/api/log_tail.py`` — O(new bytes), colseg-seeded
   cold reads, restart-resumable via a reserved Models-DAO row).
2. **Fold** the new events into a COPY of the live models through each
   algorithm's ``fold_in`` hook (closed-form per-user/per-item ridge
   against fixed opposite-side factors for ALS — arxiv 2112.02194's
   fold-in recipe on ``ops/als.py``'s gram/solve kernels; exact
   count increments for NB; online SGD for LR).
3. **Commit** the increment as a brand-new COMPLETED engine instance —
   checksummed envelope via ``model_artifact.write_model``, provenance
   (source instance, event count, LSN) in ``runtime_conf["foldin"]`` —
   so the increment is indistinguishable from a retrain to every
   consumer downstream. The marker also carries the increment's
   **freshness footprint** for the serving-side query cache: ``bases``
   (every ancestor instance id the chain folded through) and ``users``
   (the user entity ids whose rows this chain re-solved — present only
   when the batches were attributable to specific users). The engine
   server uses them at swap time to invalidate exactly the touched
   users' cached results instead of flushing the whole cache; any
   batch whose effect can't be pinned to users (non-user events, or
   more than the cap) omits ``users`` and forces the full flush.
4. **Publish through the SAME gate as a retrain.** Single-server mode:
   the engine server's shared publish-through-gate path (the PR 9
   validate → swap → watch → rollback+pin sequence — one entry point,
   ``EngineServer._publish_once``, shared with the refresh loop so the
   two can never drift). Fleet mode: the producer (replica 0) only
   commits the instance row; PR 12's coordinator discovers it as "a
   newer COMPLETED instance" and stages it as a CANARY — a poisoned
   fold-in burns one replica's watch window, pins, and the fleet never
   serves it.

Delivery semantics are **at-least-once**: the cursor commits AFTER the
increment's instance row, so a crash anywhere in between re-folds the
same events on restart (for ALS the proximal re-solve makes a
double-fold a mild re-weighting, for NB a double-count — both bounded
by one increment and strictly better than losing events; exactly-once
would need a transactional store the DAO contract doesn't offer).
While an increment's publication is DEFERRED (fleet canary staging, a
busy local gate), the next increment CHAINS onto it instead of the
served model — otherwise each increment would be built from the stale
base and the earlier batches' events would vanish the moment the
newest one publishes. A chain through a pinned link is dropped whole
(poison containment: those batches are consumed, the next increment
folds into the served last-good).

Chaos surface: fault points ``foldin.read`` (before the tail read),
``foldin.apply`` (before the fold), ``foldin.publish`` (after the
model blob lands, before the COMPLETED stamp — ``crash`` mode here is
the mid-publish SIGKILL the harness uses to prove cursor + store stay
resumable). Telemetry: ``pio_foldin_events_total``,
``pio_foldin_publishes_total``, ``pio_foldin_rollbacks_total{reason}``
and the ``pio_foldin_freshness_lag_seconds`` gauge. All documented in
docs/operations.md "Online learning".
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import os
import socket
import time
from typing import Optional

from ..common import faultinject, telemetry
from ..data.api.log_tail import LogCursor, LogTailer
from ..data.storage.event import new_event_id
from . import model_artifact
from .context import WorkflowContext

log = logging.getLogger("pio.foldin")

__all__ = ["FoldInRunner", "cursor_docs", "is_foldin_instance",
           "note_rollback"]

_M_EVENTS = telemetry.registry().counter(
    "pio_foldin_events_total",
    "Events read from the log tail by the online fold-in loop").labels()
_M_PUBLISHES = telemetry.registry().counter(
    "pio_foldin_publishes_total",
    "Fold-in increments committed as new COMPLETED engine "
    "instances").labels()
_M_ROLLBACKS = telemetry.registry().counter(
    "pio_foldin_rollbacks_total",
    "Fold-in increments refused or rolled back through the model "
    "lifecycle (validate = gate refusal, error-rate = post-swap watch "
    "breach, plus any manual/fleet pin reason)", ("reason",))
_M_LAG = telemetry.registry().gauge(
    "pio_foldin_freshness_lag_seconds",
    "Seconds since the fold-in view last caught up with the event log "
    "(grows while the loop is failing or falling behind)").labels()


# Targeted cache invalidation gives up past this many distinct users
# per increment chain: the flush costs one cold query per cached user,
# the marker row stays bounded.
_USER_FOOTPRINT_CAP = 512
# Chain-ancestry list cap in the marker (a chain this deep means the
# gate has been stuck for hundreds of ticks; full flush is fine).
_BASES_CAP = 64


def _touched_users(events) -> Optional[set]:
    """The user entity ids whose model rows this batch folds into, or
    None when the batch's effect cannot be attributed to specific
    users — any non-user-entity event (e.g. an item $set that could
    shift every user's results) or more distinct users than the cap.
    None tells the serving cache to flush instead of invalidating
    narrowly; a wrongly-narrow answer here would serve stale results,
    so unknown always degrades to the safe full flush."""
    users: set = set()
    for e in events:  # wire-format dicts (log_tail.TailBatch.events)
        if not isinstance(e, dict):
            return None
        if e.get("entityType") != "user" or not e.get("entityId"):
            return None
        users.add(str(e["entityId"]))
        if len(users) > _USER_FOOTPRINT_CAP:
            return None
    return users


def is_foldin_instance(instance) -> bool:
    """Whether this engine-instance row was produced by a fold-in
    increment (the provenance marker `_commit_increment` writes)."""
    try:
        return bool((instance.runtime_conf or {}).get("foldin"))
    except Exception:  # noqa: BLE001 — classification only
        return False


def note_rollback(reason: str) -> None:
    """Count one fold-in increment refused/rolled back (called by the
    engine server's gate + watch paths when the pinned instance carries
    the fold-in provenance marker)."""
    _M_ROLLBACKS.labels(reason).inc()


class FoldInRunner:
    """One app's fold-in producer. Owned by the engine server's fold-in
    loop and driven from a worker thread (``asyncio.to_thread``) —
    single-flight by construction (only the loop schedules it), so its
    state needs no lock; the loop publishes a snapshot dict for
    /status after every tick."""

    def __init__(self, storage, engine_factory_name: str,
                 engine_variant: str, interval_ms: float = 0.0,
                 app_name: str = ""):
        self.storage = storage
        self.engine_factory_name = engine_factory_name
        self.engine_variant = engine_variant
        self.interval_ms = float(interval_ms)
        # ``app_name`` pins a multi-tenant runner to ITS tenant: the
        # served instance must bind to that app (a mis-stamped row is a
        # structural disable, never a silent cross-tenant fold-in). The
        # cursor row id already carries the app id, so each tenant's
        # runner resumes its own durable cursor under the shared group.
        self.app_name = str(app_name or "")
        self.group = model_artifact.fleet_group(engine_factory_name,
                                                engine_variant)
        self._tailer: Optional[LogTailer] = None
        self._cursor: Optional[LogCursor] = None
        self._app_id: Optional[int] = None
        self._app_name: Optional[str] = None
        self._disabled: Optional[str] = None
        self._caught_up_at: Optional[float] = None
        self._events = 0
        self._publishes = 0
        self._last_instance: Optional[str] = None
        self._last_error: Optional[str] = None
        # increment chain: the last committed increment while its
        # publication is still DEFERRED (fleet canary staging, a busy
        # local gate). Folding every tick into the *served* models
        # instead would base each increment on the stale pre-chain
        # model and silently drop the earlier batches' events once the
        # newest increment publishes. (tip_id, ancestor_ids, models):
        # ancestor_ids = the original served base plus every superseded
        # link — any of them legitimately serving means the chain is
        # merely lagging publication, not invalidated.
        self._pending: Optional[tuple] = None

    # -- status surface ---------------------------------------------------
    def view(self) -> dict:
        now = time.time()
        lag = (now - self._caught_up_at
               if self._caught_up_at is not None else None)
        return {
            # raw anchor rides along so /status can recompute the lag
            # at READ time: a wedged tick freezes this snapshot, and a
            # frozen lagSeconds would hide exactly the wedge the
            # staleness warn-marker exists for
            "caughtUpAt": self._caught_up_at,
            # a committed increment still awaiting publication: the
            # fold-in loop retries its publish on EVERY tick (not just
            # event-bearing ones — a busy gate on the last event before
            # traffic goes quiet must not strand the increment)
            "pendingInstance": (self._pending[0]
                                if self._pending is not None else None),
            "enabled": self._disabled is None,
            "disabledReason": self._disabled,
            "ms": self.interval_ms,
            "group": self.group,
            "app": self._app_name,
            "appId": self._app_id,
            "cursorBytes": (self._cursor.total()
                            if self._cursor is not None else None),
            "cursorShards": (len(self._cursor.shards)
                             if self._cursor is not None else 0),
            "cursorResets": (self._cursor.resets
                             if self._cursor is not None else 0),
            "events": self._events,
            "publishes": self._publishes,
            "lagSeconds": round(lag, 3) if lag is not None else None,
            "lastInstance": self._last_instance,
            "lastError": self._last_error,
        }

    # -- bootstrap --------------------------------------------------------
    def arm(self, instance) -> bool:
        """Eager arming at server startup (BEFORE the listen port
        opens): the no-persisted-cursor case anchors at the log end,
        and anchoring lazily on the first tick instead would silently
        skip every event that lands in the start→first-tick window —
        exactly the new-user cold-start events this subsystem exists
        for. The armed cursor is persisted immediately: a crash inside
        the very first tick must still find a durable position to
        resume from."""
        if not self._arm(instance):
            return False
        try:
            self._persist_cursor(time.time())
        except Exception:  # noqa: BLE001 — first tick re-persists
            log.warning("fold-in: could not persist the armed cursor; "
                        "first tick retries", exc_info=True)
        return True

    def _arm(self, instance) -> bool:
        """Resolve the app + events dir + persisted cursor once (and
        again whenever the served instance's app changes). False =
        fold-in structurally unavailable on this deployment; the
        reason lands on /status instead of a crash-looping tick."""
        le = self.storage.get_l_events()
        events_dir = getattr(le, "events_dir", None)
        if not events_dir:
            self._disabled = ("event store is not a JSONL event log "
                              "(fold-in tails log files; TYPE=JSONL)")
            return False
        app_name = model_artifact.instance_app_name(instance)
        if not app_name:
            self._disabled = ("deployed instance names no app "
                             "(env.appName / data-source appName)")
            return False
        if self.app_name and app_name != self.app_name:
            self._disabled = (
                f"served instance binds to app {app_name!r}, not this "
                f"runner's tenant {self.app_name!r}")
            return False
        app = self.storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            self._disabled = f"app {app_name!r} is not registered"
            return False
        if self._app_id == app.id and self._tailer is not None:
            return True
        self._app_id, self._app_name = app.id, app_name
        self._tailer = LogTailer(events_dir, app.id)
        self._cursor = None
        doc = model_artifact.read_fleet_doc(
            self.storage, model_artifact.foldin_row_id(self.group,
                                                       app.id))
        if doc is not None:
            try:
                self._cursor = LogCursor.from_json(doc.get("cursor"))
                log.info("fold-in resuming app %r at LSN %d (%d "
                         "shard(s))", app_name, self._cursor.total(),
                         len(self._cursor.shards))
            except ValueError:
                log.warning("fold-in cursor record for app %r is "
                            "damaged; re-arming at the log end",
                            app_name, exc_info=True)
        if self._cursor is None:
            # first arm: the deployed model was trained on everything
            # already in the log — only FUTURE events are news
            self._cursor = self._tailer.end_cursor()
            log.info("fold-in armed for app %r at the current log end "
                     "(LSN %d)", app_name, self._cursor.total())
        self._disabled = None
        return True

    @staticmethod
    def _ds_params(instance) -> dict:
        try:
            doc = json.loads(instance.data_source_params or "{}")
            return doc if isinstance(doc, dict) else {}
        except ValueError:
            return {}

    def _persist_cursor(self, now: float) -> None:
        model_artifact.write_fleet_doc(
            self.storage,
            model_artifact.foldin_row_id(self.group, self._app_id),
            {
                "cursor": self._cursor.to_json(),
                "group": self.group,
                "appId": self._app_id,
                "app": self._app_name,
                "intervalMs": self.interval_ms,
                "updatedAt": now,
                "caughtUpAt": self._caught_up_at,
                "events": self._events,
                "publishes": self._publishes,
                "pid": os.getpid(),
            })

    def _chain_base(self, instance, pinned) -> Optional[list]:
        """Models the NEXT increment folds into, when the last one is
        still awaiting publication — else None (fold into the served
        deployment). Chain resolution per tick:

        - served == last increment → published; chain done
        - last increment pinned (gate refusal / watch rollback) → the
          chain carried poison; drop it and fold into the served
          last-good (self-heal; the poisoned batches are consumed —
          exactly the retrain-poisoning containment semantics)
        - served still == the chain's base → deferred (canary staging,
          busy gate); keep chaining so earlier batches are not lost
        - served moved somewhere else entirely (operator reload, a
          racing retrain promoted, fleet rollback) → the chain's base
          is stale; drop it with a warning (one-chain loss in a rare
          race beats publishing increments of a superseded model)
        """
        pend = self._pending
        if pend is None:
            return None
        pend_id, ancestors, models, _users = pend
        if instance.id == pend_id:
            self._pending = None
            return None
        if pend_id in pinned or any(a in pinned for a in ancestors):
            log.warning("fold-in: increment chain through %s carried a "
                        "pinned link; dropping it and folding into the "
                        "served last-good", pend_id)
            self._pending = None
            return None
        if instance.id in ancestors:
            # an ancestor link (or the original base) is serving: the
            # chain is lagging publication — e.g. the coordinator just
            # promoted an older link while we kept committing newer
            # ones — keep chaining from the tip
            return models
        log.warning("fold-in: served instance moved to %s while "
                    "increment %s awaited publication; resetting the "
                    "chain onto the new deployment", instance.id,
                    pend_id)
        self._pending = None
        return None

    # -- one tick ---------------------------------------------------------
    def run_once(self, deployment, instance, pinned=()) -> dict:
        """Worker-thread tick: read → fold → commit → persist cursor.
        Returns the /status view, with ``"instance"`` set when an
        increment was committed (the caller decides how it publishes:
        local gate vs fleet coordinator). ``pinned`` is the server's
        current pin set — how the chain learns its last increment was
        refused/rolled back. Raises on injected/storage faults — the
        loop logs and retries next tick, and the lag gauge keeps
        growing until a tick succeeds."""
        try:
            if not self._arm(instance):
                return self.view()
            faultinject.fault_point("foldin.read")
            batch = self._tailer.read_since(self._cursor)
            produced = None
            if batch.events:
                faultinject.fault_point("foldin.apply")
                produced = self._fold_and_commit(deployment, instance,
                                                 batch, set(pinned))
            else:
                # no new events: still resolve the chain so a promoted
                # or pinned increment is observed promptly
                self._chain_base(instance, set(pinned))
            now = time.time()
            # count events only once the cursor commits past them: a
            # tick that faults at apply/publish re-reads the same
            # batch next tick, and counting per read would inflate
            # the counter by batch-size per retry
            self._events += len(batch.events)
            _M_EVENTS.inc(len(batch.events))
            self._cursor = batch.cursor
            self._caught_up_at = now
            _M_LAG.set(0.0)
            self._persist_cursor(now)
            self._last_error = None
            out = self.view()
            if produced:
                out["instance"] = produced
            return out
        except Exception as e:
            self._last_error = str(e)
            if self._caught_up_at is not None:
                _M_LAG.set(time.time() - self._caught_up_at)
            raise

    def _fold_and_commit(self, deployment, instance, batch,
                         pinned) -> Optional[str]:
        ds_params = self._ds_params(instance)
        ctx = WorkflowContext(app_name=self._app_name or "",
                              storage=self.storage)
        ctx.engine_instance_id = instance.id
        chain = self._chain_base(instance, pinned)
        if chain is not None:
            base_models = chain
            base_id = self._pending[0]
            ancestors = self._pending[1] | {self._pending[0]}
            prev_users = self._pending[3]
        else:
            base_models = deployment.models
            base_id = instance.id
            ancestors = {instance.id}
            prev_users: Optional[set] = set()
        new_models, changed = [], False
        for (_name, algo), model in zip(deployment.algo_list,
                                        base_models):
            out = algo.fold_in(model, batch.events, ctx,
                               data_source_params=ds_params)
            new_models.append(model if out is None else out)
            changed = changed or out is not None
        if not changed:
            return None
        # freshness footprint is CUMULATIVE over a deferral chain: the
        # increment that finally publishes carries every user any link
        # re-solved, or None the moment any link was unattributable
        batch_users = _touched_users(batch.events)
        users = (None if batch_users is None or prev_users is None
                 else prev_users | batch_users)
        if users is not None and len(users) > _USER_FOOTPRINT_CAP:
            users = None
        iid = self._commit_increment(instance, deployment.algo_list,
                                     new_models, len(batch.events),
                                     batch.cursor, ancestors, users)
        self._pending = (iid, ancestors, new_models, users)
        self._publishes += 1
        self._last_instance = iid
        _M_PUBLISHES.inc()
        log.info("fold-in: %d event(s) folded into %s -> new instance "
                 "%s (LSN %d)", len(batch.events), base_id, iid,
                 batch.cursor.total())
        return iid

    def _commit_increment(self, instance, algo_list, models,
                          n_events: int, cursor: LogCursor,
                          ancestors: set,
                          users: Optional[set]) -> str:
        """Persist one increment exactly like a retrain does: instance
        row RUNNING → model blob (checksummed envelope, ``model.insert``
        fault point inside) → ``foldin.publish`` fault point →
        COMPLETED stamp. A SIGKILL before the stamp leaves a RUNNING
        row no loader will ever serve, and the cursor (committed only
        after this returns) re-folds the same events on restart."""
        from .core_workflow import serialize_models

        instances = self.storage.get_meta_data_engine_instances()
        now = _dt.datetime.now(_dt.timezone.utc)
        marker = {
            "of": instance.id,
            "events": n_events,
            "lsn": cursor.total(),
        }
        if len(ancestors) <= _BASES_CAP:
            # missing bases ⇒ the serving cache can't prove the swap is
            # a pure fold-in of what it was serving ⇒ full flush (safe)
            marker["bases"] = sorted(ancestors)
        if users is not None:
            marker["users"] = sorted(users)
        row = dataclasses.replace(
            instance,
            id=new_event_id(),
            status="RUNNING",
            start_time=now,
            end_time=None,
            runtime_conf={
                **(instance.runtime_conf or {}),
                "foldin": json.dumps(marker),
            },
            env={**(instance.env or {}), "pid": str(os.getpid()),
                 "host": socket.gethostname()},
        )
        instances.insert(row)
        blob = serialize_models(algo_list, models)
        model_artifact.write_model(self.storage, row.id, blob)
        faultinject.fault_point("foldin.publish")
        instances.update(row.with_status("COMPLETED", _dt.datetime.now(
            _dt.timezone.utc)))
        return row.id


def cursor_docs(storage) -> list[dict]:
    """Every persisted fold-in cursor record, for `pio status`: probe
    the (fleet group × registered app) combinations the metadata knows
    about — the DAO contract has no row scan, and these ids are
    deterministic. Degrades to [] when any repository is unreachable
    (a health surface must not crash)."""
    out: list[dict] = []
    try:
        instances = storage.get_meta_data_engine_instances().get_all()
        groups = {model_artifact.fleet_group(
            i.engine_factory or i.engine_id, i.engine_variant)
            for i in instances}
        apps = storage.get_meta_data_apps().get_all()
    except Exception:  # noqa: BLE001 — diagnostics only
        return out
    for group in sorted(groups):
        for app in apps:
            doc = model_artifact.read_fleet_doc(
                storage, model_artifact.foldin_row_id(group, app.id))
            if doc is not None:
                out.append({**doc, "app": doc.get("app") or app.name})
    return out
