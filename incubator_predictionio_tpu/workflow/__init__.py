"""Workflow runtime: train/eval/deploy orchestration.

Reference layer map: SURVEY.md §2.5 (core/.../workflow/).
"""

from .workflow_params import WorkflowParams
from .context import WorkflowContext
from .json_extractor import load_engine_json, resolve_engine_factory

__all__ = [
    "WorkflowContext", "WorkflowParams", "load_engine_json",
    "resolve_engine_factory",
]
