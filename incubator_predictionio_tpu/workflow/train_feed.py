"""Gang training feeds: partition-local reads orchestrated over the
gang's collective substrate.

``pio train --num-workers N`` runs N processes that all used to read
the SAME merged event view (N× decode + N× memory of the full log).
With the partition feed armed (``PIO_TRAIN_FEED=partition`` — the gang
default, ``--feed merged`` opts out), gang worker *i* reads ONLY the
event-log shards assigned to it (``data/api/partition_feed`` — shard
*j* of the canonical order belongs to worker ``j mod N``), as
sequential colseg-snapshot scans with tail-only JSON parsing, and the
gang agrees on the global view by allgathering *derived* artifacts —
never raw events — over the same gloo/ICI substrate training already
runs its collectives on:

1. **tombstone ids** (so every worker applies the merged view's
   id-global delete rule to its own partitions),
2. **entity-id vocabularies** (per-partition first-seen lists, merged
   in worker-then-shard order into ONE deterministic global BiMap —
   every process computes the identical mapping), or, for the
   classification family,
3. **per-entity property aggregates** (per-shard $set replays merged
   by last-update order).

The mapped partition-local COO then trains through
``ops.als.train_als_partition_local`` (replicated-gram all-reduce,
factor blocks sharded over the mesh) and the classification examples
through ``ops.linear.train_*_process_local`` (SparkNet-style
synchronous data parallelism) — see those docstrings for the math.

Shard scans of one worker overlap via ``workflow.input_pipeline.
prefetch`` (decode of shard N+1 runs while shard N extracts).

Fallback: a storage whose event backend is not the JSONL log (no
``events_dir``) has no partitions to feed from — the merged read stays
in effect, warned once. The decision is a pure function of the storage
config, so every gang process falls back (or not) together.
"""

from __future__ import annotations

import json
import logging
from typing import Optional, Sequence

import numpy as np

from ..common import envknobs
from ..data.api import partition_feed as pfeed
from ..data.storage.bimap import BiMap

log = logging.getLogger("pio.trainfeed")

__all__ = [
    "feed_identity", "feed_mode", "partition_examples",
    "partition_feed_active", "partition_ratings",
]

_TIME_ABSENT = np.iinfo(np.int64).min


def feed_mode() -> str:
    """Resolved PIO_TRAIN_FEED: '' (unset → merged), 'merged', or
    'partition'."""
    raw = envknobs.env_str("PIO_TRAIN_FEED", "").strip().lower()
    if raw and raw not in ("partition", "merged"):
        log.warning("PIO_TRAIN_FEED=%r: expected partition/merged; "
                    "using merged", raw)
        return "merged"
    return raw


def feed_identity() -> tuple[int, int]:
    """(worker, num_workers) of this training process — the gang
    wiring the supervisor provides (PIO_PROCESS_ID /
    PIO_NUM_PROCESSES); (0, 1) outside a gang, i.e. one worker owns
    every shard."""
    n = envknobs.env_int("PIO_NUM_PROCESSES", 1, lo=1)
    w = envknobs.env_int("PIO_PROCESS_ID", 0, lo=0)
    if w >= n:
        raise ValueError(
            f"PIO_PROCESS_ID={w} outside the gang size {n}")
    return w, n


def partition_feed_active(storage) -> bool:
    """Whether training reads should feed partition-local. True only
    when the knob says so AND the event backend is the JSONL log
    (anything else has no shard files — merged semantics are all there
    is). Pure function of env + storage config: every gang process
    agrees."""
    if feed_mode() != "partition":
        return False
    le = storage.get_l_events()
    if getattr(le, "events_dir", None) is None:
        log.warning(
            "PIO_TRAIN_FEED=partition but the event backend (%s) is "
            "not the JSONL log; falling back to the merged read",
            type(le).__name__)
        return False
    return True


# ---------------------------------------------------------------------------
# gang exchange (derived artifacts only — never raw events)
# ---------------------------------------------------------------------------


def _allgather_payload(doc) -> list:
    """Allgather one JSON-serializable payload per gang process; returns
    the list in process order (identity for single-process runs). Rides
    the jax.distributed substrate the gang already holds open — two
    int32/uint8 allgathers (sizes, then padded bytes)."""
    import jax

    if jax.process_count() <= 1:
        return [doc]
    from jax.experimental import multihost_utils

    blob = np.frombuffer(
        json.dumps(doc, separators=(",", ":")).encode("utf-8"),
        np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.int32(blob.size))).reshape(-1)
    padded = np.zeros(int(sizes.max()) if sizes.size else 0, np.uint8)
    padded[:blob.size] = blob
    gathered = np.asarray(
        multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(sizes.size, -1)
    return [
        json.loads(bytes(gathered[p, :int(sizes[p])]).decode("utf-8"))
        for p in range(sizes.size)
    ]


def _scan_assigned(feed: "pfeed.PartitionFeed",
                   start_us: Optional[int] = None,
                   until_us: Optional[int] = None) -> list:
    """Scan this worker's shards, decode overlapped through the input
    pipeline's prefetch workers (the native parse releases the GIL).
    With an event-time window, each shard scan skips the generations
    its manifest proves disjoint — the worker never decodes its own
    cold shards."""
    from .input_pipeline import PipelineConfig, prefetch

    def scan_one(p: str):
        return pfeed.scan_shard(p, start_us, until_us)

    cfg = PipelineConfig.from_env()
    paths = feed.shard_list()
    if cfg.mode == "off" or len(paths) <= 1:
        return [scan_one(p) for p in paths]
    return list(prefetch(paths, scan_one,
                         workers=cfg.workers,
                         lookahead=max(2, cfg.depth)))


def _resolve(app_name, storage, channel_name):
    from ..data.store.p_event_store import _resolve_app

    return _resolve_app(app_name, storage, channel_name)


def open_feed(app_name: str, storage=None,
              channel_name: Optional[str] = None,
              start_us: Optional[int] = None,
              until_us: Optional[int] = None) -> tuple:
    """Scan this worker's assigned shards ONCE and run the tombstone
    exchange: ``(feed, shards, global_tombstones)``. A template whose
    read needs BOTH the rating feed and a property aggregate (e.g.
    similar-product: view events + item categories) passes the result
    as ``feed_ctx`` to both calls so the shard decode and the
    tombstone allgather are not paid twice — such a SHARED context must
    stay unwindowed (property replay needs full history; the rating
    extraction's row filter still applies its window). Collective:
    every gang process must call this (and the subsequent extractions)
    in the same order."""
    s, app_id, channel_id = _resolve(app_name, storage, channel_name)
    le = s.get_l_events()
    worker, num_workers = feed_identity()
    feed = pfeed.PartitionFeed(le.events_dir, app_id, channel_id,
                               worker, num_workers)
    shards = _scan_assigned(feed, start_us, until_us)
    tombs = _allgather_payload(feed.local_tombstones(shards))
    return feed, shards, frozenset(t for part in tombs for t in part)


# ---------------------------------------------------------------------------
# ratings (ALS family)
# ---------------------------------------------------------------------------


def partition_ratings(
    app_name: str,
    event_names: Optional[Sequence[str]] = None,
    rating_from_props: bool = True,
    default_rating: float = 1.0,
    event_default_ratings: Optional[dict] = None,
    storage=None,
    channel_name: Optional[str] = None,
    start_time=None,
    until_time=None,
    feed_ctx: Optional[tuple] = None,
):
    """Partition-local mirror of ``PEventStore.find_ratings``: returns
    ``(u, i, r, users, items)`` where the COO triple holds ONLY this
    worker's partitions' events, already mapped onto the allgathered
    GLOBAL id maps (identical ``users``/``items`` BiMaps on every gang
    process; built in worker-then-shard first-seen order, so the index
    assignment differs from the merged read's time-sorted order — the
    maps, the event multiset and the trained factors per id are what
    match). ``feed_ctx`` (an :func:`open_feed` result) shares one shard
    scan + tombstone exchange with other extractions of the same
    read.

    Windowing: an all-``None`` time range fills from the ambient
    training window (``pio train --window`` / ``PIO_TRAIN_WINDOW`` —
    ``common/train_window.py``), and when this call opens its OWN feed
    the window threads down to the shard scans, where whole
    out-of-window generations are skipped by manifest bounds. A shared
    ``feed_ctx`` was scanned unwindowed, so there the window is
    row-filter only — same result, no skip."""
    from ..common import train_window

    worker, num_workers = feed_identity()
    start_time, until_time = train_window.apply_window(start_time,
                                                       until_time)
    s_us = pfeed.to_epoch_us(start_time)
    u_us = pfeed.to_epoch_us(until_time)
    feed, shards, global_tombs = (
        feed_ctx if feed_ctx is not None
        else open_feed(app_name, storage, channel_name,
                       start_us=s_us, until_us=u_us))
    user_ids: list = []
    item_ids: list = []
    u_index: dict = {}
    i_index: dict = {}
    u_parts, i_parts, r_parts = [], [], []
    for shard in shards:
        sr = pfeed.PartitionFeed.shard_ratings(
            shard, event_names, global_tombs,
            rating_from_props=rating_from_props,
            default_rating=default_rating,
            event_default_ratings=event_default_ratings,
            start_us=s_us, until_us=u_us)

        def remap(ids, index, store):
            lut = np.empty(len(ids), np.int32)
            for j, eid in enumerate(ids):
                code = index.get(eid)
                if code is None:
                    code = index[eid] = len(store)
                    store.append(eid)
                lut[j] = code
            return lut

        lut_u = remap(sr.user_ids, u_index, user_ids)
        lut_i = remap(sr.item_ids, i_index, item_ids)
        if len(sr.u):
            u_parts.append(lut_u[sr.u])
            i_parts.append(lut_i[sr.i])
            r_parts.append(sr.rating)
    u_loc = (np.concatenate(u_parts) if u_parts
             else np.empty(0, np.int32))
    i_loc = (np.concatenate(i_parts) if i_parts
             else np.empty(0, np.int32))
    r_loc = (np.concatenate(r_parts) if r_parts
             else np.empty(0, np.float32))
    # exchange 2: per-worker vocabularies → ONE deterministic global
    # BiMap (worker order, first seen wins)
    vocabs = _allgather_payload({"u": user_ids, "i": item_ids})
    users = BiMap.string_int(
        uid for part in vocabs for uid in part["u"])
    items = BiMap.string_int(
        iid for part in vocabs for iid in part["i"])
    if len(user_ids):
        glut_u = np.fromiter((users(x) for x in user_ids), np.int32,
                             count=len(user_ids))
        glut_i = np.fromiter((items(x) for x in item_ids), np.int32,
                             count=len(item_ids))
        u_loc = glut_u[u_loc]
        i_loc = glut_i[i_loc]
    log.info(
        "partition feed: worker %d/%d read %d shard(s), %d local "
        "rating event(s); global vocab %d users / %d items",
        worker, num_workers, len(shards), len(r_loc), len(users),
        len(items))
    return u_loc, i_loc, r_loc, users, items


# ---------------------------------------------------------------------------
# labeled examples (NB/LR family)
# ---------------------------------------------------------------------------


def partition_examples(
    app_name: str,
    entity_type: str,
    attributes: Sequence[str],
    label: str,
    storage=None,
    channel_name: Optional[str] = None,
):
    """Partition-local mirror of the classification read
    (``aggregate_properties`` → labeled example matrix): per-shard
    $set replays are allgathered as per-ENTITY partial aggregates
    (derived batches, not raw events) and merged by last-update order,
    so every gang process computes the identical global entity table,
    label vocabulary and example order — then each takes its strided
    slice (entity ``j mod N`` → worker ``j``) for the data-parallel
    NB/LR trainers. Returns ``(features, labels, label_values,
    n_entities)`` with the LOCAL example block and the GLOBAL label
    vocabulary/entity count.

    Exactness contract: identical to the merged read whenever each
    entity's property events live in one partition (the import shape —
    one $set per entity trivially qualifies). Cross-partition
    interleaved partial updates of ONE entity resolve by whole-map
    last-write order, and a $delete only erases $sets in its own
    partition — the documented feed caveats."""
    merged = partition_properties(app_name, entity_type,
                                  storage=storage,
                                  channel_name=channel_name)
    worker, num_workers = feed_identity()
    features, y_local, label_values, kept = _examples_from_map(
        merged, attributes, label, worker, num_workers)
    log.info(
        "partition feed: worker %d/%d holds %d of %d labeled "
        "entit(ies), %d class(es)", worker, num_workers,
        len(features), kept, len(label_values))
    return features, y_local, label_values, kept


def partition_properties(
    app_name: str,
    entity_type: str,
    storage=None,
    channel_name: Optional[str] = None,
    feed_ctx: Optional[tuple] = None,
) -> dict:
    """Partition-local mirror of ``aggregate_properties`` →
    ``{entity_id: props}``: the same per-shard replay + allgathered
    merge as :func:`partition_examples`, without the example-matrix
    shaping — for templates that read serving metadata (e.g. item
    categories) alongside the rating feed. Every gang process returns
    the identical map. ``feed_ctx`` (an :func:`open_feed` result)
    shares one shard scan + tombstone exchange with other extractions
    of the same read."""
    feed, shards, global_tombs = (
        feed_ctx if feed_ctx is not None
        else open_feed(app_name, storage, channel_name))
    my_positions = feed.canonical_positions()
    local = []
    for shard in shards:
        rep = pfeed.PartitionFeed.shard_properties(
            shard, entity_type, global_tombs)
        local.append((my_positions.get(shard.path, -1), {
            eid: [props, int(first), int(last)]
            for eid, (props, first, last) in rep.items()}))
    return _merge_property_parts(_allgather_payload(local))


def _merge_property_parts(gathered) -> dict:
    """{entity: merged props} from every worker's per-shard property
    replays (``gathered`` = list over workers of ``[(canonical shard
    position, {entity: [props, first_us, last_us]}), ...]``): per
    entity, partial maps apply in ascending last-update order (absent
    times sort last — the replay's "now" rule), ties broken by
    canonical shard position, so every process computes the identical
    merge regardless of which worker gathered what."""
    by_entity: dict = {}
    for part in gathered:
        for pos, rep in part:
            for eid, (props, first, last) in rep.items():
                by_entity.setdefault(eid, []).append(
                    (int(last), int(pos), props))
    big = np.iinfo(np.int64).max
    merged: dict = {}
    for eid, pieces in by_entity.items():
        pieces.sort(key=lambda p: (
            big if p[0] == _TIME_ABSENT else p[0], p[1]))
        props: dict = {}
        for _last, _pos, piece in pieces:
            props.update(piece)
        merged[eid] = props
    return merged


def _examples_from_map(merged: dict, attributes: Sequence[str],
                       label: str, worker: int, num_workers: int):
    """Global entity map → (this worker's strided example block, the
    GLOBAL label vocabulary, the global kept-entity count). Entities
    sort by id so every worker sees the same order; the label
    vocabulary covers ALL kept entities (np.unique — sorted, identical
    everywhere) while the feature rows are the worker's
    ``kept_index % num_workers == worker`` slice."""
    required = set(attributes) | {label}
    feats, labels, kept = [], [], 0
    for eid in sorted(merged):
        props = merged[eid]
        if not required.issubset(props):
            continue
        if kept % num_workers == worker:
            feats.append([float(props[a]) for a in attributes])
        else:
            feats.append(None)
        labels.append(props[label])  # global label vocab needs all
        kept += 1
    label_values, y_all = np.unique(np.asarray(labels),
                                    return_inverse=True)
    mine = [j for j, f in enumerate(feats) if f is not None]
    features = np.asarray([feats[j] for j in mine], np.float32)
    if features.size == 0:
        features = features.reshape(0, len(attributes))
    y_local = np.asarray(y_all).reshape(-1)[mine].astype(np.int32)
    return features, y_local, label_values, kept
