"""WorkflowParams (reference: core/.../workflow/WorkflowParams.scala —
batch label, verbosity, sanity-check and pipeline-bisection flags)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 10
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
