"""WorkflowParams (reference: core/.../workflow/WorkflowParams.scala —
batch label, verbosity, sanity-check and pipeline-bisection flags)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class WorkflowParams:
    batch: str = ""
    verbose: int = 10
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    # Mid-training checkpointing (no reference analog — SURVEY.md §5.4):
    # snapshot algorithm state every N iterations; `--resume` continues the
    # most recent interrupted instance from its last snapshot.
    checkpoint_every: int = 0
    resume: bool = False
    # Tracing/profiling (reference relied on the external Spark web UI —
    # SURVEY.md §5.1): write a jax.profiler trace of the train stage here.
    profile_dir: str = ""
    # NaN-guard tier (SURVEY.md §5.2 sanitizer analog): check every DASE
    # stage output for non-finite values with stage attribution;
    # iterative trainers dispatch per-iteration to name the iteration.
    nan_guard: bool = False
    # Cost-based device placement (workflow/placement.py): auto prices
    # accelerator-vs-CPU per algorithm with measured link/host rates and
    # runs each stage where it is fastest; tpu/cpu force one side.
    device: str = "auto"
    # Streaming input pipeline (workflow/input_pipeline.py): overlap
    # host featurize, host→device upload, and on-device compute as a
    # double-buffered chunk stream. "" defers to the PIO_PIPELINE env
    # (default auto); auto/on/off select per-run. The 0 values defer to
    # the PIO_PIPELINE_{CHUNK,DEPTH,WORKERS} envs / built-in defaults.
    pipeline: str = ""
    pipeline_chunk: int = 0
    pipeline_depth: int = 0
    pipeline_workers: int = 0
