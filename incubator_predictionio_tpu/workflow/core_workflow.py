"""CoreWorkflow — run a training job and persist its results.

Reference: core/.../workflow/{CoreWorkflow,CreateWorkflow}.scala: stamp an
EngineInstance row RUNNING → COMPLETED, run engine.train, serialize models
into the Models DAO (or let PersistentModel models save themselves).
No spark-submit: the whole thing is one in-process call (SURVEY.md §7
design stance).
"""

from __future__ import annotations

import contextlib
import dataclasses
import datetime as _dt
import json
import logging
import os
import pickle
import socket
from typing import Any, Optional

from ..common import envknobs
from ..controller.engine import Engine, EngineParams
from ..controller.persistent_model import PersistentModel
from ..data.storage.base import EngineInstance
from ..data.storage.event import new_event_id
from . import model_artifact
from .context import WorkflowContext
from .workflow_params import WorkflowParams

log = logging.getLogger("pio.workflow")


def _utcnow():
    return _dt.datetime.now(_dt.timezone.utc)


def serialize_models(algo_list, models: list[Any]) -> bytes:
    """Device pytrees → host → pickle (reference: Engine.makeSerializableModels
    + java serialization into the Models DAO). PersistentModel entries are
    replaced by a marker — they saved themselves."""
    prepared = []
    for (name, algo), model in zip(algo_list, models):
        if isinstance(model, PersistentModel):
            prepared.append({"__persistent__": type(model).__module__ + "." + type(model).__qualname__})
        else:
            prepared.append(algo.prepare_model_for_persistence(model))
    return pickle.dumps(prepared, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_models(blob: bytes, algo_list, instance_id: str, ctx) -> list[Any]:
    import importlib

    stored = pickle.loads(blob)
    out = []
    for (name, algo), item in zip(algo_list, stored):
        if isinstance(item, dict) and "__persistent__" in item:
            dotted = item["__persistent__"]
            module_name, _, cls_name = dotted.rpartition(".")
            cls = getattr(importlib.import_module(module_name), cls_name)
            out.append(cls.load(instance_id, ctx))
        else:
            out.append(item)
    return out


def _train_with_stale_checkpoint_fallback(engine, engine_params, ctx, wp,
                                          cm=contextlib.nullcontext):
    """engine.train with the --resume stale-snapshot fallback: a
    CheckpointIncompatibleError (data/rank changed) discards the
    checkpoints and retrains from scratch — otherwise every future
    --resume re-selects the same instance and fails the same way. The
    ONE implementation for the gang leader and followers: the
    fingerprint check is deterministic across the gang, so every
    process takes (or skips) this branch at the same point and the
    collectives stay aligned. ``cm`` wraps each attempt (the leader's
    profiler trace)."""
    from .checkpoint import CheckpointHook, CheckpointIncompatibleError

    try:
        with cm():
            return engine.train(ctx, engine_params, wp)
    except CheckpointIncompatibleError as e:
        if ctx.checkpoint_hook is None or not wp.resume:
            raise
        # Shared dir — rmtree tolerates gang peers racing the delete.
        log.warning(
            "--resume: %s; discarding stale checkpoints and training "
            "from scratch", e,
        )
        root = ctx.checkpoint_hook
        root.delete_all()
        ctx.checkpoint_hook = CheckpointHook(
            root.directory, every_n=root.every_n,
            max_to_keep=root.max_to_keep,
        )
        ctx.workflow_params = dataclasses.replace(wp, resume=False)
        try:
            with cm():
                return engine.train(ctx, engine_params, ctx.workflow_params)
        finally:
            ctx.workflow_params = wp


def _run_train_follower(engine, engine_params, ctx, wp, gang_id: str) -> str:
    """Gang processes 1..N-1: participate in every training collective
    (and the checkpoint barriers) under the supervisor-pinned instance
    id, but leave ALL metadata/model persistence to the leader — the
    factors are replicated, so the leader's copy is the gang's copy."""
    from .checkpoint import CheckpointHook, instance_checkpoint_dir

    ctx.engine_instance_id = gang_id
    if wp.resume:
        prior = ctx.get_storage().get_meta_data_engine_instances().get(
            gang_id)
        if prior is not None and prior.status == "COMPLETED":
            # Mirror of the leader's already-COMPLETED exit: on a
            # relaunch that raced the finish line, every process must
            # skip training or the ones that don't would wait forever
            # in the first collective.
            log.info("gang follower: EngineInstance %s already "
                     "COMPLETED; nothing to do", gang_id)
            return gang_id
    if wp.checkpoint_every > 0 or wp.resume:
        ctx.checkpoint_hook = CheckpointHook(
            instance_checkpoint_dir(gang_id), every_n=wp.checkpoint_every)
    try:
        _train_with_stale_checkpoint_fallback(engine, engine_params, ctx, wp)
    finally:
        if ctx.checkpoint_hook is not None:
            ctx.checkpoint_hook.close()
            ctx.checkpoint_hook = None
    log.info("gang follower %s: train stage complete",
             envknobs.env_str("PIO_PROCESS_ID", "?"))
    return gang_id


def _capture_foldin_anchor(storage, ctx):
    """(app_id, LogCursor) at the current event-log end, or None when
    fold-in structurally cannot apply (non-JSONL store, no app).
    Best-effort: training must never fail over its online-learning
    bookkeeping."""
    try:
        from ..data.api.log_tail import LogTailer

        le = storage.get_l_events()
        events_dir = getattr(le, "events_dir", None)
        if not events_dir or not ctx.app_name:
            return None
        app = storage.get_meta_data_apps().get_by_name(ctx.app_name)
        if app is None:
            return None
        return app.id, LogTailer(events_dir, app.id).end_cursor()
    except Exception:  # noqa: BLE001 — bookkeeping only
        return None


def _persist_foldin_anchor(storage, anchor, ctx, engine_factory_name,
                           engine_variant) -> None:
    """Seed the fold-in cursor row from a completed train — ONLY when
    none exists yet: a live fold-in producer owns an existing row
    (single-writer), and rewinding it under a running tailer would
    re-fold everything since its last tick for nothing."""
    if anchor is None:
        return
    try:
        import time as _time

        app_id, cursor = anchor
        group = model_artifact.fleet_group(engine_factory_name,
                                           engine_variant)
        row_id = model_artifact.foldin_row_id(group, app_id)
        if model_artifact.read_fleet_doc(storage, row_id) is not None:
            return
        model_artifact.write_fleet_doc(storage, row_id, {
            "cursor": cursor.to_json(),
            "group": group,
            "appId": app_id,
            "app": ctx.app_name,
            "intervalMs": 0.0,
            "updatedAt": _time.time(),
            "caughtUpAt": None,
            "events": 0,
            "publishes": 0,
            "anchor": "train",
        })
        log.info("fold-in cursor anchored at this train's read "
                 "position (LSN %d) for app %r", cursor.total(),
                 ctx.app_name)
    except Exception:  # noqa: BLE001 — bookkeeping only
        log.debug("could not persist the fold-in train anchor",
                  exc_info=True)


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    ctx: Optional[WorkflowContext] = None,
    workflow_params: Optional[WorkflowParams] = None,
    engine_factory_name: str = "",
    engine_variant: str = "default",
) -> str:
    """Run the training workflow; returns the engine-instance id.

    Call stack parity with SURVEY.md §3.1: Console→train lands here, then
    Engine.train → DataSource.read_training → Preparator.prepare →
    Algorithm.train (pjit'd hot loop) → model persistence.
    """
    ctx = ctx or WorkflowContext()
    wp = workflow_params or WorkflowParams()
    ctx.workflow_params = wp
    # Resolve the streaming-input config ONCE per run (pins the env
    # snapshot for every stage of this train) and record it — whether a
    # train streamed or single-shot must be readable from its log.
    pl = ctx.get_input_pipeline()
    log.info(
        "input pipeline: mode=%s chunk_rows=%d chunk_docs=%d depth=%d "
        "workers=%d", pl.mode, pl.chunk_rows, pl.chunk_docs, pl.depth,
        pl.workers)
    from ..parallel import supervisor as gang

    # Gang runs (parallel/supervisor.py): the supervisor pins ONE
    # engine-instance id for the whole gang so every process agrees on
    # the checkpoint directory and a relaunch resumes the same row.
    # Only process 0 (the leader) touches metadata/model storage;
    # followers train — every collective needs them — and discard.
    gang_id = os.environ.get(gang.ENV_GANG_INSTANCE_ID) or None
    follower = bool(
        gang_id) and envknobs.env_str("PIO_PROCESS_ID", "0") != "0"
    if follower:
        return _run_train_follower(engine, engine_params, ctx, wp, gang_id)
    storage = ctx.get_storage()
    instances = storage.get_meta_data_engine_instances()

    instance = EngineInstance(
        id=new_event_id(),
        status="RUNNING",
        start_time=_utcnow(),
        end_time=None,
        engine_id=engine_factory_name or "engine",
        engine_version="1",
        engine_variant=engine_variant,
        engine_factory=engine_factory_name,
        batch=wp.batch,
        # pid/host let `--resume` distinguish a SIGKILL'd RUNNING row from a
        # train that is genuinely still alive on this machine.
        env={"appName": ctx.app_name, "pid": str(os.getpid()),
             "host": socket.gethostname()},
        data_source_params=json.dumps(dict(engine_params.data_source_params)),
        preparator_params=json.dumps(dict(engine_params.preparator_params)),
        algorithms_params=json.dumps(
            [{"name": n, "params": dict(p)} for n, p in engine_params.algorithm_params_list]
        ),
        serving_params=json.dumps(dict(engine_params.serving_params)),
    )
    if gang_id:
        # Supervisor-pinned id: the row and checkpoint dir are shared
        # by every gang attempt, so resume discovery is a direct get —
        # a relaunch must never pick up some OTHER interrupted run.
        from .checkpoint import instance_checkpoint_dir

        instance = EngineInstance(**{**instance.__dict__, "id": gang_id})
        prior = instances.get(gang_id) if wp.resume else None
        if prior is not None and prior.status == "COMPLETED":
            # A relaunch can race the finish line: the leader persisted
            # and stamped COMPLETED while a wedged follower got the gang
            # killed. The job is DONE — retraining it would flip the row
            # back to RUNNING and duplicate the Model insert. Every gang
            # process takes this same exit (followers check the shared
            # row), so nobody is left alone in a collective.
            log.info("gang resume: EngineInstance %s is already "
                     "COMPLETED; nothing to do", gang_id)
            return gang_id
        if (prior is not None
                and prior.algorithms_params != instance.algorithms_params):
            # Same guard as the discovery path below: resuming under
            # changed hyperparameters would blend them — drop the stale
            # snapshots and train this gang id from scratch.
            from .checkpoint import CheckpointHook

            log.warning(
                "gang --resume: instance %s has different algorithm "
                "params; discarding its checkpoints and training from "
                "scratch", gang_id)
            CheckpointHook(instance_checkpoint_dir(gang_id)).delete_all()
            prior = None
        if (prior is not None and prior.status != "COMPLETED"
                and os.path.isdir(instance_checkpoint_dir(gang_id))):
            instance = EngineInstance(
                **{**instance.__dict__, "start_time": prior.start_time})
            instances.update(instance)
            log.info("gang resume: continuing EngineInstance %s", gang_id)
        elif instances.get(gang_id) is not None:
            # The row exists but isn't resumable (no snapshots landed
            # before the relaunch): retake it fresh — an insert here
            # would be a duplicate key on strict backends.
            instances.update(instance)
        else:
            instances.insert(instance)
        instance_id = gang_id
    elif wp.resume:
        from .checkpoint import find_resumable_instance

        prior = find_resumable_instance(
            storage, engine_factory_name or "engine", "1", engine_variant,
            data_source_params=instance.data_source_params,
            preparator_params=instance.preparator_params,
        )
        if prior is not None and prior.algorithms_params != instance.algorithms_params:
            # Same data, changed hyperparameters — resuming would blend
            # them and falsify provenance. The superseded snapshots are
            # useless under the new params: drop them and retire the row so
            # a `--resume` months from now can't restore stale factors.
            log.warning(
                "--resume: interrupted instance %s has different algorithm "
                "params than the current engine.json; discarding its "
                "checkpoints and training from scratch",
                prior.id,
            )
            from .checkpoint import CheckpointHook, instance_checkpoint_dir

            CheckpointHook(instance_checkpoint_dir(prior.id)).delete_all()
            if prior.status == "RUNNING":
                instances.update(prior.with_status("ABORTED", _utcnow()))
            prior = None
        if prior is not None:
            # Continue the interrupted run under its own instance id so the
            # checkpoint directory and metadata row line up.
            instance = EngineInstance(**{**instance.__dict__, "id": prior.id,
                                         "start_time": prior.start_time})
            instances.update(instance)
            instance_id = prior.id
            log.info("resuming interrupted EngineInstance %s", instance_id)
        else:
            log.info("--resume requested but no resumable instance found; "
                     "training from scratch")
            instance_id = instances.insert(instance)
    else:
        instance_id = instances.insert(instance)
    ctx.engine_instance_id = instance_id
    log.info("EngineInstance %s RUNNING", instance_id)
    # Online-learning anchor (docs/operations.md "Online learning"):
    # capture the event log's position BEFORE the training read so the
    # fold-in tailer's FIRST arm resumes from what this train covers —
    # without it, events ingested between train and `pio deploy
    # --online-foldin` startup fall into neither the trained model nor
    # the tail. Captured here (pre-read) so the error direction is
    # at-least-once: an event racing the read may be both trained AND
    # folded, never silently dropped.
    foldin_anchor = _capture_foldin_anchor(storage, ctx)

    if wp.checkpoint_every > 0 or wp.resume:
        from .checkpoint import CheckpointHook, instance_checkpoint_dir

        ctx.checkpoint_hook = CheckpointHook(
            instance_checkpoint_dir(instance_id), every_n=wp.checkpoint_every
        )

    def _profile_cm():
        if wp.profile_dir:
            # Device-level trace of the whole DASE train (XLA ops, HBM,
            # collectives) — the TPU answer to the Spark web UI the
            # reference leaned on (SURVEY.md §5.1). View with xprof/
            # tensorboard pointed at the directory.
            import jax

            return jax.profiler.trace(wp.profile_dir)
        return contextlib.nullcontext()

    try:
        models = _train_with_stale_checkpoint_fallback(
            engine, engine_params, ctx, wp, cm=_profile_cm)
        gang.beat()
        if wp.stop_after_read or wp.stop_after_prepare:
            instances.update(instance.with_status("ABORTED", _utcnow()))
            if ctx.checkpoint_hook is not None:
                ctx.checkpoint_hook.close()
                ctx.checkpoint_hook = None
            return instance_id

        # Persistence has no natural beat points, and at scale the
        # device_get + pickle + storage insert can outlast the stall
        # threshold — a background beat keeps the supervisor from
        # gang-killing a job whose training already succeeded.
        with gang.beat_while():
            _, _, algo_list, _ = engine.make_components(engine_params)
            persistent = 0
            for (name, algo), model in zip(algo_list, models):
                if isinstance(model, PersistentModel):
                    if model.save(instance_id, algo.params):
                        persistent += 1
            blob = serialize_models(algo_list, models)
            # Checksummed artifact via the single verifying-writer path
            # (workflow/model_artifact.py). The Model row MUST land
            # before the COMPLETED stamp below: a crash in between
            # leaves a RUNNING row (never deployed) instead of a
            # COMPLETED row without a model — and the verifying loader
            # skips the latter anyway, for rows written by older code.
            sha = model_artifact.write_model(storage, instance_id, blob)
            log.info(
                "models persisted: %d bytes pickled (sha256 %s), "
                "%d self-persisted",
                len(blob), sha[:12], persistent,
            )
            done = EngineInstance(
                **{**instance.__dict__, "id": instance_id}
            ).with_status("COMPLETED", _utcnow())
            instances.update(done)
            if ctx.checkpoint_hook is not None:
                ctx.checkpoint_hook.delete_all()  # superseded by the model
                ctx.checkpoint_hook = None
        _persist_foldin_anchor(storage, foldin_anchor, ctx,
                               engine_factory_name, engine_variant)
        log.info("EngineInstance %s COMPLETED", instance_id)
        return instance_id
    except Exception:
        # Best-effort ABORTED stamp: when the failure IS the storage
        # backend (dead store, open breaker), this second write fails
        # too — it must never mask the original training error, and the
        # row heals later (`--resume` liveness-checks RUNNING rows by
        # pid/host, so an unstamped row is still recoverable).
        try:
            instances.update(
                EngineInstance(
                    **{**instance.__dict__, "id": instance_id}
                ).with_status("ABORTED", _utcnow())
            )
        except Exception:  # noqa: BLE001 - the original error wins
            log.exception(
                "could not stamp EngineInstance %s ABORTED (storage "
                "unavailable?); surfacing the original failure", instance_id)
        if ctx.checkpoint_hook is not None:
            ctx.checkpoint_hook.close()  # keep snapshots for --resume
            ctx.checkpoint_hook = None
        raise


def load_deployment(
    engine: Engine,
    instance_id: Optional[str],
    ctx: Optional[WorkflowContext] = None,
    engine_factory_name: str = "",
    engine_variant: str = "default",
    exclude_ids=(),
    on_reject=None,
    app_name: Optional[str] = None,
):
    """Load a trained instance for serving (reference: CreateServer /
    MasterActor prepareDeployment). instance_id None → latest
    *deployable* COMPLETED: every candidate's stored model is verified
    (checksum/size/format via workflow/model_artifact.py) and a corrupt,
    missing or unpicklable artifact makes the loader WALK BACK to the
    next-older COMPLETED instance instead of crashing — the bad blob is
    counted (`pio_model_integrity_failures_total{kind}`) and kept on
    disk for forensics, never deleted. ``exclude_ids`` skips instances
    the caller has pinned (a rolled-back deployment must not be
    re-picked); ``on_reject(instance_id, kind)`` is called per skipped
    instance so callers (the refresh loop) can pin them instead of
    re-walking the same corpse every poll. An EXPLICIT instance_id
    never walks back: the operator asked for that version, so a
    failure surfaces as an error. ``app_name`` confines the candidate
    walk to ONE app's instances (the instances namespace is per
    factory/variant, not per app — multi-tenant serving interleaves
    every app's rows in one completed list)."""
    ctx = ctx or WorkflowContext()
    storage = ctx.get_storage()
    instances = storage.get_meta_data_engine_instances()
    excluded = set(exclude_ids or ())
    if instance_id is None:
        candidates = instances.get_completed(
            engine_factory_name or "engine", "1", engine_variant
        )
        if app_name is not None:
            candidates = [
                c for c in candidates
                if model_artifact.instance_app_name(c) == app_name]
        if not candidates:
            raise RuntimeError(
                "No COMPLETED engine instance found"
                + (f" for app {app_name!r}" if app_name else "")
                + "; run `pio train` first"
            )
        candidates = [c for c in candidates if c.id not in excluded]
        if not candidates:
            raise RuntimeError(
                "Every COMPLETED engine instance "
                + (f"for app {app_name!r} " if app_name else "")
                + "is pinned (rolled back "
                "or failed validation); train a fresh instance or reload "
                "one explicitly")
    else:
        instance = instances.get(instance_id)
        if instance is None:
            raise RuntimeError(f"Engine instance {instance_id} not found")
        candidates = [instance]

    rejected: list[str] = []
    caller_app_name = ctx.app_name
    for instance in candidates:
        try:
            payload = model_artifact.read_model(storage, instance.id)
        except model_artifact.ModelIntegrityError as e:
            if instance_id is not None:
                raise
            rejected.append(f"{instance.id} ({e.kind})")
            if on_reject is not None:
                on_reject(instance.id, e.kind)
            log.warning("%s; walking back to an older COMPLETED instance",
                        e)
            continue
        engine_params = EngineParams(
            data_source_params=json.loads(instance.data_source_params),
            preparator_params=json.loads(instance.preparator_params),
            algorithm_params_list=[
                (a["name"], a["params"])
                for a in json.loads(instance.algorithms_params)
            ],
            serving_params=json.loads(instance.serving_params),
        )
        ctx.engine_instance_id = instance.id
        # derive from THIS candidate, not whatever a previously rejected
        # candidate left behind — each walk iteration binds its own app
        if not caller_app_name:
            ctx.app_name = instance.env.get("appName", "")
        _, _, algo_list, _ = engine.make_components(engine_params)
        try:
            models = deserialize_models(payload, algo_list, instance.id, ctx)
        except Exception as e:  # noqa: BLE001 - checksummed yet unloadable
            if instance_id is not None:
                raise
            ctx.app_name = caller_app_name
            model_artifact.count_integrity_failure("deserialize")
            rejected.append(f"{instance.id} (deserialize)")
            if on_reject is not None:
                on_reject(instance.id, "deserialize")
            log.warning(
                "model for engine instance %s verified but failed to "
                "deserialize (%s); walking back to an older COMPLETED "
                "instance", instance.id, e)
            continue
        deployment = engine.prepare_deployment(ctx, engine_params, models)
        if rejected:
            log.warning(
                "deployed %s after skipping %d undeployable instance(s): "
                "%s", instance.id, len(rejected), ", ".join(rejected))
        return deployment, instance, engine_params
    raise RuntimeError(
        "No deployable COMPLETED engine instance: all candidates "
        f"rejected ({', '.join(rejected)}); blobs kept for forensics — "
        "`pio models verify` to inspect, `pio train` to replace")
