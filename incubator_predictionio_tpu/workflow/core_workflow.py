"""CoreWorkflow — run a training job and persist its results.

Reference: core/.../workflow/{CoreWorkflow,CreateWorkflow}.scala: stamp an
EngineInstance row RUNNING → COMPLETED, run engine.train, serialize models
into the Models DAO (or let PersistentModel models save themselves).
No spark-submit: the whole thing is one in-process call (SURVEY.md §7
design stance).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import pickle
from typing import Any, Optional

from ..controller.engine import Engine, EngineParams
from ..controller.persistent_model import PersistentModel
from ..data.storage.base import EngineInstance, Model
from ..data.storage.event import new_event_id
from .context import WorkflowContext
from .workflow_params import WorkflowParams

log = logging.getLogger("pio.workflow")


def _utcnow():
    return _dt.datetime.now(_dt.timezone.utc)


def serialize_models(algo_list, models: list[Any]) -> bytes:
    """Device pytrees → host → pickle (reference: Engine.makeSerializableModels
    + java serialization into the Models DAO). PersistentModel entries are
    replaced by a marker — they saved themselves."""
    prepared = []
    for (name, algo), model in zip(algo_list, models):
        if isinstance(model, PersistentModel):
            prepared.append({"__persistent__": type(model).__module__ + "." + type(model).__qualname__})
        else:
            prepared.append(algo.prepare_model_for_persistence(model))
    return pickle.dumps(prepared, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_models(blob: bytes, algo_list, instance_id: str, ctx) -> list[Any]:
    import importlib

    stored = pickle.loads(blob)
    out = []
    for (name, algo), item in zip(algo_list, stored):
        if isinstance(item, dict) and "__persistent__" in item:
            dotted = item["__persistent__"]
            module_name, _, cls_name = dotted.rpartition(".")
            cls = getattr(importlib.import_module(module_name), cls_name)
            out.append(cls.load(instance_id, ctx))
        else:
            out.append(item)
    return out


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    ctx: Optional[WorkflowContext] = None,
    workflow_params: Optional[WorkflowParams] = None,
    engine_factory_name: str = "",
    engine_variant: str = "default",
) -> str:
    """Run the training workflow; returns the engine-instance id.

    Call stack parity with SURVEY.md §3.1: Console→train lands here, then
    Engine.train → DataSource.read_training → Preparator.prepare →
    Algorithm.train (pjit'd hot loop) → model persistence.
    """
    ctx = ctx or WorkflowContext()
    wp = workflow_params or WorkflowParams()
    storage = ctx.get_storage()
    instances = storage.get_meta_data_engine_instances()

    instance = EngineInstance(
        id=new_event_id(),
        status="RUNNING",
        start_time=_utcnow(),
        end_time=None,
        engine_id=engine_factory_name or "engine",
        engine_version="1",
        engine_variant=engine_variant,
        engine_factory=engine_factory_name,
        batch=wp.batch,
        env={"appName": ctx.app_name},
        data_source_params=json.dumps(dict(engine_params.data_source_params)),
        preparator_params=json.dumps(dict(engine_params.preparator_params)),
        algorithms_params=json.dumps(
            [{"name": n, "params": dict(p)} for n, p in engine_params.algorithm_params_list]
        ),
        serving_params=json.dumps(dict(engine_params.serving_params)),
    )
    instance_id = instances.insert(instance)
    ctx.engine_instance_id = instance_id
    log.info("EngineInstance %s RUNNING", instance_id)

    try:
        models = engine.train(ctx, engine_params, wp)
        if wp.stop_after_read or wp.stop_after_prepare:
            instances.update(instance.with_status("ABORTED", _utcnow()))
            return instance_id

        _, _, algo_list, _ = engine.make_components(engine_params)
        persistent = 0
        for (name, algo), model in zip(algo_list, models):
            if isinstance(model, PersistentModel):
                if model.save(instance_id, algo.params):
                    persistent += 1
        blob = serialize_models(algo_list, models)
        storage.get_model_data_models().insert(Model(instance_id, blob))
        log.info(
            "models persisted: %d bytes pickled, %d self-persisted",
            len(blob), persistent,
        )
        done = EngineInstance(
            **{**instance.__dict__, "id": instance_id}
        ).with_status("COMPLETED", _utcnow())
        instances.update(done)
        log.info("EngineInstance %s COMPLETED", instance_id)
        return instance_id
    except Exception:
        instances.update(
            EngineInstance(**{**instance.__dict__, "id": instance_id}).with_status(
                "ABORTED", _utcnow()
            )
        )
        raise


def load_deployment(
    engine: Engine,
    instance_id: Optional[str],
    ctx: Optional[WorkflowContext] = None,
    engine_factory_name: str = "",
    engine_variant: str = "default",
):
    """Load a trained instance for serving (reference: CreateServer /
    MasterActor prepareDeployment). instance_id None → latest COMPLETED."""
    ctx = ctx or WorkflowContext()
    storage = ctx.get_storage()
    instances = storage.get_meta_data_engine_instances()
    if instance_id is None:
        latest = instances.get_latest_completed(
            engine_factory_name or "engine", "1", engine_variant
        )
        if latest is None:
            raise RuntimeError(
                "No COMPLETED engine instance found; run `pio train` first"
            )
        instance = latest
    else:
        instance = instances.get(instance_id)
        if instance is None:
            raise RuntimeError(f"Engine instance {instance_id} not found")

    engine_params = EngineParams(
        data_source_params=json.loads(instance.data_source_params),
        preparator_params=json.loads(instance.preparator_params),
        algorithm_params_list=[
            (a["name"], a["params"]) for a in json.loads(instance.algorithms_params)
        ],
        serving_params=json.loads(instance.serving_params),
    )
    ctx.engine_instance_id = instance.id
    if not ctx.app_name:
        ctx.app_name = instance.env.get("appName", "")
    model_row = storage.get_model_data_models().get(instance.id)
    if model_row is None:
        raise RuntimeError(f"No model blob for engine instance {instance.id}")
    _, _, algo_list, _ = engine.make_components(engine_params)
    models = deserialize_models(model_row.models, algo_list, instance.id, ctx)
    deployment = engine.prepare_deployment(ctx, engine_params, models)
    return deployment, instance, engine_params
