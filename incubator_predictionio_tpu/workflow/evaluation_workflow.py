"""EvaluationWorkflow — run candidate EngineParams, rank by metric.

Reference: core/.../workflow/EvaluationWorkflow.scala + CreateWorkflow's
eval dispatch (SURVEY.md §3.4): iterate generator candidates, run
engine.eval per candidate, feed MetricEvaluator, persist an
EvaluationInstance with the pretty/JSON results.
"""

from __future__ import annotations

import datetime as _dt
import logging
from typing import Optional

from ..controller.evaluation import EngineParamsGenerator, Evaluation
from ..controller.metric_evaluator import MetricEvaluator, MetricEvaluatorResult
from ..data.storage.base import EvaluationInstance
from ..data.storage.event import new_event_id
from .context import WorkflowContext

log = logging.getLogger("pio.evalworkflow")


def _utcnow():
    return _dt.datetime.now(_dt.timezone.utc)


def _eval_candidates_parallel(engine, params_list, ctx, parallelism):
    """Task parallelism over candidates (SURVEY.md §2.9 task row):
    independent EngineParams evaluate as independent XLA programs on
    DISJOINT single-device submeshes of the workflow mesh — an eval
    sweep on a v5e-8 runs up to 8 candidates concurrently. Each worker
    thread owns one device for its whole lifetime (so two candidates
    never contend for one chip's HBM), and jit caches key on that
    worker's submesh, so same-shape candidates reuse compilations.

    Note: candidates train on ONE device in this mode (layouts plan for
    1 shard), so scores can differ from a sequential whole-mesh run by
    float-reduction-order noise.
    """
    import concurrent.futures as cf
    import dataclasses as _dc
    import threading

    import jax

    from ..parallel.mesh import mesh_from_devices

    if jax.process_count() > 1:
        raise ValueError(
            "--parallel-candidates requires a single-controller run: "
            "per-candidate single-device meshes would hand workers "
            "devices owned by other processes (their collectives would "
            "hang). Run the sweep sequentially on multi-host.")
    devs = list(ctx.get_mesh().devices.flat)
    n_workers = max(1, min(parallelism, len(devs), len(params_list)))
    meshes = [mesh_from_devices(devices=[d]) for d in devs[:n_workers]]
    pool_lock = threading.Lock()
    local = threading.local()

    def run(idx_ep):
        idx, ep = idx_ep
        mesh = getattr(local, "mesh", None)
        if mesh is None:
            with pool_lock:
                mesh = meshes.pop()
            local.mesh = mesh
        dev = mesh.devices.flat[0]
        sub_ctx = _dc.replace(ctx, mesh=mesh)
        log.info("evaluating candidate %d/%d on %s",
                 idx + 1, len(params_list), dev)
        # default_device (thread-local) routes the serve-side arrays —
        # batch_predict / model device_puts that don't name a device —
        # onto this worker's chip too, not everyone onto device 0.
        with jax.default_device(dev):
            return ep, engine.eval(sub_ctx, ep, ctx.workflow_params)

    with cf.ThreadPoolExecutor(max_workers=n_workers) as ex:
        # ex.map yields in input order — candidate order is preserved
        return list(ex.map(run, enumerate(params_list)))


def run_evaluation(
    evaluation: Evaluation,
    generator: Optional[EngineParamsGenerator],
    ctx: Optional[WorkflowContext] = None,
    batch: str = "",
    evaluation_name: str = "",
    generator_name: str = "",
    parallelism: int = 1,
) -> tuple[MetricEvaluatorResult, str]:
    ctx = ctx or WorkflowContext()
    storage = ctx.get_storage()
    dao = storage.get_meta_data_evaluation_instances()
    engine, metric, other_metrics = evaluation.engine_metrics()
    params_list = (
        generator.params_list()
        if generator is not None
        else getattr(evaluation, "engine_params_list", None) or ()
    )
    if not params_list:
        raise ValueError(
            "no candidate EngineParams: pass an EngineParamsGenerator or set "
            "engine_params_list on the Evaluation"
        )

    instance = EvaluationInstance(
        id=new_event_id(),
        status="EVALRUNNING",
        start_time=_utcnow(),
        end_time=None,
        evaluation_class=evaluation_name or type(evaluation).__name__,
        engine_params_generator_class=generator_name or (type(generator).__name__ if generator else ""),
        batch=batch,
    )
    instance_id = dao.insert(instance)
    log.info("EvaluationInstance %s EVALRUNNING (%d candidates)",
             instance_id, len(params_list))
    try:
        if parallelism > 1:
            candidates = _eval_candidates_parallel(
                engine, params_list, ctx, parallelism)
        else:
            candidates = []
            for i, ep in enumerate(params_list):
                log.info("evaluating candidate %d/%d", i + 1, len(params_list))
                eval_data = engine.eval(ctx, ep, ctx.workflow_params)
                candidates.append((ep, eval_data))
        evaluator = MetricEvaluator(metric, other_metrics)
        result = evaluator.evaluate_candidates(candidates)
        done = EvaluationInstance(
            id=instance_id,
            status="EVALCOMPLETED",
            start_time=instance.start_time,
            end_time=_utcnow(),
            evaluation_class=instance.evaluation_class,
            engine_params_generator_class=instance.engine_params_generator_class,
            batch=batch,
            evaluator_results=result.pretty(),
            evaluator_results_html="",
            evaluator_results_json=result.to_json(),
        )
        dao.update(done)
        log.info("EvaluationInstance %s EVALCOMPLETED", instance_id)
        return result, instance_id
    except Exception:
        dao.update(
            EvaluationInstance(
                id=instance_id, status="EVALABORTED",
                start_time=instance.start_time, end_time=_utcnow(),
                evaluation_class=instance.evaluation_class,
                engine_params_generator_class=instance.engine_params_generator_class,
                batch=batch,
            )
        )
        raise
