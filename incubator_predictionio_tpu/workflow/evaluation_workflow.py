"""EvaluationWorkflow — run candidate EngineParams, rank by metric.

Reference: core/.../workflow/EvaluationWorkflow.scala + CreateWorkflow's
eval dispatch (SURVEY.md §3.4): iterate generator candidates, run
engine.eval per candidate, feed MetricEvaluator, persist an
EvaluationInstance with the pretty/JSON results.
"""

from __future__ import annotations

import datetime as _dt
import logging
from typing import Optional

from ..controller.evaluation import EngineParamsGenerator, Evaluation
from ..controller.metric_evaluator import MetricEvaluator, MetricEvaluatorResult
from ..data.storage.base import EvaluationInstance
from ..data.storage.event import new_event_id
from .context import WorkflowContext

log = logging.getLogger("pio.evalworkflow")


def _utcnow():
    return _dt.datetime.now(_dt.timezone.utc)


def run_evaluation(
    evaluation: Evaluation,
    generator: Optional[EngineParamsGenerator],
    ctx: Optional[WorkflowContext] = None,
    batch: str = "",
    evaluation_name: str = "",
    generator_name: str = "",
) -> tuple[MetricEvaluatorResult, str]:
    ctx = ctx or WorkflowContext()
    storage = ctx.get_storage()
    dao = storage.get_meta_data_evaluation_instances()
    engine, metric, other_metrics = evaluation.engine_metrics()
    params_list = (
        generator.params_list()
        if generator is not None
        else getattr(evaluation, "engine_params_list", None) or ()
    )
    if not params_list:
        raise ValueError(
            "no candidate EngineParams: pass an EngineParamsGenerator or set "
            "engine_params_list on the Evaluation"
        )

    instance = EvaluationInstance(
        id=new_event_id(),
        status="EVALRUNNING",
        start_time=_utcnow(),
        end_time=None,
        evaluation_class=evaluation_name or type(evaluation).__name__,
        engine_params_generator_class=generator_name or (type(generator).__name__ if generator else ""),
        batch=batch,
    )
    instance_id = dao.insert(instance)
    log.info("EvaluationInstance %s EVALRUNNING (%d candidates)",
             instance_id, len(params_list))
    try:
        candidates = []
        for i, ep in enumerate(params_list):
            log.info("evaluating candidate %d/%d", i + 1, len(params_list))
            eval_data = engine.eval(ctx, ep, ctx.workflow_params)
            candidates.append((ep, eval_data))
        evaluator = MetricEvaluator(metric, other_metrics)
        result = evaluator.evaluate_candidates(candidates)
        done = EvaluationInstance(
            id=instance_id,
            status="EVALCOMPLETED",
            start_time=instance.start_time,
            end_time=_utcnow(),
            evaluation_class=instance.evaluation_class,
            engine_params_generator_class=instance.engine_params_generator_class,
            batch=batch,
            evaluator_results=result.pretty(),
            evaluator_results_html="",
            evaluator_results_json=result.to_json(),
        )
        dao.update(done)
        log.info("EvaluationInstance %s EVALCOMPLETED", instance_id)
        return result, instance_id
    except Exception:
        dao.update(
            EvaluationInstance(
                id=instance_id, status="EVALABORTED",
                start_time=instance.start_time, end_time=_utcnow(),
                evaluation_class=instance.evaluation_class,
                engine_params_generator_class=instance.engine_params_generator_class,
                batch=batch,
            )
        )
        raise
