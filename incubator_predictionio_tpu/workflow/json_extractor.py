"""engine.json parsing + engine-factory resolution.

Reference: core/.../workflow/JsonExtractor.scala (JSON → Params) and the
reflective EngineFactory loading in CreateWorkflow. The Python analog:
``engineFactory`` is a dotted path ``package.module.ClassOrFunction``
resolved via importlib; it may name an EngineFactory subclass, a function
returning an Engine, or an Engine instance.

engine.json shape (wire-compatible with the reference):
{
  "id": "default", "description": ..., "engineFactory": "mytpl.engine.RecommendationEngine",
  "datasource": {"params": {...}},
  "preparator": {"params": {...}},
  "algorithms": [{"name": "als", "params": {...}}],
  "serving": {"params": {...}}
}
"""

from __future__ import annotations

import importlib
import json
import os
import sys
from typing import Any, Optional, Tuple

from ..controller.engine import Engine, EngineFactory, EngineParams


def load_engine_json(path: str, variant: Optional[str] = None) -> dict:
    """Read engine.json; ``variant`` selects engine.json.<variant> the way
    --engine-variant does upstream."""
    if variant:
        base, name = os.path.split(path)
        path = os.path.join(base, f"{name}.{variant}") if not name.endswith(variant) else path
    with open(path) as f:
        return json.load(f)


def resolve_engine_factory(dotted: str, engine_dir: Optional[str] = None):
    """Dotted path → callable returning an Engine (Doer/reflection analog).

    ``engine_dir`` is prepended to sys.path so template projects resolve
    exactly like the reference's engine-jar classpath."""
    if engine_dir and engine_dir not in sys.path:
        sys.path.insert(0, engine_dir)
    module_name, _, attr = dotted.rpartition(".")
    if not module_name:
        raise ValueError(f"engineFactory {dotted!r} must be module.ClassName")
    module = importlib.import_module(module_name)
    obj = getattr(module, attr)
    return obj


def engine_from_factory(factory_obj) -> Engine:
    if isinstance(factory_obj, Engine):
        return factory_obj
    if isinstance(factory_obj, type) and issubclass(factory_obj, EngineFactory):
        return factory_obj()()
    if isinstance(factory_obj, EngineFactory):
        return factory_obj()
    if callable(factory_obj):
        engine = factory_obj()
        if isinstance(engine, Engine):
            return engine
    raise TypeError(
        f"engineFactory resolved to {factory_obj!r}, which did not produce an Engine"
    )


def engine_and_params_from_json(
    engine_json: dict, engine_dir: Optional[str] = None
) -> Tuple[Engine, EngineParams, str]:
    factory_path = engine_json.get("engineFactory")
    if not factory_path:
        raise ValueError("engine.json is missing engineFactory")
    factory = resolve_engine_factory(factory_path, engine_dir)
    engine = engine_from_factory(factory)
    params = EngineParams.from_json(engine_json)
    return engine, params, factory_path
