""""Production day" soak — ONE scenario driver that runs the whole
story under SLOs (ISSUE 14; ROADMAP item 4).

Every subsystem has its own chaos harness (WAL crash replay, gang
kill, mid-compaction SIGKILL, poisoned retrain/fold-in, fleet canary
rollback); this driver exercises them TOGETHER: it launches the REAL
topology as subprocesses (partitioned event server ``--workers N``,
engine fleet ``pio deploy --replicas N`` with ``--model-refresh-ms``
and ``--online-foldin``), runs zipfian multi-app open-loop traffic —
ingest floods (singles + batches, enqueue + commit acks via
``X-Pio-Ack``) interleaved with deadline-carrying queries — for a
configurable wall budget while a fault scheduler injects the existing
fault menu on a seeded timeline (``PIO_FAULT_SPEC`` ``at:`` rules per
worker/replica plus driver-side poison events and retrains), then
asserts end-to-end SLOs from the telemetry registry (driver-side
scrapers of both ``/metrics`` endpoints) and the stores:

- **zero acked-event loss** — every event the flood got a 201 for is
  present EXACTLY once in the merged shards after WAL settle (the
  exactly-once ledger, reconciled offline)
- **zero non-{200,503,504}** HTTP responses (201 is ingest's 200)
- **accepted-query p99** under a bound
- **rollback within the watch window** for every poisoned publish
- **quality regression rolled back** — the shadow scorer graded real
  traffic, and a gate-passing, non-erroring, ranking-degrading
  publish (``poison_quality``) was rolled back with an explicit
  ``quality`` pin inside the window
- **fold-in freshness lag** under ``freshness_factor`` × the fold-in
  interval once traffic quiesces
- **clean drain** — both fronts exit 0 on SIGTERM

The scorecard (``SOAK.json`` + a ``measured_soak_*`` row in
BASELINE.json) is machine-readable and carries the scenario seed, so
any red soak replays: the zipfian generators AND the fault timeline
derive from one ``--seed``.

The driver deliberately spawns subprocesses (the topology IS the test
subject); ``tools/lint`` grants it the same spawn-confinement
exemption as ``parallel/supervisor.py`` — it only ever builds argv for
this repo's own console entry points.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import re
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("pio.soak")

__all__ = ["SoakConfig", "SoakPlan", "FaultAction", "plan_scenario",
           "run_soak", "evaluate_slos", "reconcile_ledger",
           "read_scorecard", "SLO_METRICS", "FAULT_POINTS", "FAULT_MENU"]

# ---------------------------------------------------------------------------
# registries (consumed by tools/lint rules_registry soak rules)
# ---------------------------------------------------------------------------

# telemetry families the driver scrapes and asserts fault evidence /
# SLO inputs from — lint (`soak-slo-registry`) fails when one of these
# stops being a documented metric family
SLO_METRICS = (
    "pio_ingest_events_total",
    "pio_ingest_append_errors_total",
    "pio_engine_rollbacks_total",
    "pio_fleet_rollbacks_total",
    "pio_foldin_publishes_total",
    "pio_foldin_rollbacks_total",
    "pio_foldin_freshness_lag_seconds",
    "pio_engine_quality_samples_total",
    "pio_engine_quality_breaches_total",
    "pio_query_cache_hits_total",
    "pio_query_cache_misses_total",
    "pio_query_cache_invalidations_total",
    "pio_tenant_shed_total",
    "pio_tenant_evictions_total",
    "pio_tenant_rollbacks_total",
    "pio_fleet_scale_events_total",
)

# spec-armed scenario faults → the fault POINT their PIO_FAULT_SPEC
# rule names — lint (`soak-fault-registry`) fails when a point is no
# longer armed anywhere (the fault-point-coverage contract)
FAULT_POINTS = {
    "worker_kill": "ingest.commit",
    "compact_crash": "compact.rename",
    "enospc_shed": "jsonl.append",
    "replica_kill": "query.serve",
}

# the full menu: spec faults above + driver-side scenario actions
# (poison events ride the data, retrains ride `pio train`)
FAULT_MENU = (
    "enospc_shed",      # scheduled OSError(ENOSPC) on one worker's log
    "poison_foldin",    # poison-serve event → increment rolls back
    "worker_kill",      # SIGKILL inside a group commit (WAL replay)
    "replica_kill",     # SIGKILL one replica mid-query (fleet only)
    "good_retrain",     # ordinary retrain → staged rollout/hot swap
    "compact_crash",    # SIGKILL inside a compaction rename
    "poison_retrain",   # gate-passing poisoned retrain → watch rollback
    "poison_quality",   # poison-rank event → non-erroring ranking
    #                     degradation; the QUALITY watch rolls it back
)

# where each fault lands inside the wall budget (fractions): rollback-
# sensitive faults stay early enough that their watch windows settle
_FAULT_WINDOWS = {
    "enospc_shed": (0.10, 0.20),
    "poison_foldin": (0.18, 0.30),
    "worker_kill": (0.30, 0.40),
    "replica_kill": (0.38, 0.48),
    "good_retrain": (0.45, 0.55),
    "compact_crash": (0.50, 0.60),
    "poison_retrain": (0.58, 0.66),
    # last: the degraded chain stays refused until the wall ends, so
    # nothing downstream should depend on fresh promotions
    "poison_quality": (0.66, 0.74),
}

# catalog size for the zipfian item popularity the floods rate against:
# ranking popular items first is MEASURABLY better than ranking them
# last, which is what gives the shadow scorer its NDCG signal
_ITEMS = 50


# ---------------------------------------------------------------------------
# config + plan
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SoakConfig:
    """One scenario. Everything observable derives from ``seed``."""

    engine_dir: str
    workdir: str
    seed: int = 20260804
    duration_s: float = 60.0
    event_workers: int = 2
    replicas: int = 2             # 0 = single-process engine server
    apps: int = 3
    primary_app: Optional[str] = None   # default: engine.json appName
    users: int = 400
    zipf_s: float = 1.1           # app/user popularity skew
    ingest_rps: float = 50.0      # offered, across all apps
    query_rps: float = 20.0
    batch_every: int = 8          # every Nth ingest is a batch POST
    batch_size: int = 6
    enqueue_frac: float = 0.5     # singles acked on enqueue vs commit
    query_deadline_ms: float = 8000.0
    foldin_ms: float = 250.0
    refresh_ms: float = 500.0     # single-process refresh poll
    swap_watch_ms: float = 2500.0
    swap_max_error_rate: float = 0.3
    # shadow scorer: every query sampled; the quality watch outlives
    # the error watch so the resolve pipeline (labels tail in, samples
    # age past the resolve window) fits inside it on a starved host
    quality_sample: float = 1.0
    quality_watch_ms: float = 6000.0
    # million-item serving (ISSUE 17): queries run with the served-
    # result cache armed and the host-shard threshold set, so the
    # kill/poison timeline fires AGAINST cached results — the
    # cache-freshness SLO row asserts rollbacks never left stale
    # entries serving. catalog_items widens the item universe the
    # floods rate against (zipf keeps the popularity head, so the
    # shadow scorer's NDCG signal survives a large catalog).
    catalog_items: int = _ITEMS
    query_cache_size: int = 256
    query_cache_ttl_ms: float = 30000.0
    serve_shard_items: int = 131072
    # multi-tenant serving (ISSUE 19): tenant_apps > 0 widens the app
    # universe to that many apps, trains EVERY app its own instance,
    # arms the engine's tenant mux (PIO_TENANT_MAX_RESIDENT) and
    # routes the query flood zipfian across all apps via X-Pio-App —
    # the `tenant-isolation` SLO row grades per-tenant availability
    # (a hot tenant's shed never reds a cold tenant's row) and that
    # the resident LRU actually churned. tenant_max_resident 0 = auto:
    # half the apps, min 2 — always smaller than the app count, so
    # evictions are guaranteed load-bearing, not incidental.
    tenant_apps: int = 0
    tenant_max_resident: int = 0
    # elastic topology (ISSUE 20): elastic=True deploys the engine
    # with `--replicas auto` and arms a RAMP phase — offered query
    # load steps ramp_factor× up at ~30% of the wall budget and back
    # down at ~65% — so the autoscaler is graded under a real load
    # step, both directions: `scale-up-within-bound` (a new replica
    # READY within scale_up_bound_s of the step) and `drain-on-quiet`
    # (fleet back at the floor within scale_down_bound_s of the load
    # going away, drained with zero non-{200,503,504})
    elastic: bool = False
    ramp_factor: float = 10.0
    ramp_up_frac: float = 0.30
    ramp_down_frac: float = 0.65
    scale_up_bound_s: float = 30.0
    scale_down_bound_s: float = 45.0
    elastic_max: int = 3          # PIO_FLEET_MAX_REPLICAS (min is 1)
    fleet_sync_ms: float = 200.0
    compact_interval_ms: float = 2000.0
    faults: tuple = FAULT_MENU
    # SLO thresholds
    p99_ms: float = 4000.0
    rollback_deadline_s: float = 30.0
    freshness_factor: float = 2.0
    freshness_settle_s: float = 20.0
    max_conn_errors: Optional[int] = None   # None → auto from kill count
    drain_timeout_s: float = 90.0
    ready_timeout_s: float = 120.0
    keep_workdir: bool = False
    out_path: Optional[str] = None          # default <cwd>/SOAK.json
    baseline_key: Optional[str] = None      # publish measured_soak_<key>
    env_extra: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FaultAction:
    """One scheduled fault: either a PIO_FAULT_SPEC ``at:`` rule armed
    on a worker/replica at launch, or a driver-side action fired by the
    scheduler thread at ``at_s`` past traffic start."""

    name: str
    kind: str                    # "spec" | "event" | "train"
    at_s: float
    point: Optional[str] = None  # spec faults: the fault point named
    target: Optional[str] = None # "worker:<i>" | "replica:<i>" | app
    spec: Optional[str] = None   # the PIO_FAULT_SPEC rule text
    detail: str = ""


@dataclasses.dataclass
class SoakPlan:
    cfg: SoakConfig
    app_names: list
    app_weights: list            # zipfian popularity over apps
    user_weights: list
    item_weights: list           # zipfian item popularity (NDCG signal)
    faults: list                 # [FaultAction]
    worker_specs: dict           # worker idx -> joined spec string
    replica_specs: dict          # replica idx -> joined spec string
    notes: list
    slos: dict                   # name -> bound (threshold snapshot)
    conn_budget: int = 0         # resolved once; the evaluator asserts
    #                              the SAME bound the dry run printed
    ramp: Optional[dict] = None  # elastic load step: {upAtS, downAtS,
    #                              factor, min, max}

    def describe(self) -> str:
        """The resolved scenario, human-readable (``--dry-run``)."""
        cfg = self.cfg
        lines = [
            f"Soak scenario (seed {cfg.seed}, {cfg.duration_s:.0f}s "
            "wall budget)",
            f"  topology: event server --workers {cfg.event_workers} "
            "(WAL on, compaction every "
            f"{cfg.compact_interval_ms:.0f}ms); engine "
            + (f"fleet --replicas auto [1, {max(2, cfg.elastic_max)}]"
               if cfg.elastic
               else f"fleet --replicas {cfg.replicas}" if cfg.replicas
               else "single process")
            + f", fold-in every {cfg.foldin_ms:.0f}ms, watch "
              f"{cfg.swap_watch_ms:.0f}ms",
            f"  apps: {', '.join(self.app_names)} (zipf s={cfg.zipf_s}"
            f", {cfg.users} users)",
            f"  traffic: ingest {cfg.ingest_rps:.0f}/s offered "
            f"(batch every {cfg.batch_every}, size {cfg.batch_size}, "
            f"{cfg.enqueue_frac:.0%} enqueue-acked), queries "
            f"{cfg.query_rps:.0f}/s with "
            f"{cfg.query_deadline_ms:.0f}ms deadlines",
            f"  serving: {cfg.catalog_items} items (host shards past "
            f"{cfg.serve_shard_items} rows); result cache "
            + (f"{cfg.query_cache_size} entries, TTL "
               f"{cfg.query_cache_ttl_ms:.0f}ms" if cfg.query_cache_size
               else "off"),
            *([f"  tenants: mux armed — {len(self.app_names)} apps "
               f"through one process, {_tenant_resident(cfg)} resident "
               "(X-Pio-App routed, per-app instances trained up front)"]
              if cfg.tenant_apps else []),
            "  phases: workspace+train -> launch+ready -> "
            f"{cfg.duration_s:.0f}s mixed load under faults -> "
            f"quiesce (freshness settle <= {cfg.freshness_settle_s:.0f}s)"
            " -> SIGTERM drain -> offline ledger reconcile -> scorecard",
            "  fault timeline:",
        ]
        for f in sorted(self.faults, key=lambda f: f.at_s):
            where = f" on {f.target}" if f.target else ""
            point = f" [{f.point}]" if f.point else ""
            lines.append(f"    t+{f.at_s:6.1f}s  {f.name}{where}"
                         f"{point}  ({f.kind}) {f.detail}")
        lines.append("  SLOs:")
        for name, bound in self.slos.items():
            lines.append(f"    {name}: {bound}")
        for n in self.notes:
            lines.append(f"  note: {n}")
        return "\n".join(lines)


def _engine_json_app(engine_dir: str) -> Optional[str]:
    """The datasource appName the template trains/queries/folds on:
    that app is the scenario's PRIMARY (queries + poisons target it;
    the other apps are ingest-only background load)."""
    try:
        with open(os.path.join(engine_dir, "engine.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    params = (doc.get("datasource") or {}).get("params") or {}
    return params.get("appName") or params.get("app_name") or None


def _conn_budget(cfg: SoakConfig, kills: int) -> int:
    """Connection-drop allowance: each crash fault opens a kill window
    (relaunch + WAL replay, ~5 s on a starved host) during which the
    open-loop floods keep offering — the budget scales with offered
    rate so it catches systemic connection failure, not TCP reality."""
    if cfg.max_conn_errors is not None:
        return cfg.max_conn_errors
    per_kill = int((cfg.ingest_rps + cfg.query_rps) * 5.0)
    return 20 + per_kill * max(1, kills)


def _tenant_resident(cfg: SoakConfig) -> int:
    """The resolved PIO_TENANT_MAX_RESIDENT bound (0 = mux off)."""
    if cfg.tenant_apps <= 0:
        return 0
    return cfg.tenant_max_resident or max(2, cfg.tenant_apps // 2)


def _zipf_weights(n: int, s: float, rng: random.Random) -> list:
    w = [1.0 / (i + 1) ** s for i in range(n)]
    rng.shuffle(w)
    total = sum(w)
    return [x / total for x in w]


def plan_scenario(cfg: SoakConfig) -> SoakPlan:
    """Resolve a config into the deterministic scenario: app/user
    popularity, the fault timeline with per-process spec assignments,
    and the SLO threshold snapshot. Same seed → same plan."""
    rng = random.Random(cfg.seed)
    primary = cfg.primary_app or _engine_json_app(cfg.engine_dir) \
        or "soak_a0"
    n_apps = max(cfg.apps, cfg.tenant_apps) if cfg.tenant_apps \
        else cfg.apps
    app_names = [primary] + [f"soak_a{i}" for i in range(1, n_apps)]
    app_weights = _zipf_weights(n_apps, cfg.zipf_s, rng)
    user_weights = _zipf_weights(cfg.users, cfg.zipf_s, rng)
    item_weights = _zipf_weights(max(1, cfg.catalog_items), cfg.zipf_s,
                                 rng)
    notes: list = []
    faults: list = []

    def offset(name: str) -> float:
        lo, hi = _FAULT_WINDOWS[name]
        return round(cfg.duration_s * rng.uniform(lo, hi), 1)

    requested = [f for f in cfg.faults if f in FAULT_MENU]
    for f in cfg.faults:
        if f not in FAULT_MENU:
            notes.append(f"unknown fault {f!r} dropped")
    if "replica_kill" in requested and (cfg.replicas < 2 or cfg.elastic):
        requested.remove("replica_kill")
        notes.append("replica_kill dropped: needs --replicas >= 2 "
                     "(a 0/1-replica deploy has no survivor to serve "
                     "through the kill)" if not cfg.elastic else
                     "replica_kill dropped: elastic membership is "
                     "dynamic — a launch-time spec cannot target a "
                     "slot the autoscaler owns")

    # spec faults are grouped per target process; a first-launch
    # process dies at its FIRST crash rule (restarts come up clean), so
    # the planner gives each crash fault its own worker when it can and
    # drops the extras loudly when it cannot
    worker_specs: dict = {}
    replica_specs: dict = {}
    crash_worker = 0

    for name in requested:
        at_s = offset(name)
        if name == "enospc_shed":
            w = cfg.event_workers - 1       # keep worker 0 for crashes
            rule = f"jsonl.append:at:{at_s * 1000:.0f}:oserr:28"
            worker_specs[w] = (worker_specs.get(w, "") + ";" + rule).strip(";")
            faults.append(FaultAction(
                name, "spec", at_s, point=FAULT_POINTS[name],
                target=f"worker:{w}", spec=rule,
                detail="one append fails ENOSPC → 503 shed window, "
                       "half-open recovery"))
        elif name in ("worker_kill", "compact_crash"):
            if crash_worker >= cfg.event_workers:
                notes.append(f"{name} dropped: every first-launch "
                             "worker already carries a crash rule "
                             "(one crash per process)")
                continue
            w = crash_worker
            crash_worker += 1
            point = FAULT_POINTS[name]
            rule = f"{point}:at:{at_s * 1000:.0f}:crash"
            worker_specs[w] = (worker_specs.get(w, "") + ";" + rule).strip(";")
            faults.append(FaultAction(
                name, "spec", at_s, point=point, target=f"worker:{w}",
                spec=rule,
                detail=("SIGKILL inside a group commit → supervisor "
                        "relaunch + WAL replay" if name == "worker_kill"
                        else "SIGKILL inside the compaction rename → "
                             "old snapshot stays active, rerun "
                             "converges")))
        elif name == "replica_kill":
            r = cfg.replicas - 1    # replica 0 is producer AND canary
            rule = f"query.serve:at:{at_s * 1000:.0f}:crash"
            replica_specs[r] = (replica_specs.get(r, "") + ";"
                                + rule).strip(";")
            faults.append(FaultAction(
                name, "spec", at_s, point=FAULT_POINTS[name],
                target=f"replica:{r}", spec=rule,
                detail="SIGKILL mid-query under flood → front routes "
                       "around it, supervisor relaunches"))
        elif name == "poison_foldin":
            app = app_names[0]
            faults.append(FaultAction(
                name, "event", at_s, target=app,
                detail="poison-serve event → gate-passing increment "
                       "rolls back through the watch, pinned"))
        elif name == "good_retrain":
            faults.append(FaultAction(
                name, "train", at_s,
                detail="ordinary retrain → staged canary/hot swap "
                       "promotes under live fire"))
        elif name == "poison_retrain":
            faults.append(FaultAction(
                name, "train", at_s, target=app_names[0],
                detail="poison-train event + retrain → gate passes, "
                       "watch rolls back + pins fleet-wide"))
        elif name == "poison_quality":
            faults.append(FaultAction(
                name, "event", at_s, target=app_names[0],
                detail="poison-rank event → gate-passing, NON-erroring "
                       "increment that ranks worst-first; only the "
                       "quality watch can catch it (reason `quality`). "
                       "No antidote: the poison rides ONE event, "
                       "consumed once by the fold-in cursor"))

    kills = sum(1 for f in faults if "kill" in f.name
                or f.name == "compact_crash")
    conn_budget = _conn_budget(cfg, kills)
    slos = {
        "acked-event-loss": "0 lost, 0 duplicated (exactly-once ledger"
                            " vs merged shards + WAL)",
        "http-codes": "ingest ⊆ {201,503}; query ⊆ {200,503,504}",
        "query-p99": f"accepted p99 <= {cfg.p99_ms:.0f}ms",
        "rollback-window": "every poisoned publish rolled back within "
                           f"{cfg.rollback_deadline_s:.0f}s",
        "quality-regression": (
            f"shadow scorer sampled live traffic "
            f"({cfg.quality_sample:.0%} of queries) and every quality "
            "poison was rolled back with reason `quality` within "
            f"{cfg.rollback_deadline_s:.0f}s"),
        "foldin-freshness": "settled lag <= "
                            f"{cfg.freshness_factor:.1f}x fold-in "
                            f"interval ({cfg.foldin_ms:.0f}ms)",
        "conn-errors": f"<= {conn_budget} connection-level drops "
                       "(kill-window TCP reality)",
        "clean-drain": "both fronts exit 0 on SIGTERM inside "
                       f"{cfg.drain_timeout_s:.0f}s",
        "cache-freshness": (
            f"armed result cache ({cfg.query_cache_size} entries, TTL "
            f"{cfg.query_cache_ttl_ms:.0f}ms) saw traffic and every "
            "rollback observation was covered by a cache invalidation "
            "event — no stale cached results after rollback"
            if cfg.query_cache_size > 0 else "cache disabled"),
    }
    if cfg.tenant_apps:
        bound = _tenant_resident(cfg)
        slos["tenant-isolation"] = (
            f"every offered tenant answered 200 ({n_apps} apps through "
            f"ONE engine process, X-Pio-App routed); a hot tenant's "
            f"503 shed never reds a cold tenant's row; resident LRU "
            f"bound {bound} < {n_apps} apps → evictions observed")
        notes.append(
            f"multi-tenant: {n_apps} apps, PIO_TENANT_MAX_RESIDENT="
            f"{bound}; the query flood's first sweep visits every app "
            "in order (guaranteed coverage + LRU churn), then goes "
            "zipfian")
    ramp = None
    if cfg.elastic:
        ramp = {
            "upAtS": round(cfg.duration_s * cfg.ramp_up_frac, 1),
            "downAtS": round(cfg.duration_s * cfg.ramp_down_frac, 1),
            "factor": cfg.ramp_factor,
            "min": 1,
            "max": max(2, cfg.elastic_max),
        }
        slos["scale-up-within-bound"] = (
            f"a replica beyond the floor READY within "
            f"{cfg.scale_up_bound_s:.0f}s of the {cfg.ramp_factor:.0f}x "
            f"load step at t+{ramp['upAtS']:.0f}s")
        slos["drain-on-quiet"] = (
            f"fleet back at the floor ({ramp['min']}) within "
            f"{cfg.scale_down_bound_s:.0f}s of the step-down at "
            f"t+{ramp['downAtS']:.0f}s — drained, never killed "
            "(non-{200,503,504} already reds http-codes)")
        notes.append(
            f"elastic: --replicas auto, bounds [1, {ramp['max']}]; the "
            "query flood multiplies its offered rate by "
            f"{cfg.ramp_factor:.0f} between t+{ramp['upAtS']:.0f}s and "
            f"t+{ramp['downAtS']:.0f}s; PIO_QUERY_MAX_PENDING is "
            "pinned low so the step is visible as utilization")
    notes.append("observations are scraped through quiesce: rollback "
                 "pins and fault evidence landing after the wall "
                 "budget (starved-host double-load) still count")
    return SoakPlan(cfg=cfg, app_names=app_names,
                    app_weights=app_weights, user_weights=user_weights,
                    item_weights=item_weights,
                    faults=faults, worker_specs=worker_specs,
                    replica_specs=replica_specs, notes=notes, slos=slos,
                    conn_budget=conn_budget, ramp=ramp)


# ---------------------------------------------------------------------------
# ledger + scrape state (shared, lock-guarded)
# ---------------------------------------------------------------------------

class _Ledger:
    """Everything the traffic threads observed, reconciled offline."""

    def __init__(self):
        self.lock = threading.Lock()
        self.acked: list = []         # (app, marker, event_id, mode)
        self.unacked: list = []       # (app, marker, why) — ambiguous
        self.ingest_codes: dict = {}
        self.query_codes: dict = {}
        self.latencies: list = []     # accepted (200) query seconds
        self.ingest_conn_errors = 0
        self.query_conn_errors = 0
        self.sent = 0
        self.violations: list = []    # first N non-contract responses
        self.tenant_codes: dict = {}  # app -> {code: n} (mux runs)

    _OK = {"ingest": (201, 503), "query": (200, 503, 504)}

    def code(self, table: str, code: int, t_off: float = -1.0,
             body: str = "") -> None:
        with self.lock:
            d = self.ingest_codes if table == "ingest" else self.query_codes
            d[code] = d.get(code, 0) + 1
            if code not in self._OK[table] and len(self.violations) < 10:
                # a red http-codes SLO must be diagnosable from the
                # scorecard: keep when/what for the first offenders
                self.violations.append(
                    {"table": table, "code": code,
                     "atS": round(t_off, 1), "body": body[:300]})

    def tenant_code(self, app: str, code: int) -> None:
        """Per-tenant response census (multi-tenant runs): the
        tenant-isolation SLO grades each app's OWN availability off
        this, so one hot tenant's shed cannot red a cold tenant."""
        with self.lock:
            d = self.tenant_codes.setdefault(app, {})
            d[code] = d.get(code, 0) + 1


class _Samples:
    """Driver-side scraper state: /status + /metrics samples from both
    fronts, keyed max() for counters, plus rollback / served-instance
    observations stamped with seconds past traffic start."""

    def __init__(self):
        self.lock = threading.Lock()
        self.metric_max: dict = {}    # "family{labels}" -> max value
        self.rollback_seen: list = [] # (t_off_s, key, detail)
        self.served: list = []        # (t_off_s, instance_id)
        self.foldin_lag: list = []    # (t_off_s, lag_seconds)
        self.foldin_publishes = 0
        self.restarts: dict = {}      # "replica:<i>" -> max restarts
        self.fleet_size: list = []    # (t_off_s, active, ready, target)
        self.query_cache: dict = {}   # /status queryCache counters, max
        self.tenants: dict = {}       # /status tenants doc, latest
        self._rollback_keys: set = set()

    def note_metrics(self, text: str) -> None:
        with self.lock:
            for name, value in _parse_prometheus(text):
                if value > self.metric_max.get(name, float("-inf")):
                    self.metric_max[name] = value

    def note_rollback(self, t_off: float, key: str, detail: str) -> None:
        with self.lock:
            if key in self._rollback_keys:
                return
            self._rollback_keys.add(key)
            self.rollback_seen.append((t_off, key, detail))


_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^}]*\})?)\s+([0-9eE+.\-]+)\s*$")


def _parse_prometheus(text: str):
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _PROM_LINE.match(line.strip())
        if m:
            try:
                yield m.group(1), float(m.group(2))
            except ValueError:
                continue


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _host_loop_mops() -> float:
    t0 = time.perf_counter()
    acc = 0
    for i in range(2_000_000):
        acc += i
    return 2.0 / (time.perf_counter() - t0)


class SoakRunner:
    """One soak run: workspace → topology → traffic + faults →
    quiesce → drain → reconcile → scorecard."""

    def __init__(self, plan: SoakPlan):
        self.plan = plan
        self.cfg = plan.cfg
        self.ledger = _Ledger()
        self.samples = _Samples()
        self.stop = threading.Event()
        # deploy freeze: while set, ingest skips the PRIMARY app so a
        # retrain is not leapfrogged by ever-newer fold-in increments
        # (the producer commits one per tick under load — "newest
        # COMPLETED wins" means sustained freshness starves retrains);
        # background apps and ALL queries continue at full rate
        self.pause_primary = threading.Event()
        # the scraper outlives `stop`: it keeps observing through
        # quiesce so rollback pins / fault evidence that land after
        # the wall budget (starved-host double-load) still count
        self.scrape_stop = threading.Event()
        self.procs: dict = {}
        self.logs: dict = {}
        self.app_ids: dict = {}
        self.access_keys: dict = {}
        self.instances: dict = {}     # label -> instance id
        self.fault_log: list = []     # scheduler's fired actions
        # elastic ramp: the query loops multiply their offered rate by
        # this each tick (the ramp thread steps it factor× up/down)
        self.rate_mult = 1.0
        self.event_port = _free_port()
        self.engine_port = _free_port()
        self.t0 = 0.0                 # traffic start (monotonic)
        self._storage = None

    # -- workspace ---------------------------------------------------------

    def _base_env(self) -> dict:
        cfg = self.cfg
        wd = cfg.workdir
        env = {
            **os.environ,
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "DB",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "DB",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "JL",
            "PIO_STORAGE_SOURCES_DB_TYPE": "SQLITE",
            "PIO_STORAGE_SOURCES_DB_PATH": os.path.join(wd, "meta.sqlite"),
            "PIO_STORAGE_SOURCES_JL_TYPE": "JSONL",
            "PIO_STORAGE_SOURCES_JL_PATH": os.path.join(wd, "events"),
            "PIO_FS_BASEDIR": os.path.join(wd, "store"),
            "PIO_WAL": "1",
            "PIO_WAL_FSYNC": "group",
            "PIO_WAL_DIR": os.path.join(wd, "wal"),
            "PIO_COMPACT_INTERVAL_MS": f"{cfg.compact_interval_ms:.0f}",
            "PIO_COMPACT_MIN_BYTES": "1",
            "PIO_FOLDIN_MS": f"{cfg.foldin_ms:.0f}",
            "PIO_SWAP_WATCH_MS": f"{cfg.swap_watch_ms:.0f}",
            # shadow-scored serving: sample everything, small minimum
            # so the thin-traffic gate still clears inside one watch
            "PIO_QUALITY_SAMPLE": f"{cfg.quality_sample}",
            "PIO_QUALITY_WATCH_MS": f"{cfg.quality_watch_ms:.0f}",
            "PIO_QUALITY_MIN_SAMPLES": "5",
            "PIO_QUALITY_RESOLVE_MS": "400",
            "PIO_QUALITY_MS": "100",
            "PIO_SWAP_MAX_ERROR_RATE": f"{cfg.swap_max_error_rate}",
            # million-item serving: cache + host-shard threshold armed
            # so the fault timeline fires against cached results (the
            # cache-freshness SLO row grades the invalidation contract)
            "PIO_QUERY_CACHE_SIZE": f"{cfg.query_cache_size:d}",
            "PIO_QUERY_CACHE_TTL_MS": f"{cfg.query_cache_ttl_ms:.0f}",
            "PIO_SERVE_SHARD_ITEMS": f"{cfg.serve_shard_items:d}",
            "PIO_FLEET_SYNC_MS": f"{cfg.fleet_sync_ms:.0f}",
            "PIO_FLEET_READY_MS": "150",
            # starved-host slack: mid-relaunch workers/replicas and
            # accept-queue droughts retry inside the fronts instead of
            # dropping clients (the gVisor netstack REFUSES connects
            # on a starved-but-healthy listener)
            "PIO_FLEET_CONNECT_RETRY_MS": "8000",
            "PIO_EVENT_CONNECT_RETRY_MS": "6000",
            # keep jax-free subprocess engines jax-free
            "PIO_COMPILATION_CACHE": "0",
            "JAX_PLATFORMS": "cpu",
        }
        if cfg.tenant_apps:
            # tenant mux armed in every engine process (fleet replicas
            # inherit): one process serves the whole app universe with
            # the resident LRU smaller than it
            env["PIO_TENANT_MAX_RESIDENT"] = str(_tenant_resident(cfg))
        if cfg.elastic:
            # elastic fleet: small pending limit so the ramp's load
            # step reads as utilization (pending/pendingLimit) fast;
            # quick ticks so detect→spawn fits the scale-up bound on a
            # 2-core host
            env["PIO_FLEET_MIN_REPLICAS"] = "1"
            env["PIO_FLEET_MAX_REPLICAS"] = str(max(2, cfg.elastic_max))
            env["PIO_QUERY_MAX_PENDING"] = "8"
            env["PIO_SCALE_TICK_MS"] = "200"
            env["PIO_SCALE_COOLDOWN_MS"] = "1500"
            env["PIO_SCALE_HYSTERESIS_TICKS"] = "2"
        for k in ("PIO_FAULT_SPEC", "PIO_EVENT_WORKER_FAULT_SPEC",
                  "PIO_FLEET_WORKER_FAULT_SPEC"):
            env.pop(k, None)
        env.update({k: str(v) for k, v in self.cfg.env_extra.items()})
        return env

    def storage(self):
        if self._storage is None:
            from ..data.storage.registry import Storage

            env = self._base_env()
            self._storage = Storage({
                k: v for k, v in env.items()
                if k.startswith("PIO_STORAGE")})
        return self._storage

    def _setup_workspace(self) -> None:
        from ..data.storage.base import AccessKey, App
        from ..data.storage.datamap import DataMap
        from ..data.storage.event import Event

        os.makedirs(self.cfg.workdir, exist_ok=True)
        os.makedirs(os.path.join(self.cfg.workdir, "logs"), exist_ok=True)
        s = self.storage()
        le = s.get_l_events()
        rng = random.Random(self.cfg.seed ^ 0x5EED)
        for name in self.plan.app_names:
            app_id = s.get_meta_data_apps().insert(App(0, name))
            le.init(app_id)
            key = s.get_meta_data_access_keys().insert(
                AccessKey("", app_id, ()))
            self.app_ids[name] = app_id
            self.access_keys[name] = key
            # seed ratings so the initial train has signal
            for i in range(8):
                le.insert(Event(
                    event="rate", entity_type="user",
                    entity_id=f"u{rng.randrange(self.cfg.users)}",
                    target_entity_type="item",
                    target_entity_id=f"i{i}",
                    properties=DataMap({"rating": float(i % 5 + 1)})),
                    app_id)

    # -- subprocess topology ----------------------------------------------

    def _console_argv(self, *args) -> list:
        return [sys.executable, "-m",
                "incubator_predictionio_tpu.tools.console", *args]

    def _spawn(self, label: str, argv: list, env: dict) -> subprocess.Popen:
        path = os.path.join(self.cfg.workdir, "logs", f"{label}.log")
        f = open(path, "ab")
        self.logs[label] = path
        proc = subprocess.Popen(argv, env=env, stdout=f,
                                stderr=subprocess.STDOUT)
        f.close()
        self.procs[label] = proc
        return proc

    def tail(self, label: str, n: int = 4000) -> str:
        try:
            with open(self.logs[label], "rb") as f:
                return f.read().decode(errors="replace")[-n:]
        except Exception:  # noqa: BLE001 — post-mortem best effort
            return "<no output>"

    def _train(self, label: str, engine_dir: Optional[str] = None) -> str:
        """One `pio train` subprocess against the workspace; returns
        the COMPLETED instance id parsed from its output."""
        out = subprocess.run(
            self._console_argv("train", "--engine-dir",
                               engine_dir or self.cfg.engine_dir),
            env=self._base_env(), capture_output=True, text=True,
            timeout=300)
        if out.returncode != 0:
            raise RuntimeError(
                f"soak {label} train failed rc={out.returncode}: "
                f"{(out.stdout + out.stderr)[-2000:]}")
        m = re.search(r"Engine instance ID: (\S+)", out.stdout)
        if not m:
            raise RuntimeError(
                f"soak {label} train printed no instance id: "
                f"{out.stdout[-2000:]}")
        self.instances[label] = m.group(1)
        return m.group(1)

    def _tenant_engine_dir(self, app: str) -> str:
        """A per-app copy of the engine template with the datasource
        appName swapped: `pio train` against it stamps env.appName =
        the tenant, which is what the mux's app-filtered candidate
        walk routes on. Same factory, same variant — every tenant's
        instances live in ONE metadata namespace, disambiguated by the
        app binding alone."""
        dst = os.path.join(self.cfg.workdir, "engines", app)
        if not os.path.isdir(dst):
            shutil.copytree(self.cfg.engine_dir, dst)
            path = os.path.join(dst, "engine.json")
            with open(path) as f:
                doc = json.load(f)
            params = doc.setdefault("datasource", {}).setdefault(
                "params", {})
            params.pop("app_name", None)
            params["appName"] = app
            with open(path, "w") as f:
                json.dump(doc, f)
        return dst

    def _train_tenants(self) -> None:
        """One instance per non-primary app, BEFORE the primary's
        initial train — the primary stays the newest COMPLETED row, so
        the deploy's default load picks it and every other app is
        served only through the mux."""
        for app in self.plan.app_names[1:]:
            self._train(f"tenant:{app}",
                        engine_dir=self._tenant_engine_dir(app))

    def _launch_event_server(self) -> None:
        env = self._base_env()
        for w, spec in self.plan.worker_specs.items():
            env[f"PIO_EVENT_WORKER_FAULT_SPEC_{w}"] = spec
        self._spawn("eventserver", self._console_argv(
            "eventserver", "--ip", "127.0.0.1",
            "--port", str(self.event_port),
            "--workers", str(self.cfg.event_workers)), env)

    def _launch_engine(self) -> None:
        cfg = self.cfg
        env = self._base_env()
        argv = self._console_argv(
            "deploy", "--engine-dir", cfg.engine_dir,
            "--ip", "127.0.0.1", "--port", str(self.engine_port),
            "--online-foldin")
        if cfg.elastic:
            argv += ["--replicas", "auto"]
        elif cfg.replicas:
            for r, spec in self.plan.replica_specs.items():
                env[f"PIO_FLEET_WORKER_FAULT_SPEC_{r}"] = spec
            argv += ["--replicas", str(cfg.replicas)]
        else:
            argv += ["--model-refresh-ms", f"{cfg.refresh_ms:.0f}"]
        self._spawn("engine", argv, env)

    def _http(self, method: str, url: str, *, timeout: float = 5.0,
              headers: Optional[dict] = None, body=None):
        import requests

        fn = requests.post if method == "POST" else requests.get
        kw: dict = {"timeout": timeout, "headers": headers}
        if body is not None:
            kw["json"] = body
        return fn(url, **kw)

    def _wait_ready(self) -> None:
        """Both fronts answering before traffic starts."""
        deadline = time.monotonic() + self.cfg.ready_timeout_s
        ev_base = f"http://127.0.0.1:{self.event_port}"
        en_base = f"http://127.0.0.1:{self.engine_port}"
        ev_ok = en_ok = False
        while time.monotonic() < deadline and not (ev_ok and en_ok):
            for label in ("eventserver", "engine"):
                p = self.procs[label]
                if p.poll() is not None:
                    raise RuntimeError(
                        f"soak {label} died at startup "
                        f"(rc={p.returncode}): {self.tail(label)}")
            try:
                if not ev_ok:
                    ev_ok = self._http(
                        "GET", ev_base + "/", timeout=2).status_code == 200
            except Exception:  # noqa: BLE001 — still booting
                pass
            try:
                if not en_ok:
                    if self.cfg.elastic:
                        doc = self._http("GET", en_base + "/healthz",
                                         timeout=2).json()
                        # the floor is enough: the ramp grows the rest
                        en_ok = (doc.get("readyReplicas") or 0) >= 1
                    elif self.cfg.replicas:
                        doc = self._http("GET", en_base + "/healthz",
                                         timeout=2).json()
                        en_ok = (doc.get("readyReplicas")
                                 == self.cfg.replicas)
                    else:
                        en_ok = self._http(
                            "GET", en_base + "/status",
                            timeout=2).status_code == 200
            except Exception:  # noqa: BLE001 — still booting
                pass
            time.sleep(0.25)
        if not (ev_ok and en_ok):
            raise RuntimeError(
                "soak topology not ready in "
                f"{self.cfg.ready_timeout_s:.0f}s — eventserver "
                f"ok={ev_ok} engine ok={en_ok}\n"
                f"eventserver: {self.tail('eventserver', 1500)}\n"
                f"engine: {self.tail('engine', 1500)}")

    # -- traffic -----------------------------------------------------------

    def _pick(self, rng: random.Random, names: list, weights: list):
        return rng.choices(names, weights=weights, k=1)[0]

    def _pick_item(self, rng: random.Random) -> int:
        # zipfian item popularity: the floods concentrate their ratings
        # on a head of popular items, so a ranking that puts the head
        # first scores measurably better than one that buries it — the
        # signal the quality watch grades poison_quality against
        return rng.choices(range(len(self.plan.item_weights)),
                           weights=self.plan.item_weights, k=1)[0]

    def _ingest_loop(self, idx: int, rate: float) -> None:
        """Open-loop single/batch ingest at ``rate``/s, zipfian over
        apps and users, alternating enqueue/commit acks. Failures are
        recorded, never retried — the ledger owns the truth."""
        import requests

        cfg = self.cfg
        rng = random.Random(cfg.seed * 1000 + idx)
        base = f"http://127.0.0.1:{self.event_port}"
        # keep-alive like a real SDK: the L4 front splices the
        # connection once, so steady state costs zero connects; after
        # any failure the pool is dropped and the next request
        # re-splices (possibly onto a different worker)
        sess = requests.Session()
        period = 1.0 / rate
        nxt = time.monotonic()
        n = 0
        while not self.stop.is_set():
            nxt += period * (0.5 + rng.random())   # jittered open loop
            delay = nxt - time.monotonic()
            if delay > 0:
                if self.stop.wait(delay):
                    break
            else:
                nxt = time.monotonic()             # fell behind: skip
            n += 1
            app = self._pick(rng, self.plan.app_names,
                             self.plan.app_weights)
            if self.pause_primary.is_set() \
                    and app == self.plan.app_names[0]:
                others = self.plan.app_names[1:]
                if not others:
                    continue        # single-app scenario: skip the send
                app = others[rng.randrange(len(others))]
            key = self.access_keys[app]
            user = rng.choices(range(cfg.users),
                               weights=self.plan.user_weights, k=1)[0]
            if n % cfg.batch_every == 0:
                events, markers = [], []
                for _ in range(cfg.batch_size):
                    marker = self._next_marker(idx)
                    markers.append(marker)
                    events.append(self._event_json(
                        f"u{user}", self._pick_item(rng), marker, rng))
                try:
                    r = sess.post(
                        f"{base}/batch/events.json?accessKey={key}",
                        json=events, timeout=12)
                except requests.RequestException:
                    sess.close()
                    with self.ledger.lock:
                        self.ledger.ingest_conn_errors += 1
                        for mk in markers:
                            self.ledger.unacked.append(
                                (app, mk, "conn-error"))
                    continue
                if r.status_code == 200:
                    for mk, item in zip(markers, r.json()):
                        self.ledger.code(
                            "ingest", item["status"],
                            time.monotonic() - self.t0,
                            str(item.get("message", "")))
                        if item["status"] == 201:
                            with self.ledger.lock:
                                self.ledger.acked.append(
                                    (app, mk, item["eventId"], "batch"))
                        else:
                            with self.ledger.lock:
                                self.ledger.unacked.append(
                                    (app, mk, f"item-{item['status']}"))
                else:
                    self.ledger.code("ingest", r.status_code,
                                     time.monotonic() - self.t0,
                                     r.text)
                    with self.ledger.lock:
                        for mk in markers:
                            self.ledger.unacked.append(
                                (app, mk, f"batch-{r.status_code}"))
            else:
                marker = self._next_marker(idx)
                mode = ("enqueue" if rng.random() < cfg.enqueue_frac
                        else "commit")
                try:
                    r = sess.post(
                        f"{base}/events.json?accessKey={key}",
                        json=self._event_json(
                            f"u{user}", self._pick_item(rng), marker,
                            rng),
                        headers={"X-Pio-Ack": mode}, timeout=12)
                except requests.RequestException:
                    sess.close()
                    with self.ledger.lock:
                        self.ledger.ingest_conn_errors += 1
                        self.ledger.unacked.append(
                            (app, marker, "conn-error"))
                    continue
                self.ledger.code("ingest", r.status_code,
                                 time.monotonic() - self.t0, r.text)
                if r.status_code == 201:
                    with self.ledger.lock:
                        self.ledger.acked.append(
                            (app, marker, r.json()["eventId"], mode))
                else:
                    with self.ledger.lock:
                        self.ledger.unacked.append(
                            (app, marker, f"http-{r.status_code}"))

    _marker_lock = threading.Lock()

    def _next_marker(self, idx: int) -> str:
        with self._marker_lock:
            self.ledger.sent += 1
            return f"soak-{idx}-{self.ledger.sent}"

    @staticmethod
    def _event_json(user: str, item: int, marker: str,
                    rng: random.Random) -> dict:
        return {"event": "rate", "entityType": "user", "entityId": user,
                "targetEntityType": "item", "targetEntityId": f"i{item}",
                "properties": {"rating": float(rng.randrange(1, 6)),
                               "marker": marker}}

    def _query_loop(self, idx: int, rate: float) -> None:
        """Open-loop deadline-carrying queries against the engine."""
        import requests

        cfg = self.cfg
        rng = random.Random(cfg.seed * 2000 + idx)
        base = f"http://127.0.0.1:{self.engine_port}"
        sess = requests.Session()
        nxt = time.monotonic()
        apps = self.plan.app_names
        n = 0
        while not self.stop.is_set():
            period = 1.0 / (rate * max(0.01, self.rate_mult))
            nxt += period * (0.5 + rng.random())
            delay = nxt - time.monotonic()
            if delay > 0:
                if self.stop.wait(delay):
                    break
            else:
                nxt = time.monotonic()
            user = rng.choices(range(cfg.users),
                               weights=self.plan.user_weights, k=1)[0]
            headers = {"X-Pio-Deadline-Ms":
                       f"{cfg.query_deadline_ms:.0f}"}
            app = None
            if cfg.tenant_apps:
                # first sweep visits every app in order — guaranteed
                # per-tenant coverage AND forced LRU churn (the sweep
                # is wider than the resident bound) — then zipfian
                app = (apps[(idx + n) % len(apps)] if n < len(apps)
                       else self._pick(rng, apps,
                                       self.plan.app_weights))
                headers["X-Pio-App"] = app
            n += 1
            body: dict = {"user": f"u{user}"}
            if cfg.elastic:
                # each query holds its admission slot ~50ms: capacity
                # becomes conc/holdS per replica, so the ramp's 10x
                # step builds real queue depth (a microsecond-answer
                # engine reads as quiet at ANY offered rate); the
                # nonce keeps each query cache-unique — a result-cache
                # hit answers before admission, so a zipfian flood
                # served from cache would be invisible to the scaler
                body["holdS"] = 0.05
                body["nonce"] = f"{idx}-{n}"
            t0 = time.monotonic()
            try:
                r = sess.post(
                    base + "/queries.json", json=body,
                    headers=headers,
                    timeout=max(15.0, cfg.query_deadline_ms / 1000 + 5))
            except requests.RequestException:
                sess.close()
                if not self.stop.is_set():
                    with self.ledger.lock:
                        self.ledger.query_conn_errors += 1
                continue
            self.ledger.code("query", r.status_code,
                             time.monotonic() - self.t0, r.text)
            if app is not None:
                self.ledger.tenant_code(app, r.status_code)
            if r.status_code == 200:
                with self.ledger.lock:
                    self.ledger.latencies.append(time.monotonic() - t0)

    def _ramp_loop(self) -> None:
        """Elastic load step: multiply the offered query rate by
        ``ramp_factor`` at ``upAtS``, back to 1x at ``downAtS`` — the
        autoscaler's detect→spawn→ready and drain-on-quiet brackets
        are graded against these two instants."""
        ramp = self.plan.ramp
        if not ramp:
            return
        for at_s, mult in ((ramp["upAtS"], ramp["factor"]),
                           (ramp["downAtS"], 1.0)):
            delay = at_s - (time.monotonic() - self.t0)
            if delay > 0 and self.stop.wait(delay):
                return
            self.rate_mult = mult
            self.fault_log.append({
                "name": "ramp", "ok": True,
                "firedAtS": round(time.monotonic() - self.t0, 1),
                "detail": f"offered query rate x{mult:g}"})

    # -- scraper -----------------------------------------------------------

    def _scrape_loop(self) -> None:
        ev_base = f"http://127.0.0.1:{self.event_port}"
        en_base = f"http://127.0.0.1:{self.engine_port}"
        while not self.scrape_stop.wait(1.0):
            self._scrape_once(ev_base, en_base)
        self._scrape_once(ev_base, en_base)     # final sample

    def _scrape_once(self, ev_base: str, en_base: str) -> None:
        for base in (ev_base, en_base):
            try:
                self.samples.note_metrics(self._http(
                    "GET", base + "/metrics", timeout=4).text)
            except Exception:  # noqa: BLE001 — kill windows drop scrapes
                pass
        try:
            doc = self._http("GET", en_base + "/status", timeout=4).json()
        except Exception:  # noqa: BLE001
            return
        t_off = time.monotonic() - self.t0
        with self.samples.lock:
            iid = doc.get("engineInstanceId")
            if iid and (not self.samples.served
                        or self.samples.served[-1][1] != iid):
                self.samples.served.append((t_off, iid))
        lc = doc.get("lifecycle") or {}
        for inst, reason in (lc.get("pinned") or {}).items():
            if reason in ("error-rate", "validate", "quality") \
                    or reason.startswith("integrity"):
                self.samples.note_rollback(
                    t_off, f"lifecycle:{inst}", f"pinned {reason}")
        fleet = doc.get("fleet") or {}
        directive = fleet.get("directive") or {}
        for inst, reason in (directive.get("pinned") or {}).items():
            self.samples.note_rollback(
                t_off, f"fleet:{inst}", f"directive pin {reason}")
        tn = doc.get("tenants")
        if isinstance(tn, dict):
            with self.samples.lock:
                # eviction counter is monotonic per process; keep the
                # freshest snapshot (fleet scrapes splice to ONE
                # replica per connection — a lower bound, like the
                # cache counters below)
                if (tn.get("evictions", 0)
                        >= self.samples.tenants.get("evictions", 0)):
                    self.samples.tenants = tn
            # a mux tenant's own rollback pin is a rollback
            # observation like any lifecycle/directive pin — a poison
            # landing on a resident tenant must still satisfy the
            # rollback-window row
            for row in tn.get("tenants") or []:
                for inst, reason in (row.get("pinned") or {}).items():
                    self.samples.note_rollback(
                        t_off, f"tenant:{row.get('app')}:{inst}",
                        f"tenant {row.get('app')} pin {reason}")
        qc = doc.get("queryCache")
        if isinstance(qc, dict):
            # counters are monotonic per replica; keyed max() mirrors
            # note_metrics (fleet scrapes splice to ONE replica per
            # connection, so this is a lower bound across the fleet)
            with self.samples.lock:
                for key in ("hits", "misses", "invalidations",
                            "invalidatedEntries", "evictions",
                            "entries"):
                    v = qc.get(key)
                    if isinstance(v, (int, float)):
                        self.samples.query_cache[key] = max(
                            self.samples.query_cache.get(key, 0), v)
        fold = doc.get("foldin") or {}
        if fold.get("producer") and fold.get("enabled", True):
            lag = fold.get("lagSeconds")
            with self.samples.lock:
                if lag is not None:
                    self.samples.foldin_lag.append((t_off, float(lag)))
                self.samples.foldin_publishes = max(
                    self.samples.foldin_publishes,
                    int(fold.get("publishes") or 0))
        if self.cfg.replicas or self.cfg.elastic:
            try:
                h = self._http("GET", en_base + "/healthz",
                               timeout=4).json()
            except Exception:  # noqa: BLE001
                return
            with self.samples.lock:
                for b in h.get("backends", []):
                    k = f"replica:{b.get('replica')}"
                    self.samples.restarts[k] = max(
                        self.samples.restarts.get(k, 0),
                        int(b.get("restarts") or 0))
                if self.cfg.elastic:
                    self.samples.fleet_size.append((
                        round(t_off, 1),
                        int(h.get("activeReplicas") or 0),
                        int(h.get("readyReplicas") or 0),
                        int(h.get("targetReplicas") or 0)))

    # -- fault scheduler ---------------------------------------------------

    def _fault_loop(self) -> None:
        """Driver-side actions on the timeline (spec faults are armed
        in the worker/replica environments and fire themselves)."""
        actions = sorted((f for f in self.plan.faults
                          if f.kind in ("event", "train")),
                         key=lambda f: f.at_s)
        for f in actions:
            delay = self.t0 + f.at_s - time.monotonic()
            if delay > 0 and self.stop.wait(delay):
                return
            if self.stop.is_set():
                return
            try:
                t_fire = time.monotonic()
                entry = {"name": f.name, "atS": f.at_s,
                         "firedAtS": round(t_fire - self.t0, 2),
                         "ok": True}
                if f.name == "poison_foldin":
                    self._insert_control(f.target, "poison-serve")
                elif f.name == "poison_quality":
                    self._insert_control(f.target, "poison-rank")
                elif f.name == "good_retrain":
                    entry["instance"], t_pub = self._retrain_frozen(
                        "good_retrain")
                    entry["firedAtS"] = round(t_pub - self.t0, 2)
                elif f.name == "poison_retrain":
                    n_rb = len(self.samples.rollback_seen)
                    self._insert_control(f.target, "poison-train")
                    try:
                        entry["instance"], t_pub = self._retrain_frozen(
                            "poison_retrain",
                            settled=lambda: len(
                                self.samples.rollback_seen) > n_rb)
                        # the rollback-window clock starts when the
                        # poisoned instance became publishable (the
                        # COMPLETED stamp), not when the control event
                        # landed — `pio train` wall time is not watch
                        # time
                        entry["firedAtS"] = round(t_pub - self.t0, 2)
                    finally:
                        # later retrains come up clean: the antidote
                        # out-dates the poison marker
                        self._insert_control(f.target, "antidote")
                self.fault_log.append(entry)
            except Exception as e:  # noqa: BLE001 — scorecard decides
                log.exception("soak fault %s failed", f.name)
                self.fault_log.append(
                    {"name": f.name, "atS": f.at_s, "ok": False,
                     "error": str(e)})

    def _retrain_frozen(self, label: str, settled=None):
        """One retrain under a deploy freeze: primary-app ingest pauses
        (fold-in increments stop outdating the retrain), the retrain
        lands and rides the normal staged rollout, and ingest resumes
        once the rollout settled — the new instance observed serving
        (good) or its rollback observed (poisoned) — or a bounded wait
        elapsed. Queries and background-app ingest never pause.
        Returns (instance id, monotonic instant the instance became
        publishable)."""
        self.pause_primary.set()
        try:
            iid = self._train(label)
            t_pub = time.monotonic()
            if settled is None:
                def settled():
                    with self.samples.lock:
                        return any(i == iid
                                   for _t, i in self.samples.served)
            deadline = t_pub + self.cfg.rollback_deadline_s
            while time.monotonic() < deadline and not settled():
                if self.stop.wait(0.25):
                    break
            return iid, t_pub
        finally:
            self.pause_primary.clear()

    def _insert_control(self, app: str, event: str) -> None:
        """Scenario control events ride the DATA (the fold-in threat
        model): inserted straight into the base shard, which every
        merged read and the log tailer already cover."""
        from ..data.storage.event import Event

        self.storage().get_l_events().insert(
            Event(event=event, entity_type="sys", entity_id="soak"),
            self.app_ids[app])

    # -- quiesce + drain + reconcile ---------------------------------------

    def _quiesce(self) -> dict:
        """After traffic stops: wait for fold-in to catch up and any
        in-flight watch windows to settle; returns freshness result."""
        cfg = self.cfg
        en_base = f"http://127.0.0.1:{self.engine_port}"
        bound_s = cfg.freshness_factor * cfg.foldin_ms / 1000.0
        deadline = time.monotonic() + cfg.freshness_settle_s
        final_lag = None
        while time.monotonic() < deadline:
            try:
                doc = self._http("GET", en_base + "/status",
                                 timeout=4).json()
            except Exception:  # noqa: BLE001
                time.sleep(0.3)
                continue
            fold = doc.get("foldin") or {}
            if fold.get("producer") and fold.get("enabled", True):
                lag = fold.get("lagSeconds")
                if lag is not None:
                    final_lag = float(lag)
                    if final_lag <= bound_s:
                        break
            time.sleep(0.3)
        # let a watch window opened by the last publishes close — the
        # QUALITY watch is the longest one, and the scrape loop is
        # still running, so late rollback pins are still observed
        time.sleep(min(6.0, max(cfg.swap_watch_ms,
                                cfg.quality_watch_ms) / 1000.0 + 0.5))
        return {"finalLagS": final_lag, "boundS": bound_s}

    def _drain(self) -> dict:
        out = {}
        for label in ("engine", "eventserver"):
            p = self.procs.get(label)
            if p is None:
                continue
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
                try:
                    rc = p.wait(timeout=self.cfg.drain_timeout_s)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)
                    rc = -9
            else:
                rc = p.returncode
            out[label] = rc
        return out

    def kill_all(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass

    def _event_supervisor_doc(self) -> Optional[dict]:
        p = self.procs.get("eventserver")
        if p is None:
            return None
        path = os.path.join(self._base_env()["PIO_FS_BASEDIR"], "gang",
                            f"pid{p.pid}", "supervisor.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- the run -----------------------------------------------------------

    def run(self) -> dict:
        cfg = self.cfg
        plan = self.plan
        started = time.time()
        mops = _host_loop_mops()
        self._setup_workspace()
        if cfg.tenant_apps:
            self._train_tenants()
        self._train("initial")
        self._launch_event_server()
        self._launch_engine()
        self._wait_ready()

        scrape_t = threading.Thread(target=self._scrape_loop,
                                    daemon=True, name="soak-scrape")
        scrape_t.start()
        threads = [threading.Thread(target=self._fault_loop,
                                    daemon=True, name="soak-faults")]
        if plan.ramp:
            threads.append(threading.Thread(
                target=self._ramp_loop, daemon=True, name="soak-ramp"))
        n_ing = 2 if cfg.ingest_rps > 25 else 1
        for i in range(n_ing):
            threads.append(threading.Thread(
                target=self._ingest_loop, args=(i, cfg.ingest_rps / n_ing),
                daemon=True, name=f"soak-ingest-{i}"))
        n_q = 2 if cfg.query_rps > 15 else 1
        if plan.ramp:
            # the ramp must be able to SATURATE: a synchronous client
            # lane holds ONE query in flight, so replica queue depth
            # is bounded by the fan-out — 16 lanes let the 10x step
            # push the floor replica past the scale-up threshold,
            # then spread thin once the fleet grows
            n_q = 16
        for i in range(n_q):
            threads.append(threading.Thread(
                target=self._query_loop, args=(i, cfg.query_rps / n_q),
                daemon=True, name=f"soak-query-{i}"))
        self.t0 = time.monotonic()
        for t in threads:
            t.start()
        try:
            time.sleep(cfg.duration_s)
        finally:
            self.stop.set()
        for t in threads:
            t.join(45)
        freshness = self._quiesce()
        self.scrape_stop.set()
        scrape_t.join(20)
        drain = self._drain()
        supervisor_doc = self._event_supervisor_doc()
        reconciliation = reconcile_ledger(self.storage(), self.ledger,
                                          self.app_ids,
                                          self._base_env())
        slos, faults = evaluate_slos(
            plan, self.ledger, self.samples, reconciliation, freshness,
            drain, supervisor_doc, self.fault_log)
        verdict = "PASS" if all(s["ok"] for s in slos) else "FAIL"
        with self.ledger.lock:
            traffic = {
                "sentMarkers": self.ledger.sent,
                "acked": len(self.ledger.acked),
                "unacked": len(self.ledger.unacked),
                "ingestCodes": dict(sorted(
                    self.ledger.ingest_codes.items())),
                "queryCodes": dict(sorted(
                    self.ledger.query_codes.items())),
                "ingestConnErrors": self.ledger.ingest_conn_errors,
                "queryConnErrors": self.ledger.query_conn_errors,
                "acceptedQueries": len(self.ledger.latencies),
                "queryP50Ms": round(_pct(self.ledger.latencies, 50)
                                    * 1000, 1),
                "queryP99Ms": round(_pct(self.ledger.latencies, 99)
                                    * 1000, 1),
            }
        with self.samples.lock:
            query_cache = dict(self.samples.query_cache)
            tenant_snap = dict(self.samples.tenants)
        scorecard = {
            "v": 1,
            "verdict": verdict,
            "seed": cfg.seed,
            "startedAt": started,
            "wallS": round(time.time() - started, 1),
            "durationS": cfg.duration_s,
            "topology": {
                "eventWorkers": cfg.event_workers,
                "replicas": cfg.replicas,
                "apps": plan.app_names,
                "foldinMs": cfg.foldin_ms,
                "watchMs": cfg.swap_watch_ms,
                "tenantApps": cfg.tenant_apps,
                "tenantMaxResident": _tenant_resident(cfg),
                "elastic": cfg.elastic,
                "ramp": plan.ramp,
            },
            "slos": slos,
            "faults": faults,
            "traffic": traffic,
            "freshness": freshness,
            "queryCache": query_cache,
            "tenants": tenant_snap if cfg.tenant_apps else None,
            "drainRc": drain,
            "reconciliation": {k: v for k, v in reconciliation.items()
                               if k != "perMarker"},
            "host": {
                "loopMops": round(mops, 2),
                "note": "2-core gVisor sandbox: offered rates are "
                        "upper bounds, achieved counts recorded above "
                        "(PR 3/8 host-ceiling precedent)",
            },
            "planNotes": plan.notes,
        }
        return scorecard


def _pct(values: list, p: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    return vs[min(len(vs) - 1, int(len(vs) * p / 100.0))]


# ---------------------------------------------------------------------------
# reconciliation + SLO evaluation (pure, unit-testable)
# ---------------------------------------------------------------------------

def reconcile_ledger(storage, ledger: _Ledger, app_ids: dict,
                     env: dict) -> dict:
    """The exactly-once census: replay leftover WAL segments (enqueue
    acks deferred by the drain), then count every ledger marker in the
    merged shards. Ack semantics: every ACKED marker must appear
    exactly once; ambiguous sends (conn errors) may appear 0 or 1
    times; NOTHING may appear twice."""
    from ..data.api import ingest_wal

    wal_summary = None
    cfg = ingest_wal.WalConfig(
        enabled=env.get("PIO_WAL") == "1",
        fsync=env.get("PIO_WAL_FSYNC", "group"),
        dir=env.get("PIO_WAL_DIR") or None)
    if cfg.enabled:
        try:
            wal_summary = ingest_wal.recover(storage, cfg)
        except ingest_wal.WalLockedError:
            wal_summary = {"error": "wal dir still live"}
    counts: dict = {}
    le = storage.get_l_events()
    for app, app_id in app_ids.items():
        for ev in le.find(app_id):
            marker = None
            if ev.properties is not None:
                marker = ev.properties.get_or_else("marker", None)
            if marker:
                counts[(app, marker)] = counts.get((app, marker), 0) + 1
    with ledger.lock:
        acked = list(ledger.acked)
        unacked = list(ledger.unacked)
    lost = [(app, mk) for app, mk, _id, _m in acked
            if counts.get((app, mk), 0) == 0]
    dup = [(app, mk, n) for (app, mk), n in counts.items() if n > 1]
    ambiguous_landed = sum(1 for app, mk, _why in unacked
                           if counts.get((app, mk), 0) > 0)
    return {
        "ackedEvents": len(acked),
        "storeMarkers": len(counts),
        "lostAcked": lost[:20],
        "lostAckedCount": len(lost),
        "duplicated": dup[:20],
        "duplicatedCount": len(dup),
        "ambiguousSends": len(unacked),
        "ambiguousLanded": ambiguous_landed,
        "walReplay": wal_summary,
        "perMarker": counts,
    }


def evaluate_slos(plan: SoakPlan, ledger: _Ledger, samples: _Samples,
                  reconciliation: dict, freshness: dict, drain: dict,
                  supervisor_doc: Optional[dict],
                  fault_log: list) -> tuple:
    """Scorecard SLO rows + per-fault evidence rows. Pure: everything
    it reads arrived as data, so seeded-violation fixtures unit-test
    every red path."""
    cfg = plan.cfg
    slos: list = []

    def slo(name: str, ok: bool, value, bound, detail: str = ""):
        slos.append({"name": name, "ok": bool(ok), "value": value,
                     "bound": bound, "detail": detail})

    lost = reconciliation["lostAckedCount"]
    dups = reconciliation["duplicatedCount"]
    slo("acked-event-loss", lost == 0 and dups == 0,
        {"lost": lost, "duplicated": dups}, 0,
        f"{reconciliation['ackedEvents']} acked events reconciled "
        "against merged shards + WAL replay")

    with ledger.lock:
        ingest_codes = dict(ledger.ingest_codes)
        query_codes = dict(ledger.query_codes)
        latencies = list(ledger.latencies)
        conn_errors = (ledger.ingest_conn_errors
                       + ledger.query_conn_errors)
        violations = list(ledger.violations)
    bad_ingest = {c: n for c, n in ingest_codes.items()
                  if c not in (201, 503)}
    bad_query = {c: n for c, n in query_codes.items()
                 if c not in (200, 503, 504)}
    slo("http-codes", not bad_ingest and not bad_query,
        {"ingest": bad_ingest, "query": bad_query},
        "ingest {201,503} / query {200,503,504}",
        f"ingest codes {ingest_codes}, query codes {query_codes}"
        + ("".join(f"; [{v['atS']}s] {v['table']} {v['code']}: "
                   f"{v['body']}" for v in violations)))

    p99_ms = _pct(latencies, 99) * 1000
    slo("query-p99", bool(latencies) and p99_ms <= cfg.p99_ms,
        round(p99_ms, 1), cfg.p99_ms,
        f"{len(latencies)} accepted queries")

    # rollback-within-window: every poison action needs its OWN
    # rollback observation after it, within the bound (one observation
    # cannot satisfy two poisons — keys are consumed)
    poisons = sorted((f for f in fault_log
                      if f["name"] in ("poison_foldin", "poison_retrain",
                                       "poison_quality")
                      and f.get("ok")),
                     key=lambda f: f.get("firedAtS", 0.0))
    with samples.lock:
        rollbacks = sorted(samples.rollback_seen)
    consumed: set = set()
    rb_rows = []
    ok_rb = True
    for f in poisons:
        fired = float(f.get("firedAtS", 0.0))
        matched = None
        for t_off, key, detail in rollbacks:
            if key in consumed or t_off < fired - 1.0:
                continue
            delta = t_off - fired
            if delta <= cfg.rollback_deadline_s:
                consumed.add(key)
                matched = {"key": key, "detail": detail,
                           "afterS": round(delta, 1)}
            break
        rb_rows.append({"fault": f["name"], "firedAtS": fired,
                        "observed": matched})
        if matched is None:
            ok_rb = False
    slo("rollback-window", ok_rb, rb_rows,
        f"<= {cfg.rollback_deadline_s}s after each poisoned publish",
        f"{len(rollbacks)} rollback observation(s): "
        + "; ".join(f"{k} @{t:.1f}s ({d})" for t, k, d in rollbacks))

    bound_s = cfg.freshness_factor * cfg.foldin_ms / 1000.0
    lag = freshness.get("finalLagS")
    slo("foldin-freshness", lag is not None and lag <= bound_s,
        lag, round(bound_s, 2),
        f"{samples.foldin_publishes} increment(s) published; settled "
        "lag after quiesce")

    budget = plan.conn_budget
    slo("conn-errors", conn_errors <= budget, conn_errors, budget,
        "connection-level drops across both floods (kill-window TCP "
        "reality; every HTTP response is already covered above)")

    slo("clean-drain",
        all(rc == 0 for rc in drain.values()) and len(drain) == 2,
        drain, 0, "SIGTERM drain exit codes (engine, eventserver)")

    # -- elastic topology: the fleet sized itself under the ramp -----------
    # two rows, one per direction of the load step. Graded purely from
    # the scraped /healthz fleet-size series, so seeded fixtures
    # unit-test both red paths (never grew / never came back down).
    if cfg.elastic and plan.ramp:
        up_at = float(plan.ramp["upAtS"])
        down_at = float(plan.ramp["downAtS"])
        floor = int(plan.ramp["min"])
        with samples.lock:
            sizes = list(samples.fleet_size)
            scale_events = sum(
                v for k, v in samples.metric_max.items()
                if k.startswith("pio_fleet_scale_events_total"))
        grew = [t for t, _active, ready, _target in sizes
                if t >= up_at and ready > floor]
        up_delta = round(grew[0] - up_at, 1) if grew else None
        slo("scale-up-within-bound",
            up_delta is not None and up_delta <= cfg.scale_up_bound_s,
            up_delta, cfg.scale_up_bound_s,
            f"{len(sizes)} fleet-size sample(s); first >{floor}-ready "
            f"observation "
            + (f"{up_delta}s after the step" if grew
               else "never seen after the step")
            + f"; scale events {scale_events:.0f}")
        shrunk = [t for t, active, _ready, _target in sizes
                  if t >= down_at and active <= floor]
        down_delta = round(shrunk[0] - down_at, 1) if shrunk else None
        slo("drain-on-quiet",
            down_delta is not None
            and down_delta <= cfg.scale_down_bound_s,
            down_delta, cfg.scale_down_bound_s,
            f"first back-at-floor ({floor}) observation "
            + (f"{down_delta}s after the step-down" if shrunk
               else "never seen after the step-down")
            + " — draining replicas finish in-flight work "
              "(non-{200,503,504} reds http-codes)")

    # -- per-fault evidence ------------------------------------------------
    with samples.lock:
        metric_max = dict(samples.metric_max)
        restarts = dict(samples.restarts)
    sup_restarts = {}
    if supervisor_doc:
        for w in supervisor_doc.get("workers", []):
            sup_restarts[f"worker:{w.get('worker')}"] = \
                int(w.get("restarts") or 0)

    def metric_at_least(prefix: str, n: float = 1) -> bool:
        return any(v >= n for k, v in metric_max.items()
                   if k.startswith(prefix))

    # -- cache freshness: rollbacks must not leave stale results -----------
    # Two legs: (a) the armed served-result cache saw real traffic —
    # an armed cache that never counted a hit or miss is a dead cache
    # nobody exercised; (b) every rollback observation is covered by
    # at least one cache invalidation EVENT apiece — the flush the
    # swap/rollback path owes the cache, so a kill/poison fault cannot
    # keep serving the rolled-back model's cached answers.
    def metric_total(family: str) -> float:
        return sum(v for k, v in metric_max.items()
                   if k == family or k.startswith(family + "{"))

    with samples.lock:
        qc = dict(samples.query_cache)
    hits = max(metric_total("pio_query_cache_hits_total"),
               float(qc.get("hits", 0)))
    misses = max(metric_total("pio_query_cache_misses_total"),
                 float(qc.get("misses", 0)))
    inv = max(metric_total("pio_query_cache_invalidations_total"),
              float(qc.get("invalidations", 0)))
    cache_armed = cfg.query_cache_size > 0
    ok_cache = (not cache_armed) or (
        hits + misses >= 1 and inv >= len(rollbacks))
    slo("cache-freshness", ok_cache,
        {"hits": hits, "misses": misses, "invalidations": inv,
         "rollbacks": len(rollbacks)},
        plan.slos.get("cache-freshness"),
        (f"{len(rollbacks)} rollback observation(s) vs {inv:.0f} cache"
         f" invalidation event(s), {hits + misses:.0f} lookups"
         if cache_armed else "cache disabled (query_cache_size=0)"))

    # -- tenant isolation: per-tenant availability + LRU churn -------------
    # One row per app, graded on that app's OWN evidence alone: a row
    # reds only when ITS tenant was offered traffic and never answered
    # a 200, or answered outside the contract — a hot tenant burning
    # its admission budget (503 shed) can never red a cold neighbor.
    # The mux must also have actually churned: with the resident bound
    # below the app count, zero evictions means the LRU was never
    # exercised and "N apps through one process" was not proven.
    if cfg.tenant_apps:
        with ledger.lock:
            tcodes = {a: dict(c)
                      for a, c in ledger.tenant_codes.items()}
        with samples.lock:
            tsnap = dict(samples.tenants)
        bound = _tenant_resident(cfg)
        rows = []
        ok_t = True
        for app in plan.app_names:
            codes = tcodes.get(app, {})
            offered = sum(codes.values())
            accepted = codes.get(200, 0)
            bad = {c: n for c, n in codes.items()
                   if c not in (200, 503, 504)}
            row_ok = (offered == 0) or (accepted >= 1 and not bad)
            rows.append({"app": app, "ok": row_ok, "offered": offered,
                         "accepted": accepted,
                         "shed": codes.get(503, 0),
                         "timeout": codes.get(504, 0), "bad": bad})
            ok_t = ok_t and row_ok
        unoffered = [r["app"] for r in rows if r["offered"] == 0]
        # the query loops' opening sweep visits every app, so an
        # unoffered tenant means the sweep never ran — red
        ok_t = ok_t and not unoffered
        evictions = tsnap.get("evictions")
        churn_ok = (len(plan.app_names) <= bound
                    or (evictions or 0) >= 1)
        slo("tenant-isolation", ok_t and churn_ok,
            {"perTenant": rows, "evictions": evictions,
             "resident": tsnap.get("resident"),
             "maxResident": tsnap.get("maxResident"),
             "coldLoads": tsnap.get("coldLoads")},
            plan.slos.get("tenant-isolation"),
            f"{len(rows)} tenant row(s), "
            f"{sum(r['accepted'] for r in rows)} accepted, "
            f"{sum(r['shed'] for r in rows)} shed; "
            + (f"{evictions} eviction(s), {tsnap.get('resident')}/"
               f"{tsnap.get('maxResident')} resident"
               if tsnap else "no tenants snapshot scraped")
            + (f"; never offered: {unoffered}" if unoffered else ""))

    fired_by_name = {f["name"]: f for f in fault_log}
    fault_rows = []
    for f in plan.faults:
        ev: dict = {"name": f.name, "kind": f.kind, "atS": f.at_s,
                    "target": f.target, "point": f.point}
        if f.kind in ("event", "train"):
            entry = fired_by_name.get(f.name)
            ev["fired"] = bool(entry and entry.get("ok"))
        else:
            ev["fired"] = True      # armed in the env; evidence decides
        if f.name == "enospc_shed":
            ev["evidence"] = metric_at_least(
                "pio_ingest_append_errors_total")
            ev["detail"] = "pio_ingest_append_errors_total >= 1"
        elif f.name in ("worker_kill", "compact_crash"):
            w = f.target or ""
            ev["evidence"] = sup_restarts.get(w, 0) >= 1
            ev["detail"] = f"supervisor.json {w} restarts " \
                           f"{sup_restarts.get(w, 0)}"
        elif f.name == "replica_kill":
            ev["evidence"] = restarts.get(f.target or "", 0) >= 1
            ev["detail"] = f"front /healthz {f.target} restarts " \
                           f"{restarts.get(f.target or '', 0)}"
        elif f.name == "poison_foldin":
            ev["evidence"] = metric_at_least("pio_foldin_rollbacks_total")
            ev["detail"] = "pio_foldin_rollbacks_total >= 1"
        elif f.name == "poison_retrain":
            ev["evidence"] = (
                metric_at_least("pio_fleet_rollbacks_total")
                or metric_at_least(
                    'pio_engine_rollbacks_total{reason="error-rate"}'))
            ev["detail"] = "fleet/engine rollback counter >= 1"
        elif f.name == "poison_quality":
            ev["evidence"] = (
                metric_at_least("pio_engine_quality_breaches_total")
                or metric_at_least(
                    'pio_engine_rollbacks_total{reason="quality"}'))
            ev["detail"] = "quality breach / quality-reason rollback " \
                           "counter >= 1"
        elif f.name == "good_retrain":
            entry = fired_by_name.get("good_retrain")
            with samples.lock:
                served_iids = {i for _t, i in samples.served}
            rolled_out = bool(entry and entry.get("instance")
                              in served_iids)
            ev["evidence"] = bool(entry and entry.get("ok")
                                  and rolled_out)
            ev["detail"] = ("retrain completed and its instance was "
                            "observed serving (staged rollout under "
                            "live fire)" if rolled_out else
                            "retrain completed but its instance was "
                            "never observed serving")
        fault_rows.append(ev)

    # -- quality SLO: the scorer graded relevance, not just uptime ---------
    # two legs: (a) the shadow scorer actually sampled live traffic
    # (armed but never sampling = a dead scorer grading nothing), and
    # (b) every fired quality poison has a rollback observation whose
    # pin reason is EXPLICITLY `quality` within the window — an
    # error-rate pin does not count, the poison never errors
    q_poisons = [f for f in poisons if f["name"] == "poison_quality"]
    q_consumed: set = set()
    q_rows = []
    ok_q = True
    for f in q_poisons:
        fired = float(f.get("firedAtS", 0.0))
        matched = None
        for t_off, key, detail in rollbacks:
            if key in q_consumed or t_off < fired - 1.0 \
                    or "quality" not in detail:
                continue
            delta = t_off - fired
            if delta <= cfg.rollback_deadline_s:
                q_consumed.add(key)
                matched = {"key": key, "detail": detail,
                           "afterS": round(delta, 1)}
            break
        q_rows.append({"fault": f["name"], "firedAtS": fired,
                       "observed": matched})
        if matched is None:
            ok_q = False
    armed = cfg.quality_sample > 0
    scorer_live = (not armed) or metric_at_least(
        "pio_engine_quality_samples_total")
    slo("quality-regression", ok_q and scorer_live,
        {"sampled": scorer_live, "rollbacks": q_rows},
        plan.slos.get("quality-regression"),
        f"{len(q_poisons)} quality poison(s) fired; scorer "
        + ("sampled live traffic" if scorer_live else "NEVER sampled"))

    missing = [r["name"] for r in fault_rows
               if r["fired"] and not r.get("evidence", True)]
    slo("fault-evidence", not missing, missing, "[]",
        "every injected fault left its telemetry/supervision trace")
    return slos, fault_rows


# ---------------------------------------------------------------------------
# scorecard persistence
# ---------------------------------------------------------------------------

def write_scorecard(scorecard: dict, out_path: str,
                    baseline_key: Optional[str] = None) -> None:
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(scorecard, f, indent=2, default=str)
        f.write("\n")
    os.replace(tmp, out_path)
    if baseline_key:
        base = os.path.join(os.path.dirname(os.path.abspath(out_path)),
                            "BASELINE.json")
        try:
            with open(base) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        row = {
            "verdict": scorecard["verdict"],
            "seed": scorecard["seed"],
            "wallS": scorecard["wallS"],
            "topology": scorecard["topology"],
            "faultsInjected": sum(
                1 for f in scorecard["faults"] if f.get("fired")),
            "slos": {s["name"]: s["ok"] for s in scorecard["slos"]},
            "traffic": scorecard["traffic"],
            "hostLoopMops": scorecard["host"]["loopMops"],
            "note": scorecard["host"]["note"],
        }
        doc.setdefault("published", {})[
            f"measured_soak_{baseline_key}"] = row
        tmp = base + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, base)


def read_scorecard(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def run_soak(plan: SoakPlan,
             progress: Callable[[str], None] = lambda s: None) -> dict:
    """Run one planned soak end to end; returns the scorecard (also
    persisted to ``cfg.out_path`` / BASELINE when configured)."""
    cfg = plan.cfg
    runner = SoakRunner(plan)
    progress(plan.describe())
    try:
        scorecard = runner.run()
    finally:
        runner.kill_all()
        if runner._storage is not None:
            try:
                runner._storage.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass
        if not cfg.keep_workdir:
            shutil.rmtree(cfg.workdir, ignore_errors=True)
    out_path = cfg.out_path or os.path.join(os.getcwd(), "SOAK.json")
    write_scorecard(scorecard, out_path, cfg.baseline_key)
    progress(f"scorecard → {out_path} ({scorecard['verdict']})")
    return scorecard
