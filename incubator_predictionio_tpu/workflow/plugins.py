"""Engine/Event server plugin interfaces.

Reference: core/.../workflow/EngineServerPlugin.scala (outputblocker /
outputsniffer hooks discovered via ServiceLoader) and
data/.../data/api/EventServerPlugin.scala. Python discovery: explicit
registration or entry-point style dotted paths in env var
PIO_ENGINE_SERVER_PLUGINS (comma separated).
"""

from __future__ import annotations

import importlib
import logging
from typing import Any, Optional

from ..common import envknobs

log = logging.getLogger("pio.plugins")


class EngineServerPlugin:
    """Hooks around the query path. ``process`` may transform the result
    (outputblocker role); ``sniff`` observes (outputsniffer role)."""

    name: str = "plugin"

    def start(self, context: "EngineServerPluginContext") -> None:
        pass

    def before_query(self, query: Any) -> Any:
        return query

    def process(self, query: Any, result: Any) -> Any:
        return result


class EventServerPlugin:
    name: str = "plugin"

    def on_event(self, event_json: dict) -> None:
        pass


class EventServerPluginContext:
    """Reference: EventServerPluginContext — ServiceLoader-discovered
    plugins observing ingested events. Python discovery: explicit list or
    dotted paths in PIO_EVENT_SERVER_PLUGINS (comma separated)."""

    def __init__(self, plugins: Optional[list[EventServerPlugin]] = None):
        self.plugins = list(plugins or [])
        for dotted in filter(None, envknobs.env_str(
                "PIO_EVENT_SERVER_PLUGINS", "", lower=False).split(",")):
            try:
                module, _, cls = dotted.strip().rpartition(".")
                self.plugins.append(getattr(importlib.import_module(module), cls)())
            except Exception:  # pragma: no cover - bad env entry
                log.exception("failed to load event server plugin %s", dotted)

    def plugin_names(self) -> list[str]:
        return [p.name for p in self.plugins]

    def on_event(self, event_json: dict) -> None:
        for p in self.plugins:
            try:
                p.on_event(event_json)
            except Exception:  # plugins must never break ingestion
                log.exception("event server plugin %s failed", p.name)


class EngineServerPluginContext:
    def __init__(self, plugins: Optional[list[EngineServerPlugin]] = None):
        self.plugins = list(plugins or [])
        for dotted in filter(None, envknobs.env_str(
                "PIO_ENGINE_SERVER_PLUGINS", "", lower=False).split(",")):
            try:
                module, _, cls = dotted.strip().rpartition(".")
                plugin = getattr(importlib.import_module(module), cls)()
                self.plugins.append(plugin)
            except Exception:  # pragma: no cover - bad env entry
                log.exception("failed to load plugin %s", dotted)
        for p in self.plugins:
            p.start(self)

    def plugin_names(self) -> list[str]:
        return [p.name for p in self.plugins]

    def before_query(self, query: Any) -> Any:
        for p in self.plugins:
            query = p.before_query(query)
        return query

    def after_query(self, query: Any, result: Any) -> Any:
        for p in self.plugins:
            result = p.process(query, result)
        return result
