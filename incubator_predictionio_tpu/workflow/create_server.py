"""Engine (deploy) server — serves a trained engine on :8000.

Reference: core/.../workflow/CreateServer.scala: MasterActor supervises a
ServerActor; POST /queries.json is the hot path; GET / is the status page;
/reload hot-swaps the latest engine instance; /stop shuts down; plugins
observe query/result pairs; optional feedback loop self-logs prediction
events.

TPU-native: the deployment holds device-resident models with warmed-up
compiled executables (ALSModel.warm_up), so the per-query Python work is
JSON parse → host gather → one device dispatch → one host fetch.
"""

from __future__ import annotations

import asyncio
import datetime as _dt
import hmac
import json
import logging
import threading
from typing import Any, Optional

import time as _time

from aiohttp import web

from ..common import telemetry
from ..controller.engine import Engine
from ..data.storage.datamap import DataMap
from ..data.storage.event import Event
from ..data.storage.registry import Storage
from .context import WorkflowContext
from .core_workflow import load_deployment
from .plugins import EngineServerPluginContext

log = logging.getLogger("pio.engineserver")


class EngineServer:
    def __init__(
        self,
        engine: Engine,
        engine_factory_name: str = "",
        engine_variant: str = "default",
        instance_id: Optional[str] = None,
        storage: Optional[Storage] = None,
        feedback: bool = False,
        feedback_app_name: Optional[str] = None,
        plugins: Optional[EngineServerPluginContext] = None,
        batch_window_ms: float = 0.0,
        max_batch: int = 64,
    ):
        self.engine = engine
        self.engine_factory_name = engine_factory_name
        self.engine_variant = engine_variant
        self.requested_instance_id = instance_id
        self.storage = storage or Storage.instance()
        self.feedback = feedback
        self.feedback_app_name = feedback_app_name
        self.plugins = plugins or EngineServerPluginContext()
        # Micro-batching window (0 = off): queries arriving within
        # batch_window_ms are coalesced into ONE vectorized
        # Deployment.batch_query dispatch. At high QPS the per-query
        # path serializes one device dispatch per request; batching
        # trades ≤ window ms of added latency for an order of magnitude
        # in throughput (SURVEY.md §2.9 serving-concurrency row / §7
        # hard part 1 "may need batching window at high QPS").
        self.batch_window_ms = float(batch_window_ms)
        # Cap: ops.topk pads pow2 only up to 256 (larger batches are the
        # bulk eval/batchpredict regime where padding wastes matmul), so
        # windows beyond that would compile per exact batch size.
        self.max_batch = min(int(max_batch), 256)
        self._batch_queue = None
        self._batch_task = None
        self.start_time = _dt.datetime.now(_dt.timezone.utc)
        self._lock = threading.Lock()
        self._query_count = 0
        # Probe marker secret: synthetic startup-probe traffic is
        # excluded from queryCount/feedback, so the marker must not be
        # spoofable — an external client sending a bare "X-Pio-Probe: 1"
        # would silently bypass the accounting. Per-process random token,
        # never exposed via any endpoint; only probe_and_record (same
        # process) knows it.
        import secrets

        self._probe_token = secrets.token_hex(16)
        # degraded mode: serving continues on the last-good model after a
        # failed reload / feedback outage; /status and /readyz surface it
        self._degraded_reason: Optional[str] = None
        self._dropped_feedback = 0
        # per-algorithm warm-up compile accounting (instance families,
        # exported via the registry collector below; gauges because a
        # reload re-measures the new instance's compiles from scratch —
        # _load rebuilds them so a reload to a different variant drops
        # the dead instance's algorithm labels)
        self._m_compile_count, self._m_compile_seconds = \
            self._new_compile_families()
        telemetry.registry().register_collector(
            "engineserver", self._collect_metrics)
        self.deployment = None
        self.instance = None
        self._load(instance_id)

        self.app = web.Application(
            middlewares=[telemetry.trace_middleware()])
        self.app.add_routes(
            [
                web.get("/", self.handle_status),
                web.get("/metrics", self.handle_metrics),
                web.get("/healthz", self.handle_healthz),
                web.get("/readyz", self.handle_readyz),
                web.post("/queries.json", self.handle_query),
                web.get("/reload", self.handle_reload),
                web.post("/reload", self.handle_reload),
                web.get("/stop", self.handle_stop),
                web.post("/stop", self.handle_stop),
                web.get("/plugins.json", self.handle_plugins),
            ]
        )
        if self.batch_window_ms > 0:
            self.app.on_startup.append(self._start_batcher)
            self.app.on_cleanup.append(self._stop_batcher)

    @staticmethod
    def _new_compile_families():
        return (telemetry.GaugeFamily(
                    "pio_engine_compile_count",
                    "Warm-up compilations performed for the live engine "
                    "instance, per algorithm", ("algorithm",)),
                telemetry.GaugeFamily(
                    "pio_engine_compile_seconds",
                    "Warm-up compilation wall seconds for the live engine "
                    "instance, per algorithm", ("algorithm",)))

    # -- lifecycle --------------------------------------------------------
    def _load(self, instance_id: Optional[str]) -> None:
        ctx = WorkflowContext(storage=self.storage)
        deployment, instance, _ = load_deployment(
            self.engine,
            instance_id,
            ctx,
            engine_factory_name=self.engine_factory_name,
            engine_variant=self.engine_variant,
        )
        # Fresh compile families for this instance: the collector reads
        # the attributes live, so swapping them drops labels that only
        # existed on the previous variant (nothing merges stale rows)
        m_count, m_seconds = self._new_compile_families()
        # Warm up every model that supports it (compile + device
        # placement); wall time per algorithm feeds the compile gauges —
        # on a cold deploy this is almost entirely XLA compilation, the
        # number an operator needs when a reload suddenly takes 30 s.
        for (algo_name, _algo), model in zip(deployment.algo_list,
                                             deployment.models):
            warm = getattr(model, "warm_up", None)
            if callable(warm):
                label = algo_name or type(model).__name__
                t0 = _time.perf_counter()
                try:
                    warm()
                except Exception:  # pragma: no cover - warmup best-effort
                    log.exception("model warm-up failed")
                else:
                    m_count.labels(label).set(1)
                    m_seconds.labels(label).set(
                        _time.perf_counter() - t0)
        self._m_compile_count, self._m_compile_seconds = m_count, m_seconds
        if self.batch_window_ms > 0:
            # Pre-compile every power-of-two batch shape the micro-batch
            # path can produce — a cold shape showed ~1.5s p99 through a
            # remote compile service, which would otherwise surface as
            # p99 spikes on live traffic. Models opt in by providing an
            # example_query() the batch path can execute.
            example = self._find_example_query(deployment)
            if example is not None:
                # up to the next pow2 ≥ max_batch: a live window of
                # max_batch queries pads to that shape
                top = 1 << max(self.max_batch - 1, 0).bit_length()
                b = 1
                n_shapes = 0
                t0 = _time.perf_counter()
                while b <= top:
                    try:
                        deployment.batch_query([dict(example)] * b)
                    except Exception:  # noqa: BLE001 - warmup best-effort
                        log.exception("batch warm-up failed at size %d", b)
                        break
                    n_shapes += 1
                    b *= 2
                self._m_compile_count.labels("batch").set(n_shapes)
                self._m_compile_seconds.labels("batch").set(
                    _time.perf_counter() - t0)
        with self._lock:
            self.deployment = deployment
            self.instance = instance
        log.info("deployed engine instance %s", instance.id)

    @staticmethod
    def _find_example_query(deployment) -> Optional[dict]:
        """First model offering a non-None example_query() (the warm-up /
        probe opt-in protocol)."""
        for model in deployment.models:
            ex = getattr(model, "example_query", None)
            if callable(ex):
                example = ex()
                if example is not None:
                    return example
        return None

    # -- handlers ---------------------------------------------------------
    async def handle_status(self, request: web.Request) -> web.Response:
        """Reference: CreateServer status page — JSON here."""
        with self._lock:
            instance = self.instance
        out = {
            "status": "alive",
            "engineInstanceId": instance.id if instance else None,
            "engineFactory": self.engine_factory_name,
            "engineVariant": self.engine_variant,
            "startTime": self.start_time.isoformat(),
            "queryCount": self._query_count,
            "plugins": self.plugins.plugin_names(),
            # resilience surface: serving on a stale model after a failed
            # reload (degraded=true), and feedback events dropped because
            # the event store write failed (counter — ops alert on growth)
            "degraded": self._degraded_reason is not None,
            "degradedReason": self._degraded_reason,
            "droppedFeedback": self._dropped_feedback,
        }
        # measured serving-latency decomposition, when a probe ran
        # (pio deploy --probe-latency persists it to the instance row)
        probe = (instance.runtime_conf.get("probe_latency")
                 if instance is not None else None)
        if probe:
            try:
                out["probeLatency"] = json.loads(probe)
            except (TypeError, json.JSONDecodeError):
                pass
        return web.json_response(out)

    def _collect_metrics(self):
        """Render-time families owned by THIS server instance."""
        qc = telemetry.GaugeFamily(
            "pio_engine_query_count",
            "Queries served by the live engine server (excludes "
            "synthetic startup probes)")
        qc.labels().set(self._query_count)
        dropped = telemetry.GaugeFamily(
            "pio_engine_dropped_feedback_total",
            "Feedback self-log events dropped by event-store failures")
        dropped.labels().set(self._dropped_feedback)
        return [self._m_compile_count, self._m_compile_seconds, qc,
                dropped]

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition: query stage histograms, compile
        gauges, storage transport + breaker families — the engine
        server's share of the process-wide registry."""
        return web.Response(text=telemetry.render_all(),
                            content_type="text/plain")

    async def handle_healthz(self, request: web.Request) -> web.Response:
        """Liveness: the process serves HTTP (mirrors the storage
        server's /health). Restart-worthy failures never answer at all."""
        return web.json_response({"status": "alive"})

    async def handle_readyz(self, request: web.Request) -> web.Response:
        """Readiness: a model is loaded AND no storage circuit breaker
        is open; not-ready answers 503 so load balancers rotate this
        replica out. The degraded flag (serving the last-good model
        after a failed reload) is deliberately NOT part of readiness —
        a degraded replica still answers queries correctly and draining
        it would trade a stale-but-valid model for no capacity; it is
        surfaced here and on /status as telemetry only."""
        with self._lock:
            loaded = self.deployment is not None
        open_breakers = [
            b["name"] for b in self._storage_breakers()
            if b.get("state") == "open"
        ]
        ready = loaded and not open_breakers
        out = {
            "ready": ready,
            "modelLoaded": loaded,
            "degraded": self._degraded_reason is not None,
            "openBreakers": open_breakers,
        }
        return web.json_response(out, status=200 if ready else 503)

    def _storage_breakers(self) -> list[dict]:
        try:
            return [b for states in
                    self.storage.breaker_states().values() for b in states]
        except Exception:  # noqa: BLE001 - readiness must never crash
            log.exception("breaker state collection failed")
            return []

    # -- micro-batching ---------------------------------------------------
    async def _start_batcher(self, app) -> None:
        self._batch_queue = asyncio.Queue()
        self._batch_task = asyncio.get_running_loop().create_task(
            self._batch_worker())

    async def _stop_batcher(self, app) -> None:
        # stop accepting, cancel the worker, and fail any stranded
        # queries cleanly instead of leaving their handlers awaiting
        # futures that will never resolve
        queue, self._batch_queue = self._batch_queue, None
        if self._batch_task is not None:
            self._batch_task.cancel()
            self._batch_task = None
        if queue is not None:
            while not queue.empty():
                _, fut = queue.get_nowait()
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("engine server shutting down"))

    async def _batch_worker(self) -> None:
        """Coalesce queued queries: wait for the first, gather more until
        the window closes (or max_batch), one vectorized dispatch. On
        cancellation (server shutdown) the IN-FLIGHT batch's futures are
        failed too — _stop_batcher only sees items still queued."""
        try:
            await self._batch_worker_loop()
        except asyncio.CancelledError:
            for _, fut in getattr(self, "_inflight_batch", []):
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("engine server shutting down"))
            raise

    async def _batch_worker_loop(self) -> None:
        loop = asyncio.get_running_loop()
        window = self.batch_window_ms / 1000.0
        while True:
            self._inflight_batch = []
            batch = self._inflight_batch
            batch.append(await self._batch_queue.get())
            deadline = loop.time() + window
            while len(batch) < self.max_batch:
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._batch_queue.get(), timeout))
                except asyncio.TimeoutError:
                    break
            with self._lock:
                deployment = self.deployment
            queries = [q for q, _ in batch]
            try:
                results = await asyncio.to_thread(
                    deployment.batch_query, queries)
            except Exception:  # noqa: BLE001
                # One bad query (e.g. missing field) must not poison its
                # batchmates: degrade to per-query processing so each
                # request gets ITS OWN result or error, exactly like the
                # unbatched path.
                def _one_by_one():
                    out = []
                    for q in queries:
                        try:
                            out.append((True, deployment.query(q)))
                        except Exception as qe:  # noqa: BLE001
                            out.append((False, qe))
                    return out

                for (_, fut), (ok, res) in zip(
                        batch, await asyncio.to_thread(_one_by_one)):
                    if fut.done():
                        continue
                    if ok:
                        fut.set_result(res)
                    else:
                        fut.set_exception(res)
                continue
            for (_, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)

    async def handle_query(self, request: web.Request) -> web.Response:
        try:
            query = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"message": "invalid JSON body"}, status=400)
        with self._lock:
            deployment = self.deployment
        if deployment is None:
            return web.json_response({"message": "no model deployed"}, status=503)
        try:
            query = self.plugins.before_query(query)
            if self._batch_queue is not None:
                fut = asyncio.get_running_loop().create_future()
                await self._batch_queue.put((query, fut))
                result = await fut
            else:
                result = await asyncio.to_thread(deployment.query, query)
            result = self.plugins.after_query(query, result)
        except KeyError as e:
            return web.json_response(
                {"message": f"missing query field {e.args[0]!r}"}, status=400
            )
        except Exception as e:  # noqa: BLE001 - surfaced as HTTP 500 w/ message
            log.exception("query failed")
            return web.json_response({"message": str(e)}, status=500)
        probe = request.headers.get("X-Pio-Probe")
        # bytes comparison: compare_digest raises TypeError on non-ASCII
        # str input, which a hostile header could use to 500 the request
        # AFTER the query already executed
        if probe and hmac.compare_digest(
                probe.encode("utf-8", "surrogateescape"),
                self._probe_token.encode()):
            # synthetic startup-probe traffic: excluded from queryCount
            # and the feedback self-log; REAL queries arriving during the
            # probe window are unaffected (the marker is per-request).
            # The marker only counts when it carries this process's
            # random token — external clients can't forge the bypass.
            return web.json_response(result)
        self._query_count += 1
        if self.feedback:
            # sync DAO write runs in the default executor, never on the
            # loop. The future must not be fire-and-forget: a failing
            # event store would otherwise drop feedback events with the
            # exception swallowed by the orphaned future — the
            # done-callback logs every failure and counts it into the
            # droppedFeedback counter on /status.
            fut = asyncio.get_running_loop().run_in_executor(
                None, self._log_feedback, query, result
            )
            fut.add_done_callback(self._feedback_done)
        return web.json_response(result)

    def _feedback_done(self, fut: "asyncio.Future") -> None:
        if fut.cancelled():
            self._dropped_feedback += 1
            return
        exc = fut.exception()
        if exc is not None:
            self._dropped_feedback += 1
            log.error("feedback logging failed (dropped=%d): %s",
                      self._dropped_feedback, exc)

    def _log_feedback(self, query: Any, result: Any) -> None:
        """Self-log the prediction as a "predict" event (reference:
        CreateServer feedback loop → event server). Raises on failure —
        the done-callback owns logging and the dropped counter."""
        app_name = self.feedback_app_name
        if not app_name:
            return
        app = self.storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            return
        self.storage.get_l_events().insert(
            Event(
                event="predict",
                entity_type="pio_pr",  # server-generated: prefix allowed internally
                entity_id=str(query.get("user", "")) if isinstance(query, dict) else "",
                properties=DataMap({"query": query, "result": result}),
            ),
            app.id,
        )

    # -- startup latency probe (reference: CreateServer hot path;
    # BASELINE.json north star #2 asks for a MEASURED full-path p50) ----
    def probe_and_record(self, base_url: str, n: int = 60) -> Optional[dict]:
        """Measure the full-path query latency decomposition against the
        LIVE server (real HTTP through loopback) and persist it to the
        EngineInstance row (runtime_conf["probe_latency"]). Components:
        http_full (wire-to-wire), predict (host gather + device dispatch
        + on-chip + download), bare device dispatch RTT (the tunnel/queue
        share), json parse. http − predict = server/HTTP overhead;
        predict − rtt ≈ on-chip + result transfer."""
        import http.client
        import ssl
        import time
        import urllib.parse

        with self._lock:
            deployment, instance = self.deployment, self.instance
        example = self._find_example_query(deployment)
        if example is None:
            log.warning(
                "probe-latency: no deployed model provides example_query(); "
                "skipping")
            return None
        body = json.dumps(example).encode()
        # Loopback self-probe: the server's own cert won't verify for
        # 127.0.0.1 (hostname-scoped / self-signed), and verification
        # adds nothing when we ARE the server.
        tls_ctx = None
        parsed = urllib.parse.urlsplit(base_url)
        if parsed.scheme == "https":
            tls_ctx = ssl.create_default_context()
            tls_ctx.check_hostname = False
            tls_ctx.verify_mode = ssl.CERT_NONE

        # ONE keep-alive connection reused across every sample: the p50
        # must measure steady-state request latency, not a per-request
        # TCP (+TLS) handshake — real serving clients hold persistent
        # connections, and the handshake share was the dominant term of
        # the old per-request-urlopen numbers at sub-ms predict times.
        conn_box: list = [None]

        def connect():
            if parsed.scheme == "https":
                return http.client.HTTPSConnection(
                    parsed.hostname, parsed.port, timeout=60,
                    context=tls_ctx)
            return http.client.HTTPConnection(
                parsed.hostname, parsed.port, timeout=60)

        def post():
            for attempt in (0, 1):
                if conn_box[0] is None:
                    conn_box[0] = connect()
                conn = conn_box[0]
                try:
                    conn.request(
                        "POST", "/queries.json", body=body,
                        headers={"Content-Type": "application/json",
                                 "X-Pio-Probe": self._probe_token})
                    conn.getresponse().read()
                    return
                except (http.client.HTTPException, OSError):
                    # server dropped the idle connection: reconnect and
                    # retry the sample once
                    conn.close()
                    conn_box[0] = None
                    if attempt:
                        raise

        def pct(a, p):
            a = sorted(a)
            return a[min(len(a) - 1, round(p / 100 * (len(a) - 1)))]

        for _ in range(5):  # warm the keep-alive connection + executables
            post()
        http_ms = []
        for _ in range(n):
            t0 = time.perf_counter()
            post()
            http_ms.append((time.perf_counter() - t0) * 1e3)
        if conn_box[0] is not None:
            conn_box[0].close()
        parse_ms, predict_ms = [], []
        for _ in range(n):
            t0 = time.perf_counter()
            q = json.loads(body)
            parse_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            deployment.query(q)
            predict_ms.append((time.perf_counter() - t0) * 1e3)
        rtt_ms = []
        try:
            import jax
            import numpy as _np

            noop = jax.jit(lambda v: v + 1)
            x = jax.device_put(_np.zeros(8, _np.float32))
            jax.device_get(noop(x))  # compile
            for _ in range(n):
                t0 = time.perf_counter()
                jax.device_get(noop(x))
                rtt_ms.append((time.perf_counter() - t0) * 1e3)
        except Exception:  # noqa: BLE001 - probe must not kill serving
            log.exception("probe-latency: device RTT probe failed")

        result = {
            "n": n,
            "attachment": _device_attachment(),
            "http_p50_ms": round(pct(http_ms, 50), 3),
            "http_p99_ms": round(pct(http_ms, 99), 3),
            "predict_p50_ms": round(pct(predict_ms, 50), 3),
            "predict_p99_ms": round(pct(predict_ms, 99), 3),
            "dispatch_rtt_p50_ms": round(pct(rtt_ms, 50), 3) if rtt_ms else None,
            "parse_p50_ms": round(pct(parse_ms, 50), 4),
        }
        result["overhead_p50_ms"] = round(
            max(result["http_p50_ms"] - result["predict_p50_ms"], 0.0), 3)
        if rtt_ms:
            result["onchip_plus_transfer_p50_ms"] = round(
                max(result["predict_p50_ms"] - result["dispatch_rtt_p50_ms"],
                    0.0), 3)
        print(f"[probe] full-path p50={result['http_p50_ms']}ms "
              f"p99={result['http_p99_ms']}ms over {n} queries "
              f"({result['attachment']})")
        print(f"[probe]   predict (gather+dispatch+on-chip+fetch) "
              f"p50={result['predict_p50_ms']}ms")
        if rtt_ms:
            print(f"[probe]   bare device dispatch RTT "
                  f"p50={result['dispatch_rtt_p50_ms']}ms → on-chip+transfer "
                  f"≈ {result['onchip_plus_transfer_p50_ms']}ms")
        print(f"[probe]   http+queue overhead p50="
              f"{result['overhead_p50_ms']}ms, json parse "
              f"p50={result['parse_p50_ms']}ms")
        try:
            import dataclasses as _dc

            instances = self.storage.get_meta_data_engine_instances()
            fresh = instances.get(instance.id) or instance
            updated = _dc.replace(
                fresh,
                runtime_conf={**fresh.runtime_conf,
                              "probe_latency": json.dumps(result)})
            instances.update(updated)
            with self._lock:
                # keep the live status page in sync with the stored row
                if self.instance is not None and self.instance.id == updated.id:
                    self.instance = updated
        except Exception:  # noqa: BLE001 - persistence is best-effort
            log.exception("probe-latency: persisting to instance row failed")
        return result

    async def handle_reload(self, request: web.Request) -> web.Response:
        """Hot-swap to the latest completed instance (reference: /reload →
        MasterActor ! ReloadServer). A failed reload NEVER takes down
        serving: the last-good model stays live and the server enters
        degraded mode (visible on /status and /readyz) until a reload
        succeeds."""
        try:
            await asyncio.to_thread(self._load, None)
        except Exception as e:  # noqa: BLE001
            self._degraded_reason = (
                f"reload failed at "
                f"{_dt.datetime.now(_dt.timezone.utc).isoformat()}: {e}; "
                "serving last-good model")
            log.exception("reload failed; continuing on last-good model")
            return web.json_response(
                {"message": str(e), "degraded": True,
                 "engineInstanceId":
                     self.instance.id if self.instance else None},
                status=500)
        self._degraded_reason = None
        return web.json_response(
            {"message": "Reloaded", "engineInstanceId": self.instance.id}
        )

    async def handle_stop(self, request: web.Request) -> web.Response:
        log.info("stop requested")
        asyncio.get_running_loop().call_later(0.1, request.app["stopper"])
        return web.json_response({"message": "Shutting down."})

    async def handle_plugins(self, request: web.Request) -> web.Response:
        return web.json_response({"plugins": self.plugins.plugin_names()})


def _device_attachment() -> str:
    """Human label for where the accelerator lives (probe output)."""
    try:
        import jax

        d = jax.devices()[0]
        return f"{d.platform}:{getattr(d, 'device_kind', '?')}"
    except Exception:  # noqa: BLE001
        return "unknown"


def run_engine_server(server: EngineServer, host: str = "0.0.0.0",
                      port: int = 8000, probe_latency: bool = False):
    """Blocking entry point (reference: CreateServer.main)."""
    loop = asyncio.new_event_loop()
    stop_event = asyncio.Event()
    server.app["stopper"] = stop_event.set

    async def main():
        from ..common import ssl_context_from_env

        tls = ssl_context_from_env()
        runner = web.AppRunner(server.app)
        await runner.setup()
        site = web.TCPSite(runner, host, port, ssl_context=tls)
        await site.start()
        log.info("Engine Server listening on %s:%d", host, port)
        if probe_latency:
            scheme = "https" if tls else "http"
            try:
                await asyncio.to_thread(
                    server.probe_and_record, f"{scheme}://127.0.0.1:{port}")
            except Exception:  # noqa: BLE001 - diagnostics must not kill serving
                log.exception("startup latency probe failed; serving anyway")
        await stop_event.wait()
        await runner.cleanup()

    loop.run_until_complete(main())
