"""Engine (deploy) server — serves a trained engine on :8000.

Reference: core/.../workflow/CreateServer.scala: MasterActor supervises a
ServerActor; POST /queries.json is the hot path; GET / is the status page;
/reload hot-swaps the latest engine instance; /stop shuts down; plugins
observe query/result pairs; optional feedback loop self-logs prediction
events.

TPU-native: the deployment holds device-resident models with warmed-up
compiled executables (ALSModel.warm_up), so the per-query Python work is
JSON parse → host gather → one device dispatch → one host fetch.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import contextvars
import copy
import datetime as _dt
import hmac
import json
import logging
import math
import os
import threading
from typing import Any, Optional

import time as _time

from aiohttp import web

from ..common import deadline, envknobs, faultinject, telemetry
from ..common.resilience import retry_after_jitter
from ..controller.engine import Engine
from ..data.storage.datamap import DataMap
from ..data.storage.event import Event
from ..data.storage.registry import Storage
from .context import WorkflowContext
from .core_workflow import load_deployment
from .plugins import EngineServerPluginContext

log = logging.getLogger("pio.engineserver")


def _env_int(name: str, default: int) -> int:
    """Tolerant integer knob: unset/unparsable degrades to the default
    (a typo'd env var must not crash a deploy). Float spellings like
    ``"1e3"`` are accepted. One shared implementation: common/envknobs."""
    return envknobs.env_int(name, default, float_ok=True)


# query-cache telemetry is process-wide monotonic (counters survive a
# server object being rebuilt in-process, like the fold-in counters)
_M_CACHE_HITS = telemetry.registry().counter(
    "pio_query_cache_hits_total",
    "Queries answered from the served-result cache without a model "
    "dispatch").labels()
_M_CACHE_MISSES = telemetry.registry().counter(
    "pio_query_cache_misses_total",
    "Cache-armed queries that had to run a model dispatch (entry "
    "absent, expired, or invalidated)").labels()
_M_CACHE_INVALIDATIONS = telemetry.registry().counter(
    "pio_query_cache_invalidations_total",
    "Query-cache invalidation events by trigger: foldin = targeted "
    "per-user eviction from an increment's freshness footprint; swap "
    "= full flush on any other model swap; rollback = full flush "
    "when a rollback restores the previous model", ("reason",))


class QueryResultCache:
    """Per-user served-result cache (``PIO_QUERY_CACHE_SIZE`` > 0 arms
    it). Keyed on (user, canonical query fingerprint, app): a
    byte-identical repeat of a query within the TTL is answered without
    touching the model — at a zipfian user mix the hot heads collapse
    onto cache hits and the sharded million-item dispatch only runs for
    the tail. The app component keeps tenants' entries disjoint
    (multi-tenant serving shares ONE cache across every resident app).

    Freshness contract (docs/serving.md "Million-item catalogs"):

    - a fold-in increment going live evicts exactly the users its
      freshness footprint names (the ``users`` list online.py writes
      into ``runtime_conf["foldin"]``) — a fold-in touching a user's
      rows MUST invalidate that user, and does;
    - any other swap (retrain, operator reload, an increment without
      an attributable footprint or of a different lineage) flushes
      everything;
    - a rollback flushes everything — the restored model must never
      answer with results the rolled-back model computed;
    - the TTL bounds staleness against serve-time event-log reads
      (e.g. the e-commerce seen-items filter) that no swap observes.

    Entries store a deep copy and hits return a deep copy: results
    flow through after_query plugins that may mutate them in place.
    Thread-safe (its own lock): lookups run on the event loop while
    swap invalidation arrives from reload worker threads."""

    def __init__(self, max_entries: int, ttl_s: float):
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        # key → (expires_monotonic, result); insertion order doubles
        # as LRU order (move_to_end on hit)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidated_entries = 0
        self.invalidations = 0
        # bumped by every invalidation: an in-flight dispatch that
        # started before a swap must not re-insert its (stale) result
        # after the invalidation ran — put() drops generation-mismatched
        # inserts, so "zero stale serves" holds without a lock spanning
        # the whole dispatch
        self.generation = 0

    @staticmethod
    def key_for(query, app: Optional[str] = None) -> tuple:
        """(user-or-None, canonical JSON fingerprint, app-or-None). The
        fingerprint is computed on the post-``before_query`` plugin
        form, so two spellings a plugin canonicalizes share one entry.
        The app component is the tenant-isolation dimension: without
        it, two apps' identical (user, query) pairs would SHARE an
        entry — tenant B served tenant A's cached result, and tenant
        A's fold-in invalidation leaving B's stale alias behind. The
        server passes its tenant's app on every lookup/insert; app=None
        (a bare single-tenant deploy, pre-multi-tenant callers) is its
        own namespace and never collides with a named app's."""
        user = query.get("user") if isinstance(query, dict) else None
        fp = json.dumps(query, sort_keys=True, separators=(",", ":"),
                        default=str)
        return (None if user is None else str(user), fp,
                None if app is None else str(app))

    def get(self, key: tuple):
        now = _time.monotonic()
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent[0] > now:
                self._entries.move_to_end(key)
                self.hits += 1
                _M_CACHE_HITS.inc()
                return copy.deepcopy(ent[1])
            if ent is not None:
                del self._entries[key]  # expired
            self.misses += 1
        _M_CACHE_MISSES.inc()
        return None

    def put(self, key: tuple, result, generation: Optional[int] = None
            ) -> None:
        entry = (_time.monotonic() + self.ttl_s, copy.deepcopy(result))
        with self._lock:
            if generation is not None and generation != self.generation:
                return  # an invalidation ran mid-dispatch: result stale
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def invalidate_users(self, users, app: Optional[str] = None) -> int:
        """Targeted eviction: drop every entry keyed to one of
        ``users``. Userless entries (similarity queries) survive — a
        fold-in re-solves only user rows against fixed item-side
        state, which userless queries score exclusively. With ``app``,
        only that tenant's entries are touched: tenant A's fold-in
        footprint naming user "u1" must not evict (or miss) app B's
        "u1", who is a different person under a different model."""
        users = {str(u) for u in users}
        app = None if app is None else str(app)
        with self._lock:
            doomed = [k for k in self._entries
                      if k[0] in users and (app is None or k[2] == app)]
            for k in doomed:
                del self._entries[k]
            self.invalidated_entries += len(doomed)
            self.invalidations += 1
            self.generation += 1
        _M_CACHE_INVALIDATIONS.labels("foldin").inc()
        return len(doomed)

    def flush_app(self, app: str, reason: str) -> int:
        """Drop every entry of ONE tenant (its rollback / unfootprinted
        swap); every other tenant's entries — and their hit rates —
        survive untouched."""
        app = str(app)
        with self._lock:
            doomed = [k for k in self._entries if k[2] == app]
            for k in doomed:
                del self._entries[k]
            self.invalidated_entries += len(doomed)
            self.invalidations += 1
            self.generation += 1
        _M_CACHE_INVALIDATIONS.labels(reason).inc()
        return len(doomed)

    def flush(self, reason: str) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self.invalidated_entries += n
            self.invalidations += 1
            self.generation += 1
        _M_CACHE_INVALIDATIONS.labels(reason).inc()
        return n

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "maxEntries": self.max_entries,
                "ttlMs": round(self.ttl_s * 1e3, 3),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "invalidatedEntries": self.invalidated_entries,
            }


class AdmissionShed(Exception):
    """The admission gate refused this query (queue full or server
    draining). Maps to HTTP 503 + jittered ``Retry-After`` — the query
    never started, so a retry elsewhere/later is safe and cheap."""

    def __init__(self, message: str, retry_after_base: float, reason: str):
        super().__init__(message)
        self.retry_after_base = retry_after_base
        self.reason = reason


class SwapValidationError(RuntimeError):
    """The validation gate refused to put a (re)loaded model live
    (nan_guard hit, warm-up failed, or the golden-query smoke predict
    raised). The last-good deployment keeps serving; the reload/refresh
    caller decides whether to pin the refused instance."""

    def __init__(self, instance_id: str, reason: str):
        super().__init__(
            f"engine instance {instance_id} failed swap validation: "
            f"{reason}")
        self.instance_id = instance_id
        self.reason = reason


class EngineServer:
    def __init__(
        self,
        engine: Engine,
        engine_factory_name: str = "",
        engine_variant: str = "default",
        instance_id: Optional[str] = None,
        storage: Optional[Storage] = None,
        feedback: bool = False,
        feedback_app_name: Optional[str] = None,
        plugins: Optional[EngineServerPluginContext] = None,
        batch_window_ms: float = 0.0,
        max_batch: int = 64,
        query_conc: Optional[int] = None,
        query_max_pending: Optional[int] = None,
        query_deadline_ms: Optional[float] = None,
        drain_deadline_ms: Optional[float] = None,
        swap_validate: Optional[bool] = None,
        swap_watch_ms: Optional[float] = None,
        swap_max_error_rate: Optional[float] = None,
        model_refresh_ms: Optional[float] = None,
        foldin_ms: Optional[float] = None,
        fleet_replica: Optional[int] = None,
        fleet_replicas: Optional[int] = None,
        fleet_sync_ms: Optional[float] = None,
        quality_sample: Optional[float] = None,
        query_cache_size: Optional[int] = None,
        query_cache_ttl_ms: Optional[float] = None,
        tenant_max_resident: Optional[int] = None,
        tenant_max_pending: Optional[int] = None,
    ):
        # start the PIO_FAULT_SPEC at-mode offset clock at "server
        # constructing", not "first query": soak timelines schedule
        # faults relative to process start (no-op when chaos is off)
        faultinject.arm()
        self.engine = engine
        self.engine_factory_name = engine_factory_name
        self.engine_variant = engine_variant
        self.requested_instance_id = instance_id
        self.storage = storage or Storage.instance()
        self.feedback = feedback
        self.feedback_app_name = feedback_app_name
        self.plugins = plugins or EngineServerPluginContext()
        # Micro-batching window (0 = off): queries arriving within
        # batch_window_ms are coalesced into ONE vectorized
        # Deployment.batch_query dispatch. At high QPS the per-query
        # path serializes one device dispatch per request; batching
        # trades ≤ window ms of added latency for an order of magnitude
        # in throughput (SURVEY.md §2.9 serving-concurrency row / §7
        # hard part 1 "may need batching window at high QPS").
        self.batch_window_ms = float(batch_window_ms)
        # Cap: ops.topk pads pow2 only up to 256 (larger batches are the
        # bulk eval/batchpredict regime where padding wastes matmul), so
        # windows beyond that would compile per exact batch size.
        self.max_batch = min(int(max_batch), 256)
        self._batch_queue = None
        self._batch_task = None
        self.start_time = _dt.datetime.now(_dt.timezone.utc)
        self._lock = threading.Lock()
        self._query_count = 0
        self._init_overload_state(query_conc, query_max_pending,
                                  query_deadline_ms, drain_deadline_ms,
                                  swap_validate, swap_watch_ms,
                                  swap_max_error_rate, model_refresh_ms,
                                  fleet_replica, fleet_replicas,
                                  fleet_sync_ms, foldin_ms,
                                  quality_sample,
                                  query_cache_size=query_cache_size,
                                  query_cache_ttl_ms=query_cache_ttl_ms,
                                  tenant_max_resident=tenant_max_resident,
                                  tenant_max_pending=tenant_max_pending)
        # Probe marker secret: synthetic startup-probe traffic is
        # excluded from queryCount/feedback, so the marker must not be
        # spoofable — an external client sending a bare "X-Pio-Probe: 1"
        # would silently bypass the accounting. Per-process random token,
        # never exposed via any endpoint; only probe_and_record (same
        # process) knows it.
        import secrets

        self._probe_token = secrets.token_hex(16)
        # degraded mode: serving continues on the last-good model after a
        # failed reload / feedback outage; /status and /readyz surface it
        self._degraded_reason: Optional[str] = None
        self._dropped_feedback = 0
        # per-algorithm warm-up compile accounting (instance families,
        # exported via the registry collector below; gauges because a
        # reload re-measures the new instance's compiles from scratch —
        # _load rebuilds them so a reload to a different variant drops
        # the dead instance's algorithm labels)
        self._m_compile_count, self._m_compile_seconds = \
            self._new_compile_families()
        telemetry.registry().register_collector(
            "engineserver", self._collect_metrics)
        self.deployment = None
        self.instance = None
        if self.fleet_mode and instance_id is None:
            self._fleet_bootstrap_load()
        else:
            self._load(instance_id)
        if self.tenant_max_resident > 0:
            from . import multitenant

            self._tenants = multitenant.TenantMux(
                self, self.tenant_max_resident, self.tenant_max_pending)

        self.app = web.Application(
            middlewares=[telemetry.trace_middleware()])
        self.app.add_routes(
            [
                web.get("/", self.handle_status),
                web.get("/status", self.handle_status),
                web.get("/metrics", self.handle_metrics),
                web.get("/healthz", self.handle_healthz),
                web.get("/readyz", self.handle_readyz),
                web.post("/queries.json", self.handle_query),
                web.get("/reload", self.handle_reload),
                web.post("/reload", self.handle_reload),
                web.get("/rollback", self.handle_rollback),
                web.post("/rollback", self.handle_rollback),
                web.get("/stop", self.handle_stop),
                web.post("/stop", self.handle_stop),
                web.get("/plugins.json", self.handle_plugins),
            ]
        )
        if self.batch_window_ms > 0:
            self.app.on_startup.append(self._start_batcher)
            self.app.on_cleanup.append(self._stop_batcher)
        self.app.on_startup.append(self._start_refresher)
        self.app.on_cleanup.append(self._stop_refresher)
        self.app.on_startup.append(self._start_foldin)
        self.app.on_cleanup.append(self._stop_foldin)
        self.app.on_startup.append(self._start_quality)
        self.app.on_cleanup.append(self._stop_quality)
        self.app.on_startup.append(self._start_fleet)
        self.app.on_cleanup.append(self._stop_fleet)
        self.app.on_startup.append(self._start_heartbeat)
        self.app.on_cleanup.append(self._stop_heartbeat)
        self.app.on_cleanup.append(self._shutdown_executor)

    def _init_overload_state(self, query_conc=None, query_max_pending=None,
                             query_deadline_ms=None,
                             drain_deadline_ms=None, swap_validate=None,
                             swap_watch_ms=None, swap_max_error_rate=None,
                             model_refresh_ms=None, fleet_replica=None,
                             fleet_replicas=None,
                             fleet_sync_ms=None, foldin_ms=None,
                             quality_sample=None, query_cache_size=None,
                             query_cache_ttl_ms=None,
                             tenant_max_resident=None,
                             tenant_max_pending=None) -> None:
        """Admission control: the query path gets a DEDICATED bounded
        executor (query_conc workers) plus a bounded waiting budget
        (query_max_pending); offered load beyond conc+pending is shed
        with 503 + jittered Retry-After instead of queueing without
        limit in the default executor. Args override the PIO_QUERY_*
        env knobs; see docs/operations.md "Serving: overload safety".
        (Separate from __init__ so harness code building a skeleton
        server via __new__ — tools/big_catalog_demo.py — can arm the
        gate without the storage-backed load.)"""
        self.query_conc = max(1, int(
            query_conc if query_conc is not None
            else _env_int("PIO_QUERY_CONC",
                          min(32, (os.cpu_count() or 4) + 4))))
        self.query_max_pending = max(0, int(
            query_max_pending if query_max_pending is not None
            else _env_int("PIO_QUERY_MAX_PENDING", 128)))
        # Deadline budget per query (0 = unbounded); the X-Pio-Deadline-Ms
        # request header overrides per request. Exceeded → 504.
        self.query_deadline_ms = float(
            query_deadline_ms if query_deadline_ms is not None
            else _env_int("PIO_QUERY_DEADLINE_MS", 30_000))
        # Ceiling on what the client header may loosen the budget TO
        # (0 = uncapped). Without it a client could grant itself an
        # effectively unbounded budget and park unkillable workers on a
        # hung model — defeating the operator's overload protection.
        self.query_deadline_max_ms = max(0.0, float(
            _env_int("PIO_QUERY_DEADLINE_MAX_MS", 600_000)))
        # Graceful-drain budget for SIGTERM / /stop.
        self.drain_deadline_ms = max(0.0, float(
            drain_deadline_ms if drain_deadline_ms is not None
            else _env_int("PIO_DRAIN_DEADLINE_MS", 10_000)))
        self._query_executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.query_conc, thread_name_prefix="pio-query")
        self._adm_lock = threading.Lock()   # pending count is touched
        self._adm_pending = 0               # from loop AND worker threads
        self._adm_peak = 0
        self._shed_count = 0
        self._deadline_count = 0
        self._orphaned = 0
        self._draining = False
        self._drain_stragglers = 0
        self._reload_lock = asyncio.Lock()
        self._reload_conflicts = 0
        # -- model lifecycle (docs/operations.md "Model lifecycle") ----
        # Validation gate: before any (re)loaded model goes live, run
        # nan_guard over its arrays, require warm-up success, and smoke-
        # predict the golden query. Gate failure → stay on last-good.
        self.swap_validate = (
            bool(swap_validate) if swap_validate is not None
            else envknobs.env_flag("PIO_SWAP_VALIDATE", True))
        # Post-swap watch: for this long after a hot swap, query
        # failures are counted against the NEW model (and hedged onto
        # the retained previous one); past the error-rate threshold the
        # swap is rolled back automatically and the bad instance pinned.
        self.swap_watch_ms = max(0.0, float(
            swap_watch_ms if swap_watch_ms is not None
            else _env_int("PIO_SWAP_WATCH_MS", 60_000)))
        self.swap_max_error_rate = float(
            swap_max_error_rate if swap_max_error_rate is not None
            else envknobs.env_float("PIO_SWAP_MAX_ERROR_RATE", 0.5,
                                    lo=0.0, hi=1.0))
        # Continuous refresh (ROADMAP item 4): poll for newer COMPLETED
        # instances and hot-swap them through the validated gate.
        # 0 = off (the default: reloads stay operator-driven).
        self.model_refresh_ms = max(0.0, float(
            model_refresh_ms if model_refresh_ms is not None
            else _env_int("PIO_MODEL_REFRESH_MS", 0)))
        # Streaming online fold-in (ROADMAP item 2; docs/operations.md
        # "Online learning"): tail the deployed app's event log and
        # fold new events into the live model continuously, publishing
        # each increment through the same gate/watch/pin path as a
        # retrain. 0 = off; `pio deploy --online-foldin` arms it.
        self.foldin_ms = max(0.0, float(
            foldin_ms if foldin_ms is not None
            else _env_int("PIO_FOLDIN_MS", 0)))
        self._foldin_task = None
        # loop-confined (the _watch idiom): the runner ticks single-
        # flight off-thread, and /status reads the last view snapshot
        self._foldin_runner = None
        self._foldin_view: Optional[dict] = None
        # Continuous quality evaluation (ROADMAP item 1's guardrail;
        # docs/operations.md "Continuous quality evaluation"): sample a
        # slice of live queries, shadow-replay them on the retained
        # last-good deployment, grade BOTH against held-out next events
        # tailed from the app's log partitions, and feed a significant
        # canary-vs-last-good regression into the SAME rollback path as
        # an error-rate breach (reason "quality"). 0 = off; `pio deploy
        # --quality-eval` arms it.
        self.quality_sample = min(1.0, max(0.0, float(
            quality_sample if quality_sample is not None
            else envknobs.env_float("PIO_QUALITY_SAMPLE", 0.0,
                                    lo=0.0, hi=1.0))))
        self.quality_k = max(1, _env_int("PIO_QUALITY_K", 10))
        self.quality_min_samples = max(1, _env_int(
            "PIO_QUALITY_MIN_SAMPLES", 20))
        self.quality_max_drop = envknobs.env_float(
            "PIO_QUALITY_MAX_DROP", 0.2, lo=0.0)
        # labels are the user's NEXT events, so the quality watch
        # usually outlives the error watch; 0 = inherit the error
        # watch's window
        self.quality_watch_ms = max(0.0, float(
            _env_int("PIO_QUALITY_WATCH_MS", 0))) or self.swap_watch_ms
        self.quality_resolve_ms = max(0.0, float(
            _env_int("PIO_QUALITY_RESOLVE_MS", 2000)))
        self.quality_ms = max(50.0, float(
            _env_int("PIO_QUALITY_MS", 500)))
        # Served-result cache (0 = off, the default): identical
        # queries within the TTL are answered without a model dispatch.
        # Freshness is invalidation-driven (fold-in footprint / swap /
        # rollback — see QueryResultCache); the TTL only bounds
        # staleness the model lifecycle can't observe.
        self.query_cache_size = max(0, int(
            query_cache_size if query_cache_size is not None
            else _env_int("PIO_QUERY_CACHE_SIZE", 0)))
        self.query_cache_ttl_ms = max(0.0, float(
            query_cache_ttl_ms if query_cache_ttl_ms is not None
            else _env_int("PIO_QUERY_CACHE_TTL_MS", 10_000)))
        self._query_cache = (
            QueryResultCache(self.query_cache_size,
                             self.query_cache_ttl_ms / 1e3)
            if self.query_cache_size > 0 and self.query_cache_ttl_ms > 0
            else None)
        # Multi-tenant serving (docs/operations.md "Multi-tenant
        # serving"): > 0 arms the tenant multiplexer — requests routed
        # by access key / X-Pio-App to an LRU cache of that many
        # resident per-app deployments, each tenant with its own
        # lifecycle/fold-in/admission state. 0 = off (single-tenant,
        # the default); `pio deploy --multitenant` arms it.
        self.tenant_max_resident = max(0, int(
            tenant_max_resident if tenant_max_resident is not None
            else _env_int("PIO_TENANT_MAX_RESIDENT", 0)))
        # One tenant's in-flight + queued budget, deliberately below
        # the process cap so a hot app sheds while cold apps serve.
        self.tenant_max_pending = max(1, int(
            tenant_max_pending if tenant_max_pending is not None
            else _env_int("PIO_TENANT_MAX_PENDING", 32)))
        # built in __init__ once storage + the default load are up
        # (skeleton servers built via __new__ stay single-tenant)
        self._tenants = None
        self._quality_task = None
        # loop-confined (the _watch idiom): offer() appends from the
        # request path, the loop ticks single-flight off-thread, and
        # /status reads the last view snapshot
        self._quality_runner = None
        self._quality_view: Optional[dict] = None
        self._quality_watch = None   # active post-swap quality watch
        self._previous = None            # (deployment, instance) resident
        self._pinned: dict[str, str] = {}  # instance id → pin reason
        # pins mid-application (store-walk rollback in flight): honored
        # by this replica's own walks but NOT published to the fleet —
        # the coordinator merges pins irreversibly, so a provisional
        # pin that fails to apply must never leak into the directive
        self._pins_provisional: set = set()
        self._watch = None               # active post-swap watch window
        self._rollbacks: dict[str, int] = {}   # reason → count
        self._swap_count = 0
        self._validate_failures = 0
        self._refresh_swaps = 0
        self._refresh_task = None
        # fleet wiring rides along so __new__-built harness skeletons
        # (tools/big_catalog_demo.py) arm everything with ONE call
        self._init_fleet_state(fleet_replica, fleet_replicas,
                               fleet_sync_ms)

    def _init_fleet_state(self, fleet_replica=None, fleet_replicas=None,
                          fleet_sync_ms=None) -> None:
        """Replica-fleet wiring (docs/operations.md "Serving fleet").

        A fleet replica (``PIO_FLEET_REPLICA`` >= 0, set by the fleet
        supervisor) does not chase the newest COMPLETED instance on its
        own: the fleet coordinator (workflow/fleet.py) stages rollouts
        through a store-mediated directive record, and this replica's
        sync loop applies directives — each swap still passing this
        replica's OWN validation gate — and publishes a status row the
        coordinator (and `pio status --engine-url`) aggregates."""
        self.fleet_replica = int(
            fleet_replica if fleet_replica is not None
            else envknobs.env_int("PIO_FLEET_REPLICA", -1))
        self.fleet_replicas = max(0, int(
            fleet_replicas if fleet_replicas is not None
            else envknobs.env_int("PIO_FLEET_REPLICAS", 0, lo=0)))
        self.fleet_sync_ms = max(50.0, float(
            fleet_sync_ms if fleet_sync_ms is not None
            else _env_int("PIO_FLEET_SYNC_MS", 1000)))
        self.fleet_mode = self.fleet_replica >= 0
        # loop-confined cache of the last directive + peer rows (the
        # _watch idiom): /status and the divergence gauge read the
        # reference atomically, never the store
        self._fleet_view: Optional[dict] = None
        self._fleet_task = None
        self._hb_task = None
        # why the operator's refresh knob "did nothing": surfaced on
        # /status as refreshMs: "disabled(fleet)" instead of silently
        # reporting 0 — a replica chasing the newest instance on its
        # own would race the coordinator's staged canary
        self._refresh_disabled: Optional[str] = None
        if self.fleet_mode and self.model_refresh_ms > 0:
            log.warning(
                "fleet mode: PIO_MODEL_REFRESH_MS=%.0f refused — the "
                "fleet coordinator owns refresh (staged canary); "
                "/status reports refreshMs: disabled(fleet)",
                self.model_refresh_ms)
            self._refresh_disabled = "fleet"
            self.model_refresh_ms = 0.0

    def _fleet_group(self) -> str:
        from . import model_artifact

        # PIO_FLEET_APP (set by the fleet front when the tenant mux is
        # armed) scopes the directive record to the DEFAULT app so the
        # coordinator and every replica agree on the same group name
        app = envknobs.env_str("PIO_FLEET_APP", "")
        return model_artifact.fleet_group(self.engine_factory_name,
                                          self.engine_variant,
                                          app or None)

    @staticmethod
    def _new_compile_families():
        return (telemetry.GaugeFamily(
                    "pio_engine_compile_count",
                    "Warm-up compilations performed for the live engine "
                    "instance, per algorithm", ("algorithm",)),
                telemetry.GaugeFamily(
                    "pio_engine_compile_seconds",
                    "Warm-up compilation wall seconds for the live engine "
                    "instance, per algorithm", ("algorithm",)))

    # -- lifecycle --------------------------------------------------------
    def _load(self, instance_id: Optional[str],
              skip_if_current: bool = False, on_reject=None) -> bool:
        """(Re)load a deployment; returns True when a deployment was
        published, False when skip_if_current short-circuited.

        At INITIAL deploy (nothing serving yet) a validation-refused
        newest instance is pinned and the walk retries older COMPLETED
        instances — the same recovery the integrity walk-back gives a
        corrupt blob, because there is no last-good model to stay on.
        Once something IS serving, a validation failure raises so the
        caller keeps the last-good deployment (and decides about
        pinning)."""
        while True:
            try:
                return self._load_once(instance_id, skip_if_current,
                                       on_reject)
            except SwapValidationError as e:
                with self._lock:
                    has_current = self.deployment is not None
                if instance_id is not None or has_current:
                    raise
                with self._lock:
                    self._validate_failures += 1
                    self._pinned[e.instance_id] = "validate"
                log.warning(
                    "initial deploy: %s; pinning it and walking back to "
                    "an older COMPLETED instance", e)

    def _load_once(self, instance_id: Optional[str],
                   skip_if_current: bool = False, on_reject=None) -> bool:
        ctx = WorkflowContext(storage=self.storage)
        # snapshot under the lock: this runs on a worker thread while
        # the event loop may be pinning concurrently (error-rate
        # rollback is not serialized by the reload lock)
        with self._lock:
            pinned = tuple(self._pinned) if instance_id is None else ()
        deployment, instance, _ = load_deployment(
            self.engine,
            instance_id,
            ctx,
            engine_factory_name=self.engine_factory_name,
            engine_variant=self.engine_variant,
            # latest-completed mode never re-picks a pinned (rolled
            # back / validation-refused) instance; an explicit id is
            # the operator overriding the pin on purpose
            exclude_ids=pinned,
            on_reject=on_reject,
        )
        with self._lock:
            current = self.instance
        if (skip_if_current and current is not None
                and instance.id == current.id):
            # refresh poll raced a walk-back onto the live instance:
            # nothing newer is deployable, keep serving as-is
            log.info("refresh: no newer deployable instance than %s",
                     current.id)
            return False
        # Fresh compile families for this instance: the collector reads
        # the attributes live, so swapping them drops labels that only
        # existed on the previous variant (nothing merges stale rows)
        m_count, m_seconds = self._new_compile_families()
        warmup_errors: list[str] = []
        # Warm up every model that supports it (compile + device
        # placement); wall time per algorithm feeds the compile gauges —
        # on a cold deploy this is almost entirely XLA compilation, the
        # number an operator needs when a reload suddenly takes 30 s.
        for (algo_name, _algo), model in zip(deployment.algo_list,
                                             deployment.models):
            warm = getattr(model, "warm_up", None)
            if callable(warm):
                label = algo_name or type(model).__name__
                t0 = _time.perf_counter()
                try:
                    warm()
                except Exception as e:  # noqa: BLE001 - gate decides below
                    log.exception("model warm-up failed")
                    warmup_errors.append(f"{label}: {e}")
                else:
                    m_count.labels(label).set(1)
                    m_seconds.labels(label).set(
                        _time.perf_counter() - t0)
        if self.batch_window_ms > 0:
            # Pre-compile every power-of-two batch shape the micro-batch
            # path can produce — a cold shape showed ~1.5s p99 through a
            # remote compile service, which would otherwise surface as
            # p99 spikes on live traffic. Models opt in by providing an
            # example_query() the batch path can execute.
            example = self._find_example_query(deployment)
            if example is not None:
                # up to the next pow2 ≥ max_batch: a live window of
                # max_batch queries pads to that shape
                top = 1 << max(self.max_batch - 1, 0).bit_length()
                b = 1
                n_shapes = 0
                t0 = _time.perf_counter()
                while b <= top:
                    try:
                        deployment.batch_query([dict(example)] * b)
                    except Exception as e:  # noqa: BLE001 - gate below
                        log.exception("batch warm-up failed at size %d", b)
                        warmup_errors.append(f"batch[{b}]: {e}")
                        break
                    n_shapes += 1
                    b *= 2
                m_count.labels("batch").set(n_shapes)
                m_seconds.labels("batch").set(
                    _time.perf_counter() - t0)
        # Validation gate — this deployment goes live only past it. A
        # failure leaves the compile gauges and the served deployment
        # exactly as they were (the caller keeps the last-good model).
        if self.swap_validate and warmup_errors:
            raise SwapValidationError(
                instance.id, "warm-up failed: " + "; ".join(warmup_errors))
        self._validate_swap(deployment, instance)
        self._m_compile_count, self._m_compile_seconds = m_count, m_seconds
        with self._lock:
            prev_dep, prev_inst = self.deployment, self.instance
            swapped = (prev_inst is not None
                       and prev_inst.id != instance.id)
            if swapped:
                # Keep exactly ONE previous deployment resident (warm,
                # device buffers intact): /rollback and the post-swap
                # error-rate watch swap back to it instantly, with no
                # storage round trip and no recompile.
                self._previous = (prev_dep, prev_inst)
                self._swap_count += 1
            self.deployment = deployment
            self.instance = instance
            if swapped and self.swap_watch_ms > 0:
                self._watch = {
                    "until": _time.monotonic() + self.swap_watch_ms / 1e3,
                    "total": 0, "errors": 0, "instance": instance.id,
                }
            if (swapped and self.quality_sample > 0
                    and self.quality_watch_ms > 0):
                # quality watch rides every swap alongside the error
                # watch: while it is open, a canary-vs-last-good NDCG
                # breach from the shadow scorer rolls this swap back
                self._quality_watch = {
                    "until": (_time.monotonic()
                              + self.quality_watch_ms / 1e3),
                    "instance": instance.id,
                }
        if swapped and self._query_cache is not None:
            # freshness-correct cache across the swap: an increment
            # whose fold-in marker proves it descends from what we were
            # serving AND names the users it touched evicts exactly
            # those users; anything else flushes the whole cache
            users = self._foldin_footprint(instance, prev_inst)
            capp = self._cache_app()
            if users is None:
                # an unfootprinted swap invalidates the DEFAULT app's
                # entries; with the mux armed other tenants' entries
                # are theirs (their own lifecycles invalidate them)
                n = (self._query_cache.flush("swap") if capp is None
                     else self._query_cache.flush_app(capp, "swap"))
                log.info("query cache: flushed %d entrie(s) on swap "
                         "to %s", n, instance.id)
            else:
                n = self._query_cache.invalidate_users(users, app=capp)
                log.info("query cache: fold-in %s evicted %d entrie(s) "
                         "for %d touched user(s)", instance.id, n,
                         len(users))
        log.info("deployed engine instance %s", instance.id)
        return True

    @staticmethod
    def _foldin_footprint(instance, prev_inst) -> Optional[list]:
        """The incoming instance's targeted-invalidation user list, or
        None when only a full flush is safe. Targeted eviction needs
        BOTH halves of the marker online.py writes: ``users`` (the rows
        the increment chain re-solved) and ``bases`` containing the
        instance this server was actually serving — an increment of
        some other lineage changed an unknown amount of state."""
        try:
            raw = (instance.runtime_conf or {}).get("foldin")
            if not raw or prev_inst is None:
                return None
            doc = json.loads(raw) if isinstance(raw, str) else raw
            users = doc.get("users")
            bases = doc.get("bases")
            if not isinstance(users, list):
                return None
            if not isinstance(bases, list) or prev_inst.id not in bases:
                return None
            return users
        except Exception:  # noqa: BLE001 — on any doubt, full flush
            return None

    def _validate_swap(self, deployment, instance) -> None:
        """Swap gate (PIO_SWAP_VALIDATE, default on): nan_guard over
        every model's arrays plus a smoke predict on the golden query
        (instance runtime_conf["golden_query"] → $PIO_GOLDEN_QUERY →
        the models' example_query protocol). The ``swap.validate``
        fault point lets the chaos harness fail the gate
        deterministically. Any failure raises
        :class:`SwapValidationError` — the model never goes live."""
        if not self.swap_validate:
            return
        from ..common.nan_guard import check_finite

        try:
            faultinject.fault_point("swap.validate")
            for (algo_name, _algo), model in zip(deployment.algo_list,
                                                 deployment.models):
                check_finite(
                    model, f"swap.validate[{algo_name or 'default'}]")
            golden = self._golden_query(instance, deployment)
            if golden is not None:
                # Drive the DASE stages directly instead of
                # Deployment.query: synthetic gate traffic must not
                # consume chaos fault-point budgets (query.*) nor
                # pollute the per-query stage histograms.
                q = deployment.serving.supplement(dict(golden))
                predictions = [
                    algo.predict(model, q)
                    for (_n, algo), model in zip(deployment.algo_list,
                                                 deployment.models)
                ]
                deployment.serving.serve(q, predictions)
            else:
                log.debug("swap validation: no golden query available; "
                          "skipping smoke predict")
        except Exception as e:  # noqa: BLE001 - any failure refuses the swap
            raise SwapValidationError(instance.id, str(e)) from e

    def _golden_query(self, instance, deployment) -> Optional[dict]:
        """The smoke-predict query: a known-good query stored on the
        instance row (runtime_conf["golden_query"]), the operator's
        $PIO_GOLDEN_QUERY, or the models' example_query() opt-in."""
        raw = ((instance.runtime_conf or {}).get("golden_query")
               or envknobs.env_str("PIO_GOLDEN_QUERY", "", lower=False))
        if raw:
            try:
                doc = json.loads(raw)
                if isinstance(doc, dict):
                    return doc
                log.warning("golden_query is not a JSON object; "
                            "falling back to example_query")
            except json.JSONDecodeError:
                log.warning("golden_query is not valid JSON; falling "
                            "back to example_query")
        return self._find_example_query(deployment)

    @staticmethod
    def _find_example_query(deployment) -> Optional[dict]:
        """First model offering a non-None example_query() (the warm-up /
        probe opt-in protocol)."""
        for model in deployment.models:
            ex = getattr(model, "example_query", None)
            if callable(ex):
                example = ex()
                if example is not None:
                    return example
        return None

    # -- handlers ---------------------------------------------------------
    async def handle_status(self, request: web.Request) -> web.Response:
        """Reference: CreateServer status page — JSON here."""
        with self._lock:
            instance = self.instance
        out = {
            "status": "alive",
            "engineInstanceId": instance.id if instance else None,
            "engineFactory": self.engine_factory_name,
            "engineVariant": self.engine_variant,
            "startTime": self.start_time.isoformat(),
            "queryCount": self._query_count,
            "plugins": self.plugins.plugin_names(),
            # resilience surface: serving on a stale model after a failed
            # reload (degraded=true), and feedback events dropped because
            # the event store write failed (counter — ops alert on growth)
            "degraded": self._degraded_reason is not None,
            "degradedReason": self._degraded_reason,
            "droppedFeedback": self._dropped_feedback,
            # overload surface: the operator's no-scrape view of the
            # admission gate (`pio status --engine-url` prints this)
            "overload": self.overload_snapshot(),
            # model-lifecycle surface: previous/pinned instances,
            # rollback + swap-validation counters, refresh config
            "lifecycle": self.lifecycle_snapshot(),
        }
        if self.foldin_ms > 0:
            # online fold-in surface: cursor LSN, freshness lag,
            # publish/rollback history (`pio status --engine-url`
            # prints the freshness-lag line off this). lagSeconds is
            # recomputed at READ time from the last caught-up anchor:
            # the view snapshot freezes while a tick is WEDGED (hung
            # storage), and serving its stale lag would disarm the
            # staleness warn-marker in exactly that case
            fv = self._foldin_view
            if fv and fv.get("caughtUpAt"):
                fv = {**fv, "lagSeconds": round(
                    max(0.0, _time.time() - fv["caughtUpAt"]), 3)}
            out["foldin"] = fv or {
                "enabled": True, "ms": self.foldin_ms,
                "producer": (not self.fleet_mode
                             or self.fleet_replica == 0),
                "events": 0, "publishes": 0, "lagSeconds": None,
            }
        if self._query_cache is not None:
            # served-result cache surface: occupancy, hit/miss and
            # invalidation accounting (`pio status --engine-url` and
            # the soak scorecard's freshness assertion read this)
            out["queryCache"] = self._query_cache.snapshot()
        if self._tenants is not None:
            # multi-tenant surface: LRU occupancy/evictions plus one
            # row per tenant — residency, pins, watch, shed/rollback
            # counters, fold-in cursor lag (`pio status --engine-url`
            # prints the per-tenant table off this)
            out["tenants"] = self._tenants.snapshot()
        if self.quality_sample > 0:
            # continuous-quality surface: sampling/scoring counters,
            # windowed live metrics, last-good deltas, holdout cursor
            # (`pio status --engine-url` prints the quality line off
            # this)
            qw = self._quality_watch
            out["quality"] = {
                **(self._quality_view or {
                    "enabled": True, "sample": self.quality_sample,
                    "sampled": 0, "scored": 0}),
                "watchMs": self.quality_watch_ms,
                "watch": ({"instance": qw["instance"],
                           "remainingMs": round(max(
                               0.0, (qw["until"] - _time.monotonic())
                               * 1e3), 1)}
                          if qw is not None else None),
            }
        if self.fleet_mode:
            # store-fed fleet aggregation, cached by the sync loop (no
            # storage I/O on the status path): directive state, every
            # peer's status row, and a divergence flag — `pio status
            # --engine-url` against the front lands on ANY replica and
            # still sees the whole fleet
            out["fleet"] = self._fleet_view or {
                "group": self._fleet_group(),
                "replica": self.fleet_replica,
                "replicas": self.fleet_replicas,
                "directive": None, "peers": [], "divergence": False,
            }
        # measured serving-latency decomposition, when a probe ran
        # (pio deploy --probe-latency persists it to the instance row)
        probe = (instance.runtime_conf.get("probe_latency")
                 if instance is not None else None)
        if probe:
            try:
                out["probeLatency"] = json.loads(probe)
            except (TypeError, json.JSONDecodeError):
                pass
        return web.json_response(out)

    def _collect_metrics(self):
        """Render-time families owned by THIS server instance."""
        qc = telemetry.GaugeFamily(
            "pio_engine_query_count",
            "Queries served by the live engine server (excludes "
            "synthetic startup probes)")
        qc.labels().set(self._query_count)
        dropped = telemetry.GaugeFamily(
            "pio_engine_dropped_feedback_total",
            "Feedback self-log events dropped by event-store failures")
        dropped.labels().set(self._dropped_feedback)
        ov = self.overload_snapshot()
        fams = [self._m_compile_count, self._m_compile_seconds, qc,
                dropped]
        for name, help_, value in (
            ("pio_engine_query_pending",
             "Accepted queries currently queued or running in the "
             "admission-gated executor", ov["pending"]),
            ("pio_engine_query_pending_limit",
             "Admission cap: PIO_QUERY_CONC + PIO_QUERY_MAX_PENDING",
             ov["pendingLimit"]),
            ("pio_engine_query_pending_peak",
             "High-water mark of accepted in-flight + queued queries",
             ov["peakPending"]),
            ("pio_engine_query_shed_total",
             "Queries refused 503 at admission (queue full or "
             "draining)", ov["shed"]),
            ("pio_engine_query_deadline_exceeded_total",
             "Queries answered 504 because their deadline budget ran "
             "out", ov["deadlineExceeded"]),
            ("pio_engine_query_orphaned_total",
             "Deadline-exceeded queries whose worker thread was still "
             "running at 504 time (freed at the next spend-point)",
             ov["orphaned"]),
            ("pio_engine_draining",
             "1 while the server drains for shutdown (readyz answers "
             "503)", 1 if ov["draining"] else 0),
            ("pio_engine_drain_stragglers",
             "Accepted queries still unfinished when the drain "
             "deadline expired", ov["drainStragglers"]),
        ):
            fam = telemetry.GaugeFamily(name, help_)
            fam.labels().set(value)
            fams.append(fam)
        lc = self.lifecycle_snapshot()
        rb = telemetry.GaugeFamily(
            "pio_engine_rollbacks_total",
            "Deployment rollbacks to the retained previous model, by "
            "reason (error-rate = automatic post-swap watch, quality = "
            "shadow-scorer breach, manual = /rollback)", ("reason",))
        # always expose the automatic-rollback rows so dashboards can
        # alert on their first increment, plus any reasons already seen
        for reason in sorted({"error-rate", "quality",
                              *lc["rollbacks"]}):
            rb.labels(reason).set(lc["rollbacks"].get(reason, 0))
        fams.append(rb)
        for name, help_, value in (
            ("pio_engine_model_swaps_total",
             "Hot swaps to a different engine instance since start "
             "(reload, explicit target, or refresh)", lc["swaps"]),
            ("pio_engine_swap_validate_failures_total",
             "Reload/refresh attempts refused by the swap validation "
             "gate (nan_guard, warm-up, golden-query smoke predict)",
             lc["validateFailures"]),
            ("pio_engine_pinned_instances",
             "Engine instances pinned against redeployment (rolled "
             "back or validation-refused)", len(lc["pinned"])),
            ("pio_engine_model_refresh_swaps_total",
             "Hot swaps performed by the continuous-refresh loop",
             lc["refreshSwaps"]),
        ):
            fam = telemetry.GaugeFamily(name, help_)
            fam.labels().set(value)
            fams.append(fam)
        if self.fleet_mode:
            view = self._fleet_view
            div = telemetry.GaugeFamily(
                "pio_fleet_divergence",
                "1 while this replica's cached peer view shows the "
                "fleet serving more than one engine instance (mixed "
                "brain; converges within PIO_FLEET_SYNC_MS)")
            div.labels().set(
                1 if (view and view.get("divergence")) else 0)
            fams.append(div)
        return fams

    async def handle_metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition: query stage histograms, compile
        gauges, storage transport + breaker families — the engine
        server's share of the process-wide registry."""
        return web.Response(text=telemetry.render_all(),
                            content_type="text/plain")

    async def handle_healthz(self, request: web.Request) -> web.Response:
        """Liveness: the process serves HTTP (mirrors the storage
        server's /health). Restart-worthy failures never answer at all."""
        return web.json_response({"status": "alive"})

    async def handle_readyz(self, request: web.Request) -> web.Response:
        """Readiness: a model is loaded AND no storage circuit breaker
        is open; not-ready answers 503 so load balancers rotate this
        replica out. The degraded flag (serving the last-good model
        after a failed reload) is deliberately NOT part of readiness —
        a degraded replica still answers queries correctly and draining
        it would trade a stale-but-valid model for no capacity; it is
        surfaced here and on /status as telemetry only.

        A DRAINING server is not-ready by design — SIGTERM / /stop flip
        this to 503 FIRST so load balancers rotate the replica out
        while the in-flight queries finish."""
        with self._lock:
            loaded = self.deployment is not None
        open_breakers = [
            b["name"] for b in self._storage_breakers()
            if b.get("state") == "open"
        ]
        with self._adm_lock:
            draining = self._draining
        ready = loaded and not open_breakers and not draining
        out = {
            "ready": ready,
            "modelLoaded": loaded,
            "degraded": self._degraded_reason is not None,
            "draining": draining,
            "openBreakers": open_breakers,
        }
        return web.json_response(out, status=200 if ready else 503)

    # -- admission control / deadlines / drain ----------------------------
    def overload_snapshot(self) -> dict:
        """Shed/deadline/drain counters for /status and `pio status`."""
        with self._adm_lock:
            pending, peak = self._adm_pending, self._adm_peak
            shed, deadline_exceeded = self._shed_count, self._deadline_count
            orphaned, draining = self._orphaned, self._draining
            stragglers = self._drain_stragglers
        return {
            "conc": self.query_conc,
            "pending": pending,
            "pendingLimit": self.query_conc + self.query_max_pending,
            "peakPending": peak,
            "shed": shed,
            "deadlineExceeded": deadline_exceeded,
            "orphaned": orphaned,
            "deadlineMsDefault": self.query_deadline_ms,
            "draining": draining,
            "drainDeadlineMs": self.drain_deadline_ms,
            "drainStragglers": stragglers,
            "reloadConflicts": self._reload_conflicts,
        }

    def _request_deadline(self, request: web.Request) \
            -> Optional[deadline.Deadline]:
        """Per-request budget: X-Pio-Deadline-Ms header, else the
        server default (0 = unbounded). The header may tighten freely
        and loosen only up to PIO_QUERY_DEADLINE_MAX_MS — a malformed,
        non-positive or non-finite header falls back to the default, so
        no client can grant itself an unbounded budget (only the
        operator's default may disable the deadline)."""
        budget_ms = self.query_deadline_ms
        raw = request.headers.get("X-Pio-Deadline-Ms")
        if raw:
            try:
                hdr = float(raw)
            except ValueError:
                hdr = float("nan")
            if math.isfinite(hdr) and hdr > 0:
                budget_ms = hdr
                if self.query_deadline_max_ms > 0:
                    budget_ms = min(budget_ms, self.query_deadline_max_ms)
        if budget_ms <= 0:
            return None
        return deadline.Deadline(budget_ms)

    def _admit(self) -> None:
        """Take one admission slot or refuse. A slot covers the query
        from acceptance until its compute FINISHES — including workers
        that overran their deadline after the client got its 504
        (threads can't be killed), so orphaned work keeps counting
        against the cap and the executor stays bounded."""
        with self._adm_lock:
            if self._draining:
                raise AdmissionShed(
                    "server is draining for shutdown", 1.0, "draining")
            cap = self.query_conc + self.query_max_pending
            if self._adm_pending >= cap:
                raise AdmissionShed(
                    f"query admission queue full ({self._adm_pending}"
                    f"/{cap})", 1.0, "full")
            self._adm_pending += 1
            if self._adm_pending > self._adm_peak:
                self._adm_peak = self._adm_pending

    def _release_slot(self, fut=None) -> None:
        """Admission-slot release; done-callback on both asyncio and
        concurrent futures (the latter runs on a worker thread). Also
        retrieves the future's exception: an orphaned worker failing
        AFTER its client got 504 must be accounted, not warned about
        as a never-retrieved exception."""
        if fut is not None and not fut.cancelled():
            exc = fut.exception()
            if exc is not None and not isinstance(
                    exc, deadline.DeadlineExceeded):
                log.debug("orphaned/abandoned query failed: %s", exc)
        with self._adm_lock:
            self._adm_pending -= 1

    def _run_admitted_query(self, deployment, query):
        """Executor-thread entry. Re-checks the budget first: a query
        that spent its whole deadline WAITING in the executor queue
        frees the worker immediately instead of computing an answer
        nobody is waiting for."""
        dl = deadline.current()
        if dl is not None:
            dl.check("executor pickup")
        return deployment.query(query)

    async def _dispatch_query(self, deployment, query, dl,
                              direct: bool = False):
        """The admission gate — the ONLY way a handler may hand a query
        to compute (guard-tested; a direct ``asyncio.to_thread(
        deployment.query, ...)`` would bypass the bounded executor,
        the shed path and the deadline budget).

        ``direct=True`` skips the micro-batch queue: the batch worker
        always dispatches against the LIVE deployment, so callers that
        must run on a SPECIFIC one (the watch window's hedge onto the
        retained previous model) go straight to the executor.

        Raises :class:`AdmissionShed` (→ 503) or
        :class:`deadline.DeadlineExceeded` (→ 504)."""
        if dl is not None:
            dl.check("admission")
        self._admit()
        slot_owned_by_future = False
        try:
            timeout = dl.remaining() if dl is not None else None
            if self._batch_queue is not None and not direct:
                fut = asyncio.get_running_loop().create_future()
                fut.add_done_callback(self._release_slot)
                slot_owned_by_future = True
                await self._batch_queue.put((query, fut))
                try:
                    return await asyncio.wait_for(fut, timeout)
                except asyncio.TimeoutError:
                    # wait_for cancelled fut; the batch worker's
                    # fut.done() check skips delivering to it
                    raise deadline.DeadlineExceeded(
                        dl.budget_ms, dl.overrun_ms(),
                        "batch queue") from None
            # deadline rides the copied context into the worker thread
            # (same mechanism that carries the trace context)
            with deadline.running(dl):
                ctx = contextvars.copy_context()
            cfut = self._query_executor.submit(
                ctx.run, self._run_admitted_query, deployment, query)
            cfut.add_done_callback(self._release_slot)
            slot_owned_by_future = True
            afut = asyncio.wrap_future(cfut)
            # the shield below can leave afut unawaited (504 path):
            # consume its result/exception so nothing warns
            afut.add_done_callback(
                lambda f: None if f.cancelled() else f.exception())
            try:
                return await asyncio.wait_for(asyncio.shield(afut),
                                              timeout)
            except asyncio.TimeoutError:
                if cfut.cancel():
                    # still queued: the model never saw this query —
                    # the stage matters to the post-swap watch, which
                    # must not blame the canary for queue starvation
                    stage = "queued"
                else:
                    # already running: the thread can't be killed; it
                    # frees itself at the next deadline spend-point
                    # (stage boundary / storage egress) and releases
                    # its admission slot then — clean overrun, the
                    # executor stays bounded
                    with self._adm_lock:
                        self._orphaned += 1
                    stage = "await"
                raise deadline.DeadlineExceeded(
                    dl.budget_ms, dl.overrun_ms(), stage) from None
        finally:
            if not slot_owned_by_future:
                self._release_slot()

    def _storage_breakers(self) -> list[dict]:
        try:
            return [b for states in
                    self.storage.breaker_states().values() for b in states]
        except Exception:  # noqa: BLE001 - readiness must never crash
            log.exception("breaker state collection failed")
            return []

    async def _shutdown_executor(self, app) -> None:
        """App cleanup: release the bounded executor's idle workers
        (don't wait — orphaned threads free themselves at their next
        deadline spend-point; finalize_shutdown owns the hard stop)."""
        self._query_executor.shutdown(wait=False, cancel_futures=True)

    # -- micro-batching ---------------------------------------------------
    async def _start_batcher(self, app) -> None:
        self._batch_queue = asyncio.Queue()
        self._batch_task = asyncio.get_running_loop().create_task(
            self._batch_worker())

    async def _stop_batcher(self, app) -> None:
        # stop accepting, cancel the worker, and fail any stranded
        # queries cleanly instead of leaving their handlers awaiting
        # futures that will never resolve
        queue, self._batch_queue = self._batch_queue, None
        if self._batch_task is not None:
            self._batch_task.cancel()
            self._batch_task = None
        if queue is not None:
            while not queue.empty():
                _, fut = queue.get_nowait()
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("engine server shutting down"))

    async def _batch_worker(self) -> None:
        """Coalesce queued queries: wait for the first, gather more until
        the window closes (or max_batch), one vectorized dispatch. On
        cancellation (server shutdown) the IN-FLIGHT batch's futures are
        failed too — _stop_batcher only sees items still queued."""
        try:
            await self._batch_worker_loop()
        except asyncio.CancelledError:
            for _, fut in getattr(self, "_inflight_batch", []):
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("engine server shutting down"))
            raise

    async def _batch_worker_loop(self) -> None:
        loop = asyncio.get_running_loop()
        window = self.batch_window_ms / 1000.0
        while True:
            self._inflight_batch = []
            batch = self._inflight_batch
            batch.append(await self._batch_queue.get())
            deadline = loop.time() + window
            while len(batch) < self.max_batch:
                timeout = deadline - loop.time()
                if timeout <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._batch_queue.get(), timeout))
                except asyncio.TimeoutError:
                    break
            # Drop entries whose future already settled — a deadline
            # timeout cancels the future but leaves the (query, fut)
            # pair queued; computing it would burn a batch slot on an
            # answer nobody is waiting for (in-place: the cancellation
            # handler aliases this list as _inflight_batch).
            batch[:] = [(q, f) for q, f in batch if not f.done()]
            if not batch:
                continue
            with self._lock:
                deployment = self.deployment
            queries = [q for q, _ in batch]
            try:
                results = await asyncio.to_thread(
                    deployment.batch_query, queries)
            except Exception:  # noqa: BLE001
                # One bad query (e.g. missing field) must not poison its
                # batchmates: degrade to per-query processing so each
                # request gets ITS OWN result or error, exactly like the
                # unbatched path.
                def _one_by_one():
                    out = []
                    for q in queries:
                        try:
                            out.append((True, deployment.query(q)))
                        except Exception as qe:  # noqa: BLE001
                            out.append((False, qe))
                    return out

                for (_, fut), (ok, res) in zip(
                        batch, await asyncio.to_thread(_one_by_one)):
                    if fut.done():
                        continue
                    if ok:
                        fut.set_result(res)
                    else:
                        fut.set_exception(res)
                continue
            for (_, fut), res in zip(batch, results):
                if not fut.done():
                    fut.set_result(res)

    async def handle_query(self, request: web.Request) -> web.Response:
        try:
            query = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"message": "invalid JSON body"}, status=400)
        if self._tenants is not None:
            routed = await self._route_tenant_query(request, query)
            if routed is not None:
                return routed
        with self._lock:
            deployment = self.deployment
        if deployment is None:
            # jittered Retry-After, like every other shed: a constant
            # (or absent) value would synchronize every honouring SDK
            # into one retry wave against the still-empty server
            return web.json_response(
                {"message": "no model deployed"}, status=503,
                headers={"Retry-After": str(retry_after_jitter(2.0))})
        dl = self._request_deadline(request)
        # Plugin hooks run OUTSIDE the watch-window accounting below: a
        # plugin raising on particular client input is not evidence
        # against a freshly-swapped model, and hedging past a failed
        # before_query would serve the untransformed query.
        try:
            query = self.plugins.before_query(query)
        except KeyError as e:
            return web.json_response(
                {"message": f"missing query field {e.args[0]!r}"}, status=400
            )
        except Exception as e:  # noqa: BLE001
            log.exception("before_query plugin failed")
            return web.json_response({"message": str(e)}, status=500)
        cache = self._query_cache
        ckey = None
        cgen = 0
        if cache is not None and "X-Pio-Probe" not in request.headers:
            # probe traffic bypasses the cache BOTH ways: the latency
            # probe must measure the real dispatch path, and synthetic
            # queries must not pollute hit/miss accounting. The key is
            # the post-plugin query — see QueryResultCache.key_for.
            ckey = QueryResultCache.key_for(query, self._cache_app())
            cgen = cache.generation
            cached = cache.get(ckey)
            if cached is not None:
                return await self._finish_query(request, query, cached)
        try:
            result = await self._dispatch_query(deployment, query, dl)
            if self._watch is not None and self._is_live(deployment):
                self._note_watch(ok=True)
            if (self._quality_runner is not None
                    and self._is_live(deployment)):
                # shadow-scorer sampling: one RNG draw on the hot path;
                # sampled queries cost one ranking extraction + an
                # atomic deque append (scored off-loop by the quality
                # tick, never here)
                self._quality_runner.offer(query, result)
            if ckey is not None:
                # only CLEAN dispatch results are cached — the hedged
                # path below (watch-window failure answered by the
                # retained last-good model) never inserts, so a cache
                # hit is always the live model's own answer; the
                # generation guard drops the insert if a swap
                # invalidated mid-dispatch
                cache.put(ckey, result, cgen)
        except AdmissionShed as e:
            with self._adm_lock:
                self._shed_count += 1
            return web.json_response(
                {"message": f"query shed: {e}"}, status=503,
                headers={"Retry-After":
                         str(retry_after_jitter(e.retry_after_base))})
        except deadline.DeadlineExceeded as e:
            # accepted but out of time: 504, NOT 503 — work started, a
            # blind client retry may duplicate load, so the two cases
            # stay distinguishable
            with self._adm_lock:
                self._deadline_count += 1
            # A pathologically SLOW new model is a rollback trigger
            # too: overruns whose stage shows compute was running count
            # against the watch window (no hedge — the budget is
            # spent). Queue-side stages are overload, not the model,
            # and an overrun on a PRE-swap deployment still in flight
            # is not evidence against the model that replaced it.
            if (self._watch is not None
                    and e.stage not in ("admission", "executor pickup",
                                        "batch queue", "queued")
                    and self._is_live(deployment)
                    and self._note_watch(ok=False)):
                self._rollback_to_previous("error-rate")
            return web.json_response({"message": str(e)}, status=504)
        except KeyError as e:
            return web.json_response(
                {"message": f"missing query field {e.args[0]!r}"}, status=400
            )
        except Exception as e:  # noqa: BLE001 - surfaced as HTTP 500 w/ message
            log.exception("query failed")
            # Inside a post-swap watch window: count the failure against
            # the NEW model (rolling back past the error-rate threshold)
            # and hedge this query onto the retained last-good model so
            # the client still gets its answer. The hedge's OWN
            # overload/deadline outcomes keep their 503/504 verdicts —
            # before this mapping they fell into the bare return below
            # as the canary's raw 500 (the 1-in-~12 seed-5 soak red).
            try:
                hedged = await self._watched_failure(deployment, query,
                                                     dl)
            except AdmissionShed as e2:
                with self._adm_lock:
                    self._shed_count += 1
                return web.json_response(
                    {"message": f"query shed: {e2}"}, status=503,
                    headers={"Retry-After":
                             str(retry_after_jitter(
                                 e2.retry_after_base))})
            except deadline.DeadlineExceeded as e2:
                with self._adm_lock:
                    self._deadline_count += 1
                return web.json_response({"message": str(e2)},
                                         status=504)
            if hedged is None:
                return web.json_response({"message": str(e)}, status=500)
            result = hedged
        return await self._finish_query(request, query, result)

    # -- multi-tenant routing (docs/operations.md "Multi-tenant
    # serving"; the mux itself lives in workflow/multitenant.py) -------

    def _default_app_name(self) -> str:
        """The app the process's default deployment serves — anonymous
        requests and this app's keyed requests share the classic
        single-tenant path (and its cache/lifecycle/fold-in state)."""
        from . import model_artifact

        with self._lock:
            inst = self.instance
        name = (model_artifact.instance_app_name(inst)
                if inst is not None else "")
        return name or (self.feedback_app_name or "")

    def _cache_app(self) -> Optional[str]:
        """Cache-key app component for the DEFAULT query path: None
        while single-tenant (the pre-multi-tenant key shape), the
        default app's name once the mux is armed — the default tenant's
        entries must be app-scoped like everyone else's, or an
        anonymous hit could alias a named tenant's miss."""
        if self._tenants is None:
            return None
        return self._default_app_name() or None

    def _tenant_cache_invalidate(self, app: str,
                                 users=None) -> None:
        """Mux callback: invalidate ONE tenant's served-result cache
        entries — by fold-in freshness footprint when attributable,
        else the whole tenant. Never the neighbors: that asymmetry is
        the reason cache keys carry the app component at all."""
        cache = self._query_cache
        if cache is None:
            return
        if users:
            n = cache.invalidate_users(users, app=app)
        else:
            n = cache.flush_app(app, "tenant")
        if n:
            log.info("tenant %r: invalidated %d cached result(s)",
                     app, n)

    async def _route_tenant_query(self, request: web.Request, query):
        """Route a query to its tenant, or return None for the classic
        default path (anonymous requests and the default app's own
        key). A BAD credential is 401/404 — never a silent fallthrough
        that would serve the default app's model under another
        tenant's key."""
        from . import multitenant

        mux = self._tenants
        try:
            app = mux.resolve_app(request)
        except multitenant.UnknownTenant as e:
            return web.json_response({"message": str(e)}, status=401)
        if app is None or app == self._default_app_name():
            return None
        dl = self._request_deadline(request)
        # same contract as the default path: plugin hooks run OUTSIDE
        # the per-tenant watch accounting
        try:
            query = self.plugins.before_query(query)
        except KeyError as e:
            return web.json_response(
                {"message": f"missing query field {e.args[0]!r}"},
                status=400)
        except Exception as e:  # noqa: BLE001
            log.exception("before_query plugin failed")
            return web.json_response({"message": str(e)}, status=500)
        try:
            state = mux.admit(app)
        except multitenant.UnknownTenant as e:
            return web.json_response({"message": str(e)}, status=404)
        except AdmissionShed as e:
            # the TENANT's budget refused (its own counter); the
            # process-wide gate still guards the dispatch below
            return web.json_response(
                {"message": f"query shed: {e}"}, status=503,
                headers={"Retry-After":
                         str(retry_after_jitter(e.retry_after_base))})
        try:
            # admit→release brackets the whole query: the refcount it
            # holds is what "eviction never drops a tenant mid-query"
            # means mechanically
            return await self._tenant_query(request, state, query, dl)
        finally:
            mux.release(state)

    async def _tenant_query(self, request: web.Request, state, query,
                            dl) -> web.Response:
        """One admitted tenant query: lazy load, app-scoped cache,
        dispatch through the PROCESS admission gate, per-tenant watch
        accounting with the rollback-and-answer hedge."""
        mux = self._tenants
        try:
            await asyncio.to_thread(mux.ensure_loaded, state)
        except Exception as e:  # noqa: BLE001 — nothing deployable for
            # THIS app (never trained / every instance pinned): the
            # tenant is unavailable, the process is healthy → 503
            log.warning("tenant %r load failed: %s", state.name, e)
            return web.json_response(
                {"message": f"tenant {state.name!r}: {e}"}, status=503,
                headers={"Retry-After": str(retry_after_jitter(2.0))})
        cache = self._query_cache
        ckey = None
        cgen = 0
        if cache is not None and "X-Pio-Probe" not in request.headers:
            ckey = QueryResultCache.key_for(query, state.name)
            cgen = cache.generation
            cached = cache.get(ckey)
            if cached is not None:
                return await self._finish_query(request, query, cached)
        deployment = state.deployment
        try:
            result = await self._dispatch_query(deployment, query, dl)
            mux.note_result(state, ok=True)
            if ckey is not None:
                cache.put(ckey, result, cgen)
        except AdmissionShed as e:
            with self._adm_lock:
                self._shed_count += 1
            return web.json_response(
                {"message": f"query shed: {e}"}, status=503,
                headers={"Retry-After":
                         str(retry_after_jitter(e.retry_after_base))})
        except deadline.DeadlineExceeded as e:
            with self._adm_lock:
                self._deadline_count += 1
            # compute-stage overruns count against the tenant's OWN
            # watch (same stage taxonomy as the default path)
            if (e.stage not in ("admission", "executor pickup",
                                "batch queue", "queued")
                    and mux.note_result(state, ok=False)):
                await asyncio.to_thread(mux.rollback_tenant, state,
                                        "error-rate")
            return web.json_response({"message": str(e)}, status=504)
        except KeyError as e:
            return web.json_response(
                {"message": f"missing query field {e.args[0]!r}"},
                status=400)
        except Exception as e:  # noqa: BLE001 — per-tenant watch+hedge
            log.exception("tenant %r query failed", state.name)
            restored = None
            if mux.note_result(state, ok=False):
                # watch breach: pin + roll back THIS tenant alone
                restored = await asyncio.to_thread(
                    mux.rollback_tenant, state, "error-rate")
            if restored is not None:
                # the tenant analogue of the watch hedge: answer the
                # triggering query on the restored deployment
                try:
                    result = await self._dispatch_query(
                        restored, query, dl, direct=True)
                except Exception:  # noqa: BLE001 — original verdict
                    return web.json_response({"message": str(e)},
                                             status=500)
                return await self._finish_query(request, query, result)
            return web.json_response({"message": str(e)}, status=500)
        return await self._finish_query(request, query, result)

    async def _finish_query(self, request: web.Request, query,
                            result) -> web.Response:
        """Shared response tail for dispatched AND cache-hit results:
        after_query plugin, probe-marker accounting bypass, query
        count, feedback self-log. A cache hit goes through the same
        plugin + feedback path as a dispatch — only the model call is
        skipped."""
        try:
            result = self.plugins.after_query(query, result)
        except KeyError as e:
            return web.json_response(
                {"message": f"missing query field {e.args[0]!r}"}, status=400
            )
        except Exception as e:  # noqa: BLE001
            log.exception("after_query plugin failed")
            return web.json_response({"message": str(e)}, status=500)
        probe = request.headers.get("X-Pio-Probe")
        # bytes comparison: compare_digest raises TypeError on non-ASCII
        # str input, which a hostile header could use to 500 the request
        # AFTER the query already executed
        if probe and hmac.compare_digest(
                probe.encode("utf-8", "surrogateescape"),
                self._probe_token.encode()):
            # synthetic startup-probe traffic: excluded from queryCount
            # and the feedback self-log; REAL queries arriving during the
            # probe window are unaffected (the marker is per-request).
            # The marker only counts when it carries this process's
            # random token — external clients can't forge the bypass.
            return web.json_response(result)
        self._query_count += 1
        if self.feedback:
            # sync DAO write runs in the default executor, never on the
            # loop. The future must not be fire-and-forget: a failing
            # event store would otherwise drop feedback events with the
            # exception swallowed by the orphaned future — the
            # done-callback logs every failure and counts it into the
            # droppedFeedback counter on /status.
            fut = asyncio.get_running_loop().run_in_executor(
                None, self._log_feedback, query, result
            )
            fut.add_done_callback(self._feedback_done)
        return web.json_response(result)

    def _feedback_done(self, fut: "asyncio.Future") -> None:
        if fut.cancelled():
            self._dropped_feedback += 1
            return
        exc = fut.exception()
        if exc is not None:
            self._dropped_feedback += 1
            log.error("feedback logging failed (dropped=%d): %s",
                      self._dropped_feedback, exc)

    def _log_feedback(self, query: Any, result: Any) -> None:
        """Self-log the prediction as a "predict" event (reference:
        CreateServer feedback loop → event server). Raises on failure —
        the done-callback owns logging and the dropped counter."""
        app_name = self.feedback_app_name
        if not app_name:
            return
        app = self.storage.get_meta_data_apps().get_by_name(app_name)
        if app is None:
            return
        self.storage.get_l_events().insert(
            Event(
                event="predict",
                entity_type="pio_pr",  # server-generated: prefix allowed internally
                entity_id=str(query.get("user", "")) if isinstance(query, dict) else "",
                properties=DataMap({"query": query, "result": result}),
            ),
            app.id,
        )

    # -- startup latency probe (reference: CreateServer hot path;
    # BASELINE.json north star #2 asks for a MEASURED full-path p50) ----
    def probe_and_record(self, base_url: str, n: int = 60) -> Optional[dict]:
        """Measure the full-path query latency decomposition against the
        LIVE server (real HTTP through loopback) and persist it to the
        EngineInstance row (runtime_conf["probe_latency"]). Components:
        http_full (wire-to-wire), predict (host gather + device dispatch
        + on-chip + download), bare device dispatch RTT (the tunnel/queue
        share), json parse. http − predict = server/HTTP overhead;
        predict − rtt ≈ on-chip + result transfer."""
        import http.client
        import ssl
        import time
        import urllib.parse

        with self._lock:
            deployment, instance = self.deployment, self.instance
        example = self._find_example_query(deployment)
        if example is None:
            log.warning(
                "probe-latency: no deployed model provides example_query(); "
                "skipping")
            return None
        body = json.dumps(example).encode()
        # Loopback self-probe: the server's own cert won't verify for
        # 127.0.0.1 (hostname-scoped / self-signed), and verification
        # adds nothing when we ARE the server.
        tls_ctx = None
        parsed = urllib.parse.urlsplit(base_url)
        if parsed.scheme == "https":
            tls_ctx = ssl.create_default_context()
            tls_ctx.check_hostname = False
            tls_ctx.verify_mode = ssl.CERT_NONE

        # ONE keep-alive connection reused across every sample: the p50
        # must measure steady-state request latency, not a per-request
        # TCP (+TLS) handshake — real serving clients hold persistent
        # connections, and the handshake share was the dominant term of
        # the old per-request-urlopen numbers at sub-ms predict times.
        conn_box: list = [None]

        def connect():
            if parsed.scheme == "https":
                return http.client.HTTPSConnection(
                    parsed.hostname, parsed.port, timeout=60,
                    context=tls_ctx)
            return http.client.HTTPConnection(
                parsed.hostname, parsed.port, timeout=60)

        def post():
            for attempt in (0, 1):
                if conn_box[0] is None:
                    conn_box[0] = connect()
                conn = conn_box[0]
                try:
                    conn.request(
                        "POST", "/queries.json", body=body,
                        headers={"Content-Type": "application/json",
                                 "X-Pio-Probe": self._probe_token})
                    conn.getresponse().read()
                    return
                except (http.client.HTTPException, OSError):
                    # server dropped the idle connection: reconnect and
                    # retry the sample once
                    conn.close()
                    conn_box[0] = None
                    if attempt:
                        raise

        def pct(a, p):
            a = sorted(a)
            return a[min(len(a) - 1, round(p / 100 * (len(a) - 1)))]

        for _ in range(5):  # warm the keep-alive connection + executables
            post()
        http_ms = []
        for _ in range(n):
            t0 = time.perf_counter()
            post()
            http_ms.append((time.perf_counter() - t0) * 1e3)
        if conn_box[0] is not None:
            conn_box[0].close()
        parse_ms, predict_ms = [], []
        for _ in range(n):
            t0 = time.perf_counter()
            q = json.loads(body)
            parse_ms.append((time.perf_counter() - t0) * 1e3)
            t0 = time.perf_counter()
            deployment.query(q)
            predict_ms.append((time.perf_counter() - t0) * 1e3)
        rtt_ms = []
        try:
            import jax
            import numpy as _np

            noop = jax.jit(lambda v: v + 1)
            x = jax.device_put(_np.zeros(8, _np.float32))
            jax.device_get(noop(x))  # compile
            for _ in range(n):
                t0 = time.perf_counter()
                jax.device_get(noop(x))
                rtt_ms.append((time.perf_counter() - t0) * 1e3)
        except Exception:  # noqa: BLE001 - probe must not kill serving
            log.exception("probe-latency: device RTT probe failed")

        result = {
            "n": n,
            "attachment": _device_attachment(),
            "http_p50_ms": round(pct(http_ms, 50), 3),
            "http_p99_ms": round(pct(http_ms, 99), 3),
            "predict_p50_ms": round(pct(predict_ms, 50), 3),
            "predict_p99_ms": round(pct(predict_ms, 99), 3),
            "dispatch_rtt_p50_ms": round(pct(rtt_ms, 50), 3) if rtt_ms else None,
            "parse_p50_ms": round(pct(parse_ms, 50), 4),
        }
        result["overhead_p50_ms"] = round(
            max(result["http_p50_ms"] - result["predict_p50_ms"], 0.0), 3)
        if rtt_ms:
            result["onchip_plus_transfer_p50_ms"] = round(
                max(result["predict_p50_ms"] - result["dispatch_rtt_p50_ms"],
                    0.0), 3)
        print(f"[probe] full-path p50={result['http_p50_ms']}ms "
              f"p99={result['http_p99_ms']}ms over {n} queries "
              f"({result['attachment']})")
        print(f"[probe]   predict (gather+dispatch+on-chip+fetch) "
              f"p50={result['predict_p50_ms']}ms")
        if rtt_ms:
            print(f"[probe]   bare device dispatch RTT "
                  f"p50={result['dispatch_rtt_p50_ms']}ms → on-chip+transfer "
                  f"≈ {result['onchip_plus_transfer_p50_ms']}ms")
        print(f"[probe]   http+queue overhead p50="
              f"{result['overhead_p50_ms']}ms, json parse "
              f"p50={result['parse_p50_ms']}ms")
        try:
            import dataclasses as _dc

            instances = self.storage.get_meta_data_engine_instances()
            fresh = instances.get(instance.id) or instance
            updated = _dc.replace(
                fresh,
                runtime_conf={**fresh.runtime_conf,
                              "probe_latency": json.dumps(result)})
            instances.update(updated)
            with self._lock:
                # keep the live status page in sync with the stored row
                if self.instance is not None and self.instance.id == updated.id:
                    self.instance = updated
        except Exception:  # noqa: BLE001 - persistence is best-effort
            log.exception("probe-latency: persisting to instance row failed")
        return result

    # -- post-swap watch + rollback ---------------------------------------
    def lifecycle_snapshot(self) -> dict:
        """Model-lifecycle state for /status and `pio status
        --engine-url`: current/previous instance, pins, rollback and
        validation counters, refresh/watch config."""
        from . import model_artifact

        with self._lock:
            cur, prev = self.instance, self._previous
            pinned = dict(self._pinned)
            rollbacks = dict(self._rollbacks)
            swaps = self._swap_count
            validate_failures = self._validate_failures
            refresh_swaps = self._refresh_swaps
        w = self._watch
        return {
            "instance": cur.id if cur else None,
            "previous": prev[1].id if prev else None,
            # process-wide: every model blob the verifying loader
            # refused in this process, by failure kind
            "integrityFailures": model_artifact.integrity_failure_counts(),
            "pinned": pinned,
            "rollbacks": rollbacks,
            "swaps": swaps,
            "validateFailures": validate_failures,
            "validate": self.swap_validate,
            # "disabled(fleet)" when the operator's knob was refused
            # (the coordinator owns refresh) — a bare 0 here looked
            # exactly like "never configured" and hid the reason
            "refreshMs": (f"disabled({self._refresh_disabled})"
                          if self._refresh_disabled
                          else self.model_refresh_ms),
            "refreshSwaps": refresh_swaps,
            "watchMs": self.swap_watch_ms,
            "maxErrorRate": self.swap_max_error_rate,
            "watch": ({"total": w["total"], "errors": w["errors"]}
                      if w is not None else None),
        }

    def _is_live(self, deployment) -> bool:
        """Whether ``deployment`` is the one currently published — watch
        accounting must ignore outcomes of queries dispatched to a
        PRE-swap deployment that were still in flight when the swap
        landed."""
        with self._lock:
            return self.deployment is deployment

    def _note_watch(self, ok: bool) -> bool:
        """Record one query outcome against the post-swap watch window
        (loop context only). Returns True when the error rate tripped
        the rollback threshold — at least 2 failures AND a failure
        fraction above PIO_SWAP_MAX_ERROR_RATE, so one flaky query
        can't roll back a healthy model."""
        w = self._watch
        if w is None:
            return False
        with self._lock:
            cur = self.instance
        if cur is None or w["instance"] != cur.id:
            # a newer swap/rollback superseded this window — but only
            # clear OUR snapshot: a concurrent _load (worker thread) may
            # have already installed the NEW swap's watch, which must
            # not be disarmed by a query that raced the swap
            if self._watch is w:
                self._watch = None
            return False
        if _time.monotonic() > w["until"]:
            log.info("post-swap watch for %s closed clean (%d queries, "
                     "%d errors)", w["instance"], w["total"], w["errors"])
            if self._watch is w:
                self._watch = None
            return False
        w["total"] += 1
        if not ok:
            w["errors"] += 1
            if (w["errors"] >= 2
                    and w["errors"] / w["total"] > self.swap_max_error_rate):
                return True
        return False

    def _rollback_to_previous(self, reason: str) -> Optional[str]:
        """Instant swap back to the resident previous deployment (no
        storage round trip, no recompile — it stayed warm). The bad
        instance is PINNED so neither the latest-completed walk nor the
        refresh loop re-picks it; its blob is never deleted. Returns
        the restored instance id, or None when no previous deployment
        is resident."""
        with self._lock:
            if self._previous is None:
                return None
            bad_inst = self.instance
            self.deployment, self.instance = self._previous
            self._previous = None
            restored = self.instance
        self._watch = None
        # the bad instance's quality watch dies with it — the restored
        # model is the last-good baseline, not a canary
        self._quality_watch = None
        if self._query_cache is not None:
            # every cached result was computed by the model we just
            # rolled away from; the restored model must answer fresh
            n = self._query_cache.flush("rollback")
            log.info("query cache: flushed %d entrie(s) on rollback", n)
        with self._lock:
            # setdefault: a fleet-directed rollback arrives AFTER the
            # coordinator already recorded the real pin reason (e.g.
            # error-rate from the canary) — "fleet" must not clobber it
            self._pinned.setdefault(bad_inst.id, reason)
            self._rollbacks[reason] = self._rollbacks.get(reason, 0) + 1
        self._degraded_reason = (
            f"rolled back from {bad_inst.id} to {restored.id} ({reason}) "
            f"at {_dt.datetime.now(_dt.timezone.utc).isoformat()}; "
            f"{bad_inst.id} pinned until an operator reloads it "
            "explicitly")
        try:
            from . import online

            # a poisoned fold-in rolling back counts on ITS family too,
            # so operators can tell bad increments from bad retrains
            if online.is_foldin_instance(bad_inst):
                online.note_rollback(reason)
        except Exception:  # noqa: BLE001 — accounting must not block it
            pass
        log.warning("automatic rollback (%s): %s → %s; %s pinned",
                    reason, bad_inst.id, restored.id, bad_inst.id)
        return restored.id

    async def _watched_failure(self, deployment, query, dl):
        """A query failed on a deployment inside its post-swap watch
        window: hedge it onto the last-good deployment, and — only when
        last-good SUCCEEDS on the same query (differential diagnosis:
        a query that fails on both models is the query's problem, not
        the canary's) — count the failure against the new model,
        rolling back past the error-rate threshold. Either way the
        client gets the hedged answer instead of the canary's 500.
        Returns the hedged result, or None (caller answers the
        original error). Overload/deadline failures of the HEDGE
        dispatch itself (:class:`AdmissionShed`,
        :class:`deadline.DeadlineExceeded`) PROPAGATE — they are the
        server's state, not the canary's, so the caller must answer
        503/504, never convert them into the canary's raw 500 (the
        soak's seed-5 leak), and they never count against the watch."""
        w = self._watch
        with self._lock:
            live_dep = self.deployment
            prev = self._previous
            cur = self.instance
        if w is None:
            # No watch — but if the deployment this query failed on is
            # no longer the live one, a rollback (which clears the
            # watch) or a swap landed while the query was in flight:
            # its failure is stale evidence, and the client deserves
            # the LIVE model's answer, not the retired model's 500.
            # This is the post-rollback straggler leg of the seed-5
            # soak's raw-500 leak.
            if live_dep is not None and live_dep is not deployment:
                try:
                    return await self._dispatch_query(live_dep, query,
                                                      dl, direct=True)
                except (AdmissionShed, deadline.DeadlineExceeded):
                    raise
                except Exception:  # noqa: BLE001 - original error stands
                    log.exception("retry on live model failed")
                    return None
            return None
        # prune an expired or superseded window BEFORE hedging: outside
        # the watch the client must get the live model's real error,
        # not a silent answer from a long-superseded previous model
        if cur is None or w["instance"] != cur.id:
            if self._watch is w:     # superseded by a newer swap
                self._watch = None
            return None
        if _time.monotonic() > w["until"]:
            log.info("post-swap watch for %s closed clean (%d queries, "
                     "%d errors)", w["instance"], w["total"], w["errors"])
            if self._watch is w:
                self._watch = None
            return None
        if live_dep is not deployment:
            # a concurrent query already rolled back: serve the restored
            try:
                return await self._dispatch_query(live_dep, query, dl,
                                                  direct=True)
            except (AdmissionShed, deadline.DeadlineExceeded):
                raise   # server state, not the canary's error — 503/504
            except Exception:  # noqa: BLE001 - original error stands
                log.exception("retry on restored model failed")
                return None
        if prev is None:
            return None
        try:
            # direct=True: the micro-batch queue would dispatch against
            # the LIVE (canary) deployment, defeating the hedge
            result = await self._dispatch_query(prev[0], query, dl,
                                                direct=True)
        except (AdmissionShed, deadline.DeadlineExceeded):
            # the hedge ran out of budget/capacity: NOT evidence against
            # either model — surface the overload verdict (503/504)
            raise
        except Exception:  # noqa: BLE001 - query fails on BOTH models
            log.exception("hedged retry on last-good model failed too; "
                          "not counting against the new model")
            return None
        if self._note_watch(ok=False):
            self._rollback_to_previous("error-rate")
        return result

    async def handle_rollback(self, request: web.Request) -> web.Response:
        """Operator rollback to the retained previous deployment
        (`pio models rollback --engine-url` / `pio deploy --rollback`).
        Instant — the previous model stayed resident — and pins the
        rolled-back instance so refresh/reload-latest won't re-pick
        it."""
        if self._reload_lock.locked():
            self._reload_conflicts += 1
            return web.json_response(
                {"message": "reload in progress; retry shortly"},
                status=409)
        async with self._reload_lock:
            restored = self._rollback_to_previous("manual")
            if restored is None and self.fleet_mode:
                restored = await self._fleet_rollback_via_store()
        if restored is None:
            return web.json_response(
                {"message": "no previous deployment resident to roll "
                            "back to"}, status=409)
        if self.fleet_mode:
            # propagate NOW instead of waiting for the next tick: the
            # pin lands in this replica's status row, the coordinator
            # picks it up on its next poll, and the whole fleet
            # converges on last-good within the sync bound
            t = asyncio.get_running_loop().create_task(self._fleet_sync())
            t.add_done_callback(
                lambda f: None if f.cancelled() else f.exception())
        return web.json_response(
            {"message": "Rolled back", "engineInstanceId": restored,
             **({"fleet": True} if self.fleet_mode else {})})

    # -- continuous refresh ------------------------------------------------
    async def _start_refresher(self, app) -> None:
        if self.model_refresh_ms > 0:
            self._refresh_task = asyncio.get_running_loop().create_task(
                self._refresh_loop())

    async def _stop_refresher(self, app) -> None:
        task, self._refresh_task = self._refresh_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _refresh_loop(self) -> None:
        """Continuous model refresh (PIO_MODEL_REFRESH_MS > 0): poll
        for a newer COMPLETED instance and hot-swap it through the SAME
        validated gate as /reload. A validation failure pins the
        candidate (it will fail again — NaN models don't heal) and
        stays on last-good; a poll/storage error is logged and retried
        next tick. The loop must never die."""
        log.info("model refresh loop armed (every %.0f ms)",
                 self.model_refresh_ms)
        while True:
            await asyncio.sleep(self.model_refresh_ms / 1000.0)
            try:
                await self._refresh_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - poll errors never kill it
                log.exception("model refresh poll failed; retrying next "
                              "tick")

    async def _refresh_once(self) -> None:
        candidate = await asyncio.to_thread(self._newer_candidate)
        if candidate is None:
            return
        log.info("refresh: newer COMPLETED instance %s; validating "
                 "hot swap", candidate.id)
        if await self._publish_once("refresh") == "swapped":
            with self._lock:
                self._refresh_swaps += 1

    async def _publish_once(self, source: str) -> str:
        """THE publish-through-gate entry point — the ONE place a newer
        COMPLETED instance becomes the served deployment outside an
        operator /reload: validated load of the newest deployable
        instance (skip-if-current), gate-refusal pin + degraded mode,
        integrity-rejection pins, post-swap watch armed by the swap
        itself. Shared by the continuous-refresh loop and the online
        fold-in publisher (docs/operations.md "Online learning") so the
        two paths cannot drift — duplicating the gate/watch/pin
        sequence is exactly how they would. Returns "swapped" |
        "current" | "busy" | "refused" | "error"."""
        if self._reload_lock.locked():
            return "busy"
        async with self._reload_lock:
            rejected: list[tuple[str, str]] = []
            result = "current"
            try:
                swapped = await asyncio.to_thread(
                    self._load, None, True,
                    lambda iid, kind: rejected.append((iid, kind)))
            except SwapValidationError as e:
                with self._lock:
                    self._validate_failures += 1
                    self._pinned[e.instance_id] = "validate"
                self._degraded_reason = (
                    f"{source}: {e}; serving last-good model "
                    f"({e.instance_id} pinned)")
                log.warning("%s swap refused: %s", source, e)
                # a refused FOLD-IN increment counts on its family no
                # matter which caller's gate caught it — the refresh
                # loop can win the reload-lock race for an increment
                # the fold-in tick committed a moment earlier
                await asyncio.to_thread(self._count_foldin_refusal,
                                        e.instance_id)
                result = "refused"
            except Exception as e:  # noqa: BLE001 - stay on last-good
                self._degraded_reason = (
                    f"{source} reload failed at "
                    f"{_dt.datetime.now(_dt.timezone.utc).isoformat()}: "
                    f"{e}; serving last-good model")
                log.exception("%s reload failed; continuing on "
                              "last-good model", source)
                result = "error"
            else:
                if swapped:
                    result = "swapped"
                # the load SUCCEEDED — whether it swapped or confirmed
                # the live instance is still the newest deployable, a
                # degraded reason from an earlier transient failure no
                # longer describes reality
                self._degraded_reason = None
            # pin integrity-rejected candidates: a corrupt blob won't
            # heal, and without the pin every poll would re-walk (and
            # re-count) the same corpse
            for iid, kind in rejected:
                with self._lock:
                    self._pinned.setdefault(iid, f"integrity:{kind}")
                log.warning("%s: pinned undeployable instance %s "
                            "(%s)", source, iid, kind)
            return result

    def _count_foldin_refusal(self, instance_id: str) -> None:
        """Worker-thread classification of a gate-refused instance:
        increments pio_foldin_rollbacks_total{validate} when the row
        carries the fold-in provenance marker. Best-effort — metric
        accounting must never fail a publish path."""
        try:
            from . import online

            row = self.storage.get_meta_data_engine_instances().get(
                instance_id)
            if row is not None and online.is_foldin_instance(row):
                online.note_rollback("validate")
        except Exception:  # noqa: BLE001 — accounting only
            log.debug("fold-in refusal classification failed",
                      exc_info=True)

    # -- streaming online fold-in (docs/operations.md "Online learning") --
    async def _start_foldin(self, app) -> None:
        if self.foldin_ms <= 0:
            return
        if self.fleet_mode and self.fleet_replica != 0:
            # ONE producer per fleet: replica 0 commits increments and
            # the coordinator canaries them to everyone (this replica
            # included) — N replicas each folding the same events would
            # race N duplicate instance rows into the store
            log.info("fold-in: replica %d stands by — replica 0 is the "
                     "fleet's fold-in producer", self.fleet_replica)
            return
        from . import online

        runner = self._foldin_runner = online.FoldInRunner(
            self.storage, self.engine_factory_name, self.engine_variant,
            interval_ms=self.foldin_ms)
        with self._lock:
            instance = self.instance
        if instance is not None:
            # arm the cursor BEFORE the listen port opens: without a
            # persisted cursor the tailer anchors at the log end, and
            # anchoring on the first tick instead would skip events
            # that land in the start→first-tick window
            try:
                await asyncio.to_thread(runner.arm, instance)
            except Exception:  # noqa: BLE001 — first tick retries
                log.exception("fold-in arm failed; first tick retries")
        self._foldin_view = {**runner.view(), "producer": True}
        self._foldin_task = asyncio.get_running_loop().create_task(
            self._foldin_loop())

    async def _stop_foldin(self, app) -> None:
        task, self._foldin_task = self._foldin_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _foldin_loop(self) -> None:
        """Online fold-in (PIO_FOLDIN_MS > 0): tail the app's event
        log, fold new events into a copy of the live models, commit the
        increment as a new COMPLETED instance, and publish it through
        the SAME gate as a retrain (fleet mode: leave publication to
        the coordinator's staged canary). A failed tick is logged and
        retried — the loop must never die, and the freshness-lag gauge
        keeps growing until a tick lands."""
        log.info("online fold-in loop armed (every %.0f ms%s)",
                 self.foldin_ms,
                 ", fleet producer" if self.fleet_mode else "")
        while True:
            await asyncio.sleep(self.foldin_ms / 1000.0)
            try:
                await self._foldin_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - tick errors never kill it
                log.exception("fold-in tick failed; retrying next tick")

    async def _foldin_once(self) -> None:
        from . import online

        if self._tenants is not None:
            # per-tenant fold-in rides the same clock: each resident
            # tenant's runner reads its OWN durable cursor row and its
            # increments publish through that tenant's gate + watch;
            # per-tenant failures are contained inside the tick
            await asyncio.to_thread(self._tenants.foldin_tick)
        with self._lock:
            deployment, instance = self.deployment, self.instance
            pinned = tuple(self._pinned)
        if deployment is None or instance is None:
            return
        runner = self._foldin_runner
        if runner is None:
            runner = self._foldin_runner = online.FoldInRunner(
                self.storage, self.engine_factory_name,
                self.engine_variant, interval_ms=self.foldin_ms)
        try:
            view = await asyncio.to_thread(runner.run_once, deployment,
                                           instance, pinned)
        finally:
            self._foldin_view = {**runner.view(), "producer": True}
        produced = view.get("instance")
        if self.fleet_mode:
            if produced:
                # the coordinator discovers the new COMPLETED row on
                # its next tick and stages it as a CANARY; publishing
                # locally would bypass the staged rollout (and be
                # reverted by the next directive sync anyway)
                log.info("fold-in: instance %s committed; awaiting the "
                         "fleet coordinator's canary staging", produced)
            return
        if not produced and not view.get("pendingInstance"):
            return
        # produced this tick OR still pending from an earlier one (a
        # busy gate / failed cursor persist must not strand a committed
        # increment until the next event happens to arrive)
        # gate refusals are classified + counted inside _publish_once
        # (via the provenance marker), so refusals caught by the
        # refresh loop's racing publish land on the same family
        await self._publish_once("foldin")
        self._foldin_view = {**runner.view(), "producer": True}

    # -- continuous quality evaluation (docs/operations.md
    # "Continuous quality evaluation") ------------------------------------
    async def _start_quality(self, app) -> None:
        if self.quality_sample <= 0:
            return
        from . import quality

        self._quality_runner = quality.QualityShadow(
            self.storage, sample=self.quality_sample,
            k=self.quality_k, min_samples=self.quality_min_samples,
            max_drop=self.quality_max_drop,
            resolve_ms=self.quality_resolve_ms)
        self._quality_view = self._quality_runner.view()
        self._quality_task = asyncio.get_running_loop().create_task(
            self._quality_loop())

    async def _stop_quality(self, app) -> None:
        task, self._quality_task = self._quality_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _quality_loop(self) -> None:
        """Shadow scoring (PIO_QUALITY_SAMPLE > 0): replay sampled live
        queries against the retained last-good deployment, grade both
        against held-out next events tailed from the app's log
        partitions, and roll a quality-watch breach back through the
        SAME path as an error-rate breach (reason "quality"). A failed
        tick is logged and retried — the loop must never die."""
        log.info("quality shadow loop armed (sample %.3f, every %.0f "
                 "ms, watch %.0f ms, min %d samples, max ndcg drop "
                 "%.3f)", self.quality_sample, self.quality_ms,
                 self.quality_watch_ms, self.quality_min_samples,
                 self.quality_max_drop)
        while True:
            await asyncio.sleep(self.quality_ms / 1000.0)
            try:
                await self._quality_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - tick errors never kill it
                log.exception("quality tick failed; retrying next tick")

    async def _quality_once(self) -> None:
        runner = self._quality_runner
        if runner is None:
            return
        with self._lock:
            deployment, instance = self.deployment, self.instance
            prev = self._previous
        if deployment is None or instance is None:
            return
        qw = self._quality_watch
        if qw is not None and (instance.id != qw["instance"]
                               or _time.monotonic() > qw["until"]):
            # superseded by a newer swap/rollback, or closed clean —
            # only clear OUR snapshot (the _note_watch idiom): a
            # concurrent _load may have armed the NEW swap's watch
            if self._quality_watch is qw:
                if instance.id == qw["instance"]:
                    log.info("quality watch for %s closed clean",
                             qw["instance"])
                self._quality_watch = None
            qw = None
        prev_dep = prev[0] if prev is not None else None
        try:
            view = await asyncio.to_thread(runner.run_once, deployment,
                                           instance, prev_dep)
        finally:
            self._quality_view = runner.view()
        if not view.get("breach") or qw is None:
            return
        with self._lock:
            live = self.instance
        if (self._quality_watch is qw and live is not None
                and live.id == qw["instance"]):
            self._quality_watch = None
            restored = self._rollback_to_previous("quality")
            if restored:
                log.warning(
                    "quality watch breach on %s (ndcg drop %.4f > "
                    "%.4f over %d graded samples): rolled back to %s",
                    qw["instance"], view["deltas"].get("ndcg", 0.0),
                    self.quality_max_drop,
                    view.get("live", {}).get("n", 0), restored)

    def _newer_candidate(self):
        """Worker-thread poll: the newest non-pinned COMPLETED instance
        strictly newer than the live one, or None when up to date (the
        shared definition in model_artifact — the fleet coordinator's
        rollout staging must agree with this poll about "newer")."""
        from . import model_artifact

        with self._lock:
            cur = self.instance
            pinned = set(self._pinned)
        # with the tenant mux armed the DEFAULT path refreshes within
        # its own app only — a tenant's fold-in increment is newer but
        # must never hot-swap in as the default deployment
        app = self._cache_app() if self._tenants is not None else None
        return model_artifact.newer_completed_instance(
            self.storage.get_meta_data_engine_instances(),
            self.engine_factory_name, self.engine_variant, cur,
            exclude=pinned, app_name=app)

    # -- replica fleet (store-mediated staged rollout) ---------------------
    def _fleet_bootstrap_load(self) -> None:
        """Initial load of a fleet replica: honor the fleet record
        BEFORE touching the instance walk — a replica relaunched after
        a fleet rollback must come up on the directed last-good
        instance with the fleet's pins applied, not on the newest
        COMPLETED row (which may be exactly the poisoned artifact the
        fleet just rolled back)."""
        from . import model_artifact

        row_id = model_artifact.fleet_row_id(self._fleet_group())
        directive = model_artifact.read_fleet_doc(self.storage, row_id)
        if directive is None:
            # the coordinator re-commits the directive every sync tick,
            # and on backends whose Models.insert is DELETE-then-INSERT
            # (pg/mysql) a read can land in the gap and see the row
            # absent — one short retry separates "no directive yet"
            # from that window, because booting onto the newest
            # COMPLETED row here may be exactly the poisoned artifact
            # the fleet just rolled back
            _time.sleep(0.05)
            directive = model_artifact.read_fleet_doc(
                self.storage, row_id)
        directive = directive or {}
        with self._lock:
            for iid, reason in (directive.get("pinned") or {}).items():
                self._pinned.setdefault(iid, reason)
            pinned = set(self._pinned)
        want = directive.get("instance")
        if want and want not in pinned:
            try:
                self._load(want)
                return
            except Exception:  # noqa: BLE001 - degrade to the walk
                log.warning(
                    "fleet directive instance %s not deployable at "
                    "startup; walking back to latest", want,
                    exc_info=True)
        self._load(None)

    async def _start_fleet(self, app) -> None:
        if self.fleet_mode:
            self._fleet_task = asyncio.get_running_loop().create_task(
                self._fleet_loop())

    async def _stop_fleet(self, app) -> None:
        task, self._fleet_task = self._fleet_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _fleet_loop(self) -> None:
        """Fleet sync (PIO_FLEET_SYNC_MS): apply coordinator directives
        and publish this replica's status row. Never dies — a storage
        flake is logged and retried next tick."""
        log.info("fleet sync loop armed (replica %d, every %.0f ms)",
                 self.fleet_replica, self.fleet_sync_ms)
        while True:
            try:
                await self._fleet_sync()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - poll errors never kill it
                log.exception("fleet sync failed; retrying next tick")
            await asyncio.sleep(self.fleet_sync_ms / 1000.0)

    async def _fleet_sync(self) -> None:
        from . import model_artifact

        directive = await asyncio.to_thread(
            model_artifact.read_fleet_doc, self.storage,
            model_artifact.fleet_row_id(self._fleet_group())) or {}
        with self._lock:
            # fleet pins propagate to every replica: a restarting
            # refresh/reload on this replica must never re-pick an
            # instance any peer rolled back (mixed-brain prevention)
            for iid, reason in (directive.get("pinned") or {}).items():
                self._pinned.setdefault(iid, reason)
            pinned = set(self._pinned)
            cur = self.instance
        want = directive.get("instance")
        if (directive.get("state") == "canary"
                and directive.get("canaryReplica") == self.fleet_replica
                and directive.get("target")):
            # staged rollout: ONLY the canary replica swaps to the
            # target; everyone else holds the directed instance until
            # the coordinator promotes a clean watch window
            want = directive.get("target")
        if (want and want not in pinned
                and (cur is None or want != cur.id)
                and not self._reload_lock.locked()):
            async with self._reload_lock:
                # recheck under the lock: a concurrent sync (manual-
                # rollback fast path) may have applied this directive
                # while we queued — re-applying would pay a full
                # storage reload for nothing
                with self._lock:
                    cur = self.instance
                if cur is None or want != cur.id:
                    await self._fleet_apply(want)
        await asyncio.to_thread(self._fleet_publish, directive)

    async def _fleet_apply(self, want: str) -> None:
        """Apply one directive target through this replica's own gate.
        A directed rollback whose target is the still-resident previous
        deployment swaps back instantly (no storage round trip); other
        targets take the full verified + validated load. Failures pin
        (validate/integrity) or degrade (transient) — the coordinator
        sees the pin in the next status row and propagates."""
        from . import model_artifact

        with self._lock:
            prev = self._previous
        if prev is not None and prev[1].id == want:
            self._rollback_to_previous("fleet")
            return
        try:
            await asyncio.to_thread(self._load, want)
        except SwapValidationError as e:
            with self._lock:
                self._validate_failures += 1
                self._pinned.setdefault(e.instance_id, "validate")
            self._degraded_reason = (
                f"fleet: {e}; serving last-good model "
                f"({e.instance_id} pinned)")
            log.warning("fleet swap refused by gate: %s", e)
        except model_artifact.ModelIntegrityError as e:
            with self._lock:
                self._pinned.setdefault(e.instance_id,
                                        f"integrity:{e.kind}")
            self._degraded_reason = (
                f"fleet: directed instance {e.instance_id} failed "
                f"integrity ({e.kind}); serving last-good model")
            log.warning("fleet swap refused by integrity: %s", e)
        except Exception as e:  # noqa: BLE001 - transient: retry next tick
            self._degraded_reason = (
                f"fleet reload failed at "
                f"{_dt.datetime.now(_dt.timezone.utc).isoformat()}: {e}; "
                "serving last-good model")
            log.exception("fleet swap failed; continuing on last-good")
        else:
            self._degraded_reason = None

    def _fleet_publish(self, directive: dict) -> None:
        """Worker-thread half of the sync: write this replica's status
        row (single writer: us) and refresh the cached peer view that
        /status and the divergence gauge read."""
        from . import model_artifact

        with self._lock:
            cur, prev = self.instance, self._previous
            pinned = {i: r for i, r in self._pinned.items()
                      if i not in self._pins_provisional}
            rollbacks = dict(self._rollbacks)
        with self._adm_lock:
            draining = self._draining
        w = self._watch
        qw = self._quality_watch
        # the coordinator treats the quality watch EXACTLY like the
        # error watch: a canary promotes only once BOTH windows close
        # clean (a ranking-degrading canary must not be promoted while
        # its labels are still arriving)
        watch_done = ((w is None or cur is None
                       or w.get("instance") != cur.id
                       or _time.monotonic() > w["until"])
                      and (qw is None or cur is None
                           or qw.get("instance") != cur.id
                           or _time.monotonic() > qw["until"]))
        group = self._fleet_group()
        status = {
            "replica": self.fleet_replica,
            "pid": os.getpid(),
            "instance": cur.id if cur else None,
            "previous": prev[1].id if prev else None,
            "pinned": pinned,
            "rollbacks": rollbacks,
            "draining": draining,
            "watchDone": watch_done,
            "epochSeen": directive.get("epoch", 0),
            "updatedAt": _time.time(),
        }
        model_artifact.write_fleet_doc(
            self.storage, model_artifact.fleet_row_id(
                group, self.fleet_replica), status)
        peers = directive.get("peers")
        if peers is None:
            # no coordinator peer snapshot yet (coordinator not started,
            # or a pre-snapshot directive): fall back to reading each
            # peer row directly
            peers = []
            for i in range(max(self.fleet_replicas,
                               self.fleet_replica + 1)):
                doc = model_artifact.read_fleet_doc(
                    self.storage, model_artifact.fleet_row_id(group, i))
                if doc is not None:
                    peers.append(doc)
        else:
            # the coordinator aggregates every status row each tick and
            # ships the snapshot inside the directive — consuming it
            # costs each replica ONE store read per tick instead of N
            # (O(N) fleet-wide, not O(N^2)); substitute our own
            # just-written row so this replica's /status never lags
            # itself by a coordinator tick
            peers = [p for p in peers
                     if p.get("replica") != self.fleet_replica]
            peers.append(status)
            peers.sort(key=lambda p: p.get("replica") or 0)
        serving = {p.get("instance") for p in peers if p.get("instance")}
        self._fleet_view = {
            "group": group,
            "replica": self.fleet_replica,
            "replicas": self.fleet_replicas,
            "syncMs": self.fleet_sync_ms,
            "directive": {k: directive.get(k) for k in
                          ("state", "instance", "target",
                           "canaryReplica", "lastGood", "epoch",
                           "pinned")},
            "peers": peers,
            "divergence": len(serving) > 1,
        }

    async def _fleet_rollback_via_store(self) -> Optional[str]:
        """Fleet rollback on a replica with NO resident previous
        deployment (it was relaunched and booted straight onto the
        current instance): the front's round-robin must not make
        `pio models rollback --engine-url <front>` nondeterministic, so
        pin the current instance and walk back through the store
        instead. Caller holds the reload lock. Returns the restored
        instance id, or None (pin reverted) when nothing older is
        deployable."""
        with self._lock:
            cur = self.instance
        if cur is None:
            return None
        with self._lock:
            # provisional until the walk-back lands: a concurrent
            # _fleet_publish tick during the (slow) storage walk must
            # not ship this pin to the coordinator — pins merge into
            # the directive irreversibly, and if no older instance is
            # deployable we pop the pin and keep serving cur. Only a
            # pin WE insert is provisional/poppable: a pre-existing pin
            # (e.g. merged from the directive while this replica still
            # serves it) is real and must neither vanish from published
            # status rows during the walk nor be deleted on failure
            inserted = cur.id not in self._pinned
            if inserted:
                self._pinned[cur.id] = "manual"
                self._pins_provisional.add(cur.id)
        try:
            await asyncio.to_thread(self._load, None)
        except Exception:  # noqa: BLE001 - nothing older deployable
            if inserted:
                with self._lock:
                    self._pinned.pop(cur.id, None)
                    self._pins_provisional.discard(cur.id)
            log.exception("fleet rollback: no older deployable "
                          "instance; keeping %s live", cur.id)
            return None
        # the reload retained the PINNED instance as "previous" and
        # opened a watch on the restored one — both wrong for a
        # rollback (the hedge/swap-back target must never be the model
        # we just pinned); drop them
        with self._lock:
            self._pins_provisional.discard(cur.id)
            self._previous = None
            self._rollbacks["manual"] = \
                self._rollbacks.get("manual", 0) + 1
            restored = self.instance
        self._watch = None
        log.warning("fleet rollback via store: %s pinned, restored %s",
                    cur.id, restored.id)
        return restored.id

    async def _start_heartbeat(self, app) -> None:
        if envknobs.env_str("PIO_WORKER_HEARTBEAT_FILE", "",
                            lower=False):
            self._hb_task = asyncio.get_running_loop().create_task(
                self._heartbeat_loop())

    async def _stop_heartbeat(self, app) -> None:
        task, self._hb_task = self._hb_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    async def _heartbeat_loop(self) -> None:
        """Supervised-replica liveness (the event-server pattern):
        touch the heartbeat file so a wedged event loop — not just a
        dead process — is detected and this replica relaunched. The
        touch is disk I/O, shipped off-loop."""
        from ..parallel import supervisor

        interval = max(0.05, envknobs.env_ms(
            "PIO_WORKER_HEARTBEAT_MS", 1000.0, lo_ms=20.0) / 2.0)
        while True:
            await asyncio.to_thread(supervisor.beat)
            await asyncio.sleep(interval)

    async def handle_reload(self, request: web.Request) -> web.Response:
        """Hot-swap to the latest completed instance (reference: /reload →
        MasterActor ! ReloadServer), or — with ``?instance=<id>`` — to an
        EXPLICIT engine instance (operator rollback/pin-override to a
        known-good version; the target is verified and validated like
        any other swap, and un-pinned on success). A failed reload NEVER
        takes down serving: the last-good model stays live and the
        server enters degraded mode (visible on /status and /readyz)
        until a reload succeeds.

        Serialized: two concurrent /reload calls race `_load` (two
        warm-ups, interleaved compile-gauge swaps, last-writer-wins on
        the deployment) — the loser gets 409 and retries once the
        winner finishes."""
        target = request.query.get("instance") or None
        if self.fleet_mode:
            # a reload through the front would land on ONE replica and
            # be silently reverted by the next directive sync — refuse
            # loudly instead of pretending: rollouts are staged by the
            # coordinator (retrain → canary → promote), rollbacks via
            # POST /rollback (fleet-wide)
            return web.json_response(
                {"message": "fleet mode: model rollout is coordinator-"
                            "driven — retrain to stage a canary, POST "
                            "/rollback for a fleet rollback",
                 "engineInstanceId":
                     self.instance.id if self.instance else None},
                status=409)
        if self._reload_lock.locked():
            self._reload_conflicts += 1
            return web.json_response(
                {"message": "reload already in progress",
                 "engineInstanceId":
                     self.instance.id if self.instance else None},
                status=409)
        async with self._reload_lock:
            try:
                await asyncio.to_thread(self._load, target)
            except Exception as e:  # noqa: BLE001
                if isinstance(e, SwapValidationError):
                    with self._lock:
                        self._validate_failures += 1
                self._degraded_reason = (
                    f"reload failed at "
                    f"{_dt.datetime.now(_dt.timezone.utc).isoformat()}: {e}; "
                    "serving last-good model")
                log.exception("reload failed; continuing on last-good model")
                return web.json_response(
                    {"message": str(e), "degraded": True,
                     "engineInstanceId":
                         self.instance.id if self.instance else None},
                    status=500)
            if target:
                # the operator explicitly chose (and the gate passed)
                # this version — a standing pin no longer applies
                with self._lock:
                    self._pinned.pop(target, None)
        self._degraded_reason = None
        return web.json_response(
            {"message": "Reloaded", "engineInstanceId": self.instance.id}
        )

    # -- graceful drain ----------------------------------------------------
    async def drain_then_stop(self, stopper=None) -> None:
        """SIGTERM / /stop sequence: flip /readyz to 503 FIRST (load
        balancers rotate this replica out and new arrivals shed 503 at
        admission), wait for every ACCEPTED in-flight query up to
        PIO_DRAIN_DEADLINE_MS, then stop — stragglers past the budget
        are failed by shutdown (batch-queue cleanup + connection
        close) rather than holding the process open."""
        with self._adm_lock:
            if self._draining:
                return      # second SIGTERM / /stop: first drain owns it
            self._draining = True
        log.info("draining: readyz → 503, waiting for in-flight queries "
                 "(budget %.0f ms)", self.drain_deadline_ms)
        if stopper is None:
            stopper = self.app.get("stopper")
        await asyncio.sleep(0.05)   # let the triggering response flush
        t_end = _time.monotonic() + self.drain_deadline_ms / 1000.0
        while _time.monotonic() < t_end:
            with self._adm_lock:
                pending = self._adm_pending
            if pending == 0:
                break
            await asyncio.sleep(0.02)
        with self._adm_lock:
            stragglers = self._adm_pending
        if stragglers:
            with self._adm_lock:
                self._drain_stragglers = stragglers
            log.warning("drain deadline (%.0f ms) expired with %d "
                        "query(ies) unfinished; failing them",
                        self.drain_deadline_ms, stragglers)
        else:
            log.info("drain complete: all accepted queries answered")
        if stopper is not None:
            stopper()

    def finalize_shutdown(self, grace: float = 2.0) -> None:
        """After the event loop exits. Worker threads can't be killed,
        so: cancel everything still queued, give RUNNING orphans a
        short grace, then hard-exit rather than letting a hung model
        call block interpreter shutdown forever (the SIGKILL-after-
        drain contract a supervisor would apply, applied to
        ourselves)."""
        self._query_executor.shutdown(wait=False, cancel_futures=True)
        t_end = _time.monotonic() + grace
        while _time.monotonic() < t_end:
            with self._adm_lock:
                if self._adm_pending <= 0:
                    return
            _time.sleep(0.02)
        with self._adm_lock:
            left = self._adm_pending
        log.warning("%d query worker(s) still running after shutdown "
                    "grace; exiting anyway", left)
        os._exit(0)

    async def handle_stop(self, request: web.Request) -> web.Response:
        if self.fleet_mode:
            # through the front this lands on ONE replica, which would
            # drain and exit cleanly — and a clean exit is NOT
            # relaunched by the supervisor, so `pio undeploy` against a
            # fleet would silently shrink it by one replica while
            # reporting success. Refuse loudly: the fleet stops as a
            # unit (SIGTERM to the `pio deploy --replicas` front
            # process drains every replica)
            return web.json_response(
                {"message": "fleet mode: a single-replica stop would "
                            "silently shrink the fleet — stop the "
                            "whole fleet by terminating the `pio "
                            "deploy --replicas` front process "
                            "(SIGTERM)"},
                status=409)
        log.info("stop requested")
        with self._adm_lock:
            draining = self._draining
        if draining:
            return web.json_response({"message": "Already draining."})
        asyncio.get_running_loop().create_task(
            self.drain_then_stop(request.app["stopper"]))
        return web.json_response({"message": "Shutting down."})

    async def handle_plugins(self, request: web.Request) -> web.Response:
        return web.json_response({"plugins": self.plugins.plugin_names()})


def _device_attachment() -> str:
    """Human label for where the accelerator lives (probe output)."""
    try:
        import jax

        d = jax.devices()[0]
        return f"{d.platform}:{getattr(d, 'device_kind', '?')}"
    except Exception:  # noqa: BLE001
        return "unknown"


def run_engine_server(server: EngineServer, host: str = "0.0.0.0",
                      port: int = 8000, probe_latency: bool = False):
    """Blocking entry point (reference: CreateServer.main)."""
    loop = asyncio.new_event_loop()
    stop_event = asyncio.Event()
    server.app["stopper"] = stop_event.set

    async def main():
        from ..common import ssl_context_from_env

        tls = ssl_context_from_env()
        # short shutdown_timeout: stragglers already got the full drain
        # window; aiohttp's default 60 s grace would triple-wait them
        runner = web.AppRunner(server.app, shutdown_timeout=5.0)
        await runner.setup()
        site = web.TCPSite(runner, host, port, ssl_context=tls)
        await site.start()
        log.info("Engine Server listening on %s:%d", host, port)
        # SIGTERM/SIGINT → graceful drain (readyz 503 first, in-flight
        # queries answered, then exit) — what a rolling restart sends
        import signal as _signal

        rloop = asyncio.get_running_loop()

        def _on_term(signame: str) -> None:
            log.info("%s received: graceful drain", signame)
            rloop.create_task(server.drain_then_stop(stop_event.set))

        for signame in ("SIGTERM", "SIGINT"):
            try:
                rloop.add_signal_handler(
                    getattr(_signal, signame), _on_term, signame)
            except (NotImplementedError, RuntimeError, AttributeError):
                pass    # platform without unix signal support
        if probe_latency:
            scheme = "https" if tls else "http"
            try:
                await asyncio.to_thread(
                    server.probe_and_record, f"{scheme}://127.0.0.1:{port}")
            except Exception:  # noqa: BLE001 - diagnostics must not kill serving
                log.exception("startup latency probe failed; serving anyway")
        await stop_event.wait()
        await runner.cleanup()

    loop.run_until_complete(main())
    server.finalize_shutdown()
