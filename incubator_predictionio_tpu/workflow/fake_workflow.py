"""FakeWorkflow — minimal in-process engine for workflow tests.

Reference: core/.../workflow/FakeWorkflow.scala (FakeEngine/FakeRun used by
unit tests to exercise workflow plumbing without a real engine). Paired
with the MEMORY storage backend this gives fully hermetic workflow tests.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from ..controller import Algorithm, DataSource, Engine, FirstServing, IdentityPreparator


@dataclasses.dataclass
class FakeTrainingData:
    values: list


class FakeDataSource(DataSource):
    """Yields the values it was constructed with; records calls."""

    def __init__(self, params=None):
        super().__init__(params)
        self.read_count = 0
        self.values = (params or {}).get("values", [1, 2, 3]) if isinstance(params, dict) else [1, 2, 3]

    def read_training(self, ctx) -> FakeTrainingData:
        self.read_count += 1
        return FakeTrainingData(list(self.values))

    def read_eval(self, ctx):
        td = self.read_training(ctx)
        qa = [({"q": v}, {"a": v}) for v in td.values]
        return [(td, None, qa)]


class FakeAlgorithm(Algorithm):
    """model = sum of values; predict echoes query + model."""

    def train(self, ctx, pd: FakeTrainingData):
        return {"total": sum(pd.values)}

    def predict(self, model, query):
        return {"echo": query.get("q"), "total": model["total"]}


def fake_engine() -> Engine:
    return Engine(
        data_source_class=FakeDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"": FakeAlgorithm},
        serving_class=FirstServing,
    )


def fake_run(ctx=None, run_fn: Callable[[Engine], Any] | None = None):
    """Run a quick train through the real CoreWorkflow (reference:
    FakeRun)."""
    from ..controller.engine import EngineParams
    from .context import WorkflowContext
    from .core_workflow import run_train

    engine = fake_engine()
    ctx = ctx or WorkflowContext()
    return run_train(engine, EngineParams(), ctx, engine_factory_name="fake")
