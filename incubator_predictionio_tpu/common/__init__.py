"""Shared server utilities (reference: common/src/main/scala/.../predictionio/
{KeyAuthentication,SSLConfiguration}.scala) plus the cross-stack
resilience layer (resilience.py, faultinject.py)."""

from .resilience import (
    CircuitBreaker,
    CircuitOpenError,
    RetryBudgetExceeded,
    RetryPolicy,
    breaker_snapshots,
    is_retryable,
    resilient_urlopen,
)
from .ssl_config import ssl_context_from_env

__all__ = [
    "CircuitBreaker", "CircuitOpenError", "RetryBudgetExceeded",
    "RetryPolicy", "breaker_snapshots", "is_retryable",
    "resilient_urlopen", "ssl_context_from_env",
]
