"""Shared server utilities (reference: common/src/main/scala/.../predictionio/
{KeyAuthentication,SSLConfiguration}.scala)."""

from .ssl_config import ssl_context_from_env

__all__ = ["ssl_context_from_env"]
