"""Deterministic fault injection for chaos tests — no flaky network needed.

The storage transports consult this module at named *fault points*
(e.g. ``http.call``, ``http.stream``, ``hbase.rpc``, ``es.request``)
before touching the wire. The active fault plan comes from the
``PIO_FAULT_SPEC`` environment variable, so chaos scenarios work
identically in-process, across subprocesses, and in CI:

    PIO_FAULT_SPEC="rule[;rule...]"
    rule = <point-pattern>:<mode>:<count>[:<param>]

- ``point-pattern`` — fnmatch pattern against the fault-point name
  (``http.call``, ``http.*``, ``*``).
- ``fail:N`` — the first N matching calls raise :class:`InjectedFault`
  (a ``ConnectionError``, so it classifies as retryable exactly like a
  real torn socket).
- ``latency:N:SECONDS`` — the first N matching calls sleep SECONDS
  before proceeding.
- ``drop:N:AFTER`` — streaming points only: the first N matching
  streams raise :class:`InjectedFault` after AFTER items have been
  produced (a connection dropped mid-stream).
- ``crash:N`` — the N-th matching call kills the process dead:
  SIGKILL to self (``os._exit(137)`` fallback), no Python cleanup, no
  atexit, no flushing beyond what already reached the OS — the
  deterministic `kill -9` used by the WAL crash-recovery harness.
  Unlike ``fail``, the count selects WHICH call crashes (a process
  only crashes once): ``ingest.commit:crash:3`` survives two group
  commits and dies inside the third.
- ``oserr:N:ERRNO`` — the first N matching calls raise a plain
  ``OSError(ERRNO, ...)`` (NOT the retryable :class:`InjectedFault`
  class): the deterministic disk fault (``oserr:1:28`` = ENOSPC) used
  by the append-error shed tests, where the failure must classify as
  resource exhaustion rather than a torn connection.
- ``at:MS[:SUBMODE[:PARAM]]`` — time-scheduled arming (the soak
  driver's fault-timeline mode): instead of counting calls, the rule
  arms a monotonic offset. The FIRST matching call at or after MS
  milliseconds past plan arming fires SUBMODE (``fail`` by default;
  ``crash``; ``latency`` with PARAM = seconds to sleep; ``oserr``
  with PARAM = errno), then the rule is spent. The clock
  starts when the plan is armed in THIS process: the first fault-point
  consult that sees the current spec value (``reset()`` + a consult
  re-arms it). ``ingest.commit:at:4000:crash`` = SIGKILL inside the
  first group commit 4 s into serving, wherever that call lands.

Counts are per-rule and deterministic: "fail first 2 calls" means
exactly the first two matching calls in this process fail, then the
rule is spent. ``reset()`` re-arms the plan (tests call it after
setting the env var); parsing is cached and re-checked against the env
value on every fault point, so flipping the variable mid-process takes
effect immediately.
"""

from __future__ import annotations

import fnmatch
import os
import threading
import time
from typing import Optional

__all__ = ["InjectedFault", "arm", "fault_point", "stream_fault",
           "reset", "active_spec"]

ENV_VAR = "PIO_FAULT_SPEC"


class InjectedFault(ConnectionError):
    """A deterministic, injected transport failure (retryable class)."""


class _Rule:
    __slots__ = ("pattern", "mode", "remaining", "param", "at_s",
                 "submode")

    def __init__(self, pattern: str, mode: str, count: int, param: float,
                 at_s: float = 0.0, submode: str = "fail"):
        self.pattern = pattern
        self.mode = mode
        self.remaining = count
        self.param = param
        self.at_s = at_s          # "at" rules: offset past plan arming
        self.submode = submode    # "at" rules: what fires at the offset


_AT_SUBMODES = ("fail", "crash", "latency", "oserr")


def _parse_at(raw: str, parts: list[str]) -> _Rule:
    """``point:at:MS[:SUBMODE[:PARAM]]`` — monotonic-offset arming."""
    try:
        at_ms = float(parts[2])
    except ValueError as e:
        raise ValueError(f"{ENV_VAR}: bad offset in {raw!r}") from e
    if at_ms < 0:
        raise ValueError(f"{ENV_VAR}: negative offset in {raw!r}")
    submode = parts[3].lower() if len(parts) > 3 else "fail"
    if submode not in _AT_SUBMODES:
        raise ValueError(
            f"{ENV_VAR}: unknown at-submode {submode!r} in {raw!r} "
            f"(want one of {'/'.join(_AT_SUBMODES)})")
    param = 0.0
    if len(parts) > 4:
        try:
            param = float(parts[4])
        except ValueError as e:
            raise ValueError(f"{ENV_VAR}: bad param in {raw!r}") from e
    elif submode in ("latency", "oserr"):
        raise ValueError(f"{ENV_VAR}: at-submode {submode!r} needs a "
                         f"param ({raw!r})")
    return _Rule(parts[0], "at", 1, param, at_s=at_ms / 1000.0,
                 submode=submode)


def _parse(spec: str) -> list[_Rule]:
    rules: list[_Rule] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 3:
            raise ValueError(
                f"{ENV_VAR}: malformed rule {raw!r} "
                "(want point:mode:count[:param])")
        pattern, mode, count = parts[0], parts[1].lower(), parts[2]
        if mode == "at":
            rules.append(_parse_at(raw, parts))
            continue
        if mode not in ("fail", "latency", "drop", "crash", "oserr"):
            raise ValueError(f"{ENV_VAR}: unknown fault mode {mode!r}")
        try:
            n = int(count)
        except ValueError as e:
            raise ValueError(f"{ENV_VAR}: bad count in {raw!r}") from e
        param = 0.0
        if len(parts) > 3:
            try:
                param = float(parts[3])
            except ValueError as e:
                raise ValueError(f"{ENV_VAR}: bad param in {raw!r}") from e
        elif mode in ("latency", "drop", "oserr"):
            raise ValueError(f"{ENV_VAR}: mode {mode!r} needs a param "
                             f"({raw!r})")
        rules.append(_Rule(pattern, mode, n, param))
    return rules


_lock = threading.Lock()
_cached_spec: Optional[str] = None
_rules: list[_Rule] = []
_armed_at: float = 0.0   # monotonic instant the current plan armed


def _active_rules() -> list[_Rule]:
    """Current rule set, re-parsed whenever the env value changes.
    A changed value re-arms all counts (it is a NEW plan) and restarts
    the ``at``-mode offset clock."""
    global _cached_spec, _rules, _armed_at
    spec = os.environ.get(ENV_VAR, "")
    if spec != _cached_spec:
        _rules = _parse(spec)
        _cached_spec = spec
        _armed_at = time.monotonic()
    return _rules


def reset() -> None:
    """Forget the cached plan so counts re-arm from the env value."""
    global _cached_spec, _rules
    with _lock:
        _cached_spec = None
        _rules = []


def arm() -> None:
    """Parse the current plan NOW, starting the ``at``-mode offset
    clock, instead of waiting for the first fault-point consult.
    Servers call this at construction so scheduled offsets measure
    from "server up", not "first request". No-op when chaos is off."""
    if not os.environ.get(ENV_VAR):
        return
    with _lock:
        _active_rules()


def active_spec() -> str:
    """The raw spec currently in force ('' when chaos is off)."""
    return os.environ.get(ENV_VAR, "")


def _crash(name: str) -> None:  # pragma: no cover — the process dies
    """Deterministic `kill -9` of THIS process: no Python-level
    cleanup runs, so whatever the code under test had flushed to the
    OS is exactly what a recovery pass gets to see."""
    import signal

    try:
        os.kill(os.getpid(), signal.SIGKILL)
    except (OSError, AttributeError, ValueError):
        pass
    os._exit(137)


def fault_point(name: str) -> None:
    """Declare a unit of wire work. Applies ``fail``, ``latency`` and
    ``crash`` rules matching ``name``; no-op (one dict lookup) when
    chaos is off."""
    if not os.environ.get(ENV_VAR):
        return
    delay = 0.0
    boom: Optional[Exception] = None
    die = False
    with _lock:
        for rule in _active_rules():
            if rule.remaining <= 0 or rule.mode == "drop":
                continue
            if not fnmatch.fnmatch(name, rule.pattern):
                continue
            if rule.mode == "at":
                # time-scheduled arming: the first matching call at or
                # past the offset fires the submode, earlier calls pass
                # untouched (and never consume the rule)
                if time.monotonic() - _armed_at < rule.at_s:
                    continue
                rule.remaining -= 1
                if rule.submode == "crash":
                    die = True
                    break
                if rule.submode == "fail":
                    boom = InjectedFault(
                        f"injected scheduled fault at {name!r} "
                        f"({ENV_VAR})")
                    break
                if rule.submode == "oserr":
                    boom = OSError(
                        int(rule.param),
                        f"injected scheduled disk fault at {name!r} "
                        f"({ENV_VAR})")
                    break
                delay += rule.param          # latency
                continue
            rule.remaining -= 1
            if rule.mode == "crash":
                # the count selects WHICH call crashes: survive the
                # first N-1 matches, die inside the N-th
                if rule.remaining <= 0:
                    die = True
                    break
                continue
            if rule.mode == "fail":
                boom = InjectedFault(
                    f"injected fault at {name!r} ({ENV_VAR})")
                break
            if rule.mode == "oserr":
                boom = OSError(
                    int(rule.param),
                    f"injected disk fault at {name!r} ({ENV_VAR})")
                break
            delay += rule.param
    if die:
        _crash(name)
    if delay > 0:
        time.sleep(delay)
    if boom is not None:
        raise boom


class StreamFault:
    """Armed mid-stream drop: call :meth:`on_item` once per produced
    item; raises :class:`InjectedFault` when the drop threshold hits."""

    def __init__(self, name: str, after: int):
        self.name = name
        self.after = after
        self._produced = 0

    def on_item(self) -> None:
        self._produced += 1
        if self._produced > self.after:
            raise InjectedFault(
                f"injected mid-stream drop at {self.name!r} after "
                f"{self.after} item(s) ({ENV_VAR})")


def stream_fault(name: str) -> Optional[StreamFault]:
    """Arm a ``drop`` rule for one stream (consumes one count), or
    ``None`` when no drop rule matches."""
    if not os.environ.get(ENV_VAR):
        return None
    with _lock:
        for rule in _active_rules():
            if (rule.mode == "drop" and rule.remaining > 0
                    and fnmatch.fnmatch(name, rule.pattern)):
                rule.remaining -= 1
                return StreamFault(name, int(rule.param))
    return None
