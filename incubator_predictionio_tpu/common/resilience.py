"""Unified resilience layer: retries, circuit breakers, resilient I/O.

Large-scale serving treats partial failure as the steady state
(PAPERS.md 1605.08695 builds recoverable state into the dataflow core;
the Spark lineage this port descends from inherited retry/recovery from
RDDs — the Spark-free JAX backend must rebuild that net explicitly).
Every wire client in ``data/storage/`` routes its socket work through
this module so one policy governs the whole stack:

- :class:`RetryPolicy` — exponential backoff with FULL jitter
  (delay ~ U(0, min(cap, base·2^attempt))), a per-attempt timeout cap,
  an overall deadline budget, and retryable-vs-fatal classification.
- :class:`CircuitBreaker` — per-endpoint closed → open → half-open with
  state/transition counters; open circuits fail fast with
  :class:`CircuitOpenError` carrying a ``retry_after`` hint the servers
  surface as HTTP 503 + ``Retry-After``.
- :func:`resilient_urlopen` — the ONE place storage backends are
  allowed to call ``urllib.request.urlopen`` (a guard test enforces
  this), so every HTTP-speaking backend gets fault injection
  (``common/faultinject.py``), retries and breaker accounting for free.

Breakers register themselves in a process-wide registry so ``pio
status``, the storage registry, and the serving /readyz endpoint can
report per-backend circuit state without owning the breaker objects.
"""

from __future__ import annotations

import http.client as _http_client
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Iterable, Optional

from . import deadline as _deadline
from . import faultinject, telemetry

__all__ = [
    "CircuitBreaker", "CircuitOpenError", "RetryPolicy", "RetryBudgetExceeded",
    "all_breakers", "breaker_snapshots", "is_retryable", "resilient_urlopen",
    "retry_after_jitter",
]

_jitter_rng = random.Random()


def retry_after_jitter(base: float,
                       rng: Optional[random.Random] = None) -> int:
    """Full-jittered integer ``Retry-After`` seconds for a 503 shed.

    A constant Retry-After synchronizes every SDK that honoured it into
    one retry wave exactly N seconds later — the thundering herd the
    shed was meant to prevent. Same cure as :meth:`RetryPolicy.backoff`:
    full jitter, here ``1 + U(0, 2·base)`` truncated to whole seconds
    (the header is integer delta-seconds per RFC 9110), so the mean
    stays ~``1 + base`` while the herd spreads over ``[1, 2·base + 1]``.
    """
    spread = (rng or _jitter_rng).uniform(0.0, 2.0 * max(0.0, base))
    return 1 + int(spread)


# ---------------------------------------------------------------------------
# telemetry: every wire transport reports through these two families
# (labelled by the transport's fault point, e.g. "es.request",
# "http.call", "hbase.rpc"), and the breaker registry doubles as the
# live source of the per-endpoint breaker-state gauge.
# ---------------------------------------------------------------------------

STORAGE_OP_SECONDS = telemetry.registry().histogram(
    "pio_storage_op_seconds",
    "Storage transport operation latency per backend endpoint "
    "(one observation per attempt, including failed attempts)",
    ("backend",))
STORAGE_OP_ERRORS = telemetry.registry().counter(
    "pio_storage_op_errors_total",
    "Storage transport operation failures per backend endpoint",
    ("backend",))

#: breaker-state gauge encoding (Prometheus has no string values)
_BREAKER_STATE_CODE = {"closed": 0, "half-open": 1, "open": 2}


def _breaker_collector():
    """Render-time gauge family from the live breaker registry —
    breakers are owned by storage clients (and vanish with them), so
    their state is collected, not recorded."""
    fam = telemetry.GaugeFamily(
        "pio_storage_breaker_state",
        "Circuit breaker state per endpoint (0=closed, 1=half-open, "
        "2=open)", ("endpoint",))
    fails = telemetry.GaugeFamily(
        "pio_storage_breaker_failures_total",
        "Connectivity failures accounted to each endpoint breaker",
        ("endpoint",))
    for snap in breaker_snapshots():
        fam.labels(snap["name"]).set(
            _BREAKER_STATE_CODE.get(snap["state"], -1))
        fails.labels(snap["name"]).set(snap["failure"])
    return [fam, fails]


telemetry.registry().register_collector("resilience.breakers",
                                        _breaker_collector)


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------

#: HTTP statuses that signal a transient server/infrastructure condition.
#: 429/503 are explicit backpressure; 502/504 are proxy-path failures.
RETRYABLE_HTTP = frozenset({429, 502, 503, 504})


def is_retryable(exc: BaseException) -> bool:
    """Default retryable-vs-fatal classification.

    Retryable: anything that can heal on its own — socket-level failures
    (``OSError`` covers refused/reset/unreachable/timeouts and the
    injected faults, which subclass ``ConnectionError``), torn HTTP
    framing, and the transient HTTP statuses. Fatal: everything else
    (4xx protocol errors, server-side application exceptions, bugs).
    """
    if isinstance(exc, CircuitOpenError):
        return False            # fail fast: the breaker already said no
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in RETRYABLE_HTTP
    if isinstance(exc, (urllib.error.URLError, _http_client.HTTPException,
                        OSError, TimeoutError)):
        return True
    retriable = getattr(exc, "retriable", None)
    if retriable is not None:   # protocol errors may self-classify
        return bool(retriable)
    return False


class RetryBudgetExceeded(Exception):
    """Deadline budget ran out before an attempt could start; carries
    the last attempt's error as ``__cause__``."""


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

class RetryPolicy:
    """Exponential backoff with full jitter under a deadline budget.

    ``call(fn)`` runs ``fn`` up to ``max_attempts`` times. After a
    retryable failure it sleeps ``U(0, min(max_delay, base_delay ·
    2^attempt))`` — full jitter, so a fleet of clients retrying the same
    dead store doesn't synchronize into waves. The overall ``deadline``
    is a budget across ALL attempts and sleeps: once spent, the last
    error is raised rather than starting another attempt.

    ``per_attempt_timeout`` is advisory — callers that take a timeout
    (urlopen, sockets) cap theirs with :meth:`attempt_timeout` so one
    black-holed attempt can't eat the whole budget.
    """

    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, deadline: float = 15.0,
                 per_attempt_timeout: Optional[float] = None,
                 retryable: Callable[[BaseException], bool] = is_retryable,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = float(deadline)
        self.per_attempt_timeout = per_attempt_timeout
        self.retryable = retryable
        self._sleep = sleep
        self._rng = rng or random.Random()

    def backoff(self, attempt: int) -> float:
        """Jittered delay before retry number ``attempt`` (0-based).
        The exponent is clamped so huge attempt counts (operator sets
        RETRY_ATTEMPTS in the thousands) can't overflow float range."""
        cap = min(self.max_delay, self.base_delay * (2 ** min(attempt, 62)))
        return self._rng.uniform(0.0, cap)

    def attempt_timeout(self, default: float) -> float:
        """Per-attempt timeout: the caller's default, capped by the
        policy's explicit per-attempt cap (when one was configured).
        The deadline budget deliberately does NOT truncate an in-flight
        attempt — it only gates whether ANOTHER attempt may start, so a
        legitimately slow single operation (a multi-GB model blob
        transfer) keeps its full configured TIMEOUT; worst-case total
        time is bounded by deadline + one attempt timeout.

        A request-scoped deadline (``common/deadline.py`` contextvar —
        storage egress running inside a served query) is the exception:
        it DOES truncate the attempt, because past that point nobody is
        waiting for the answer. A small floor keeps a nearly-spent
        budget from degenerating into timeout=0 (invalid for sockets)."""
        t = default
        if self.per_attempt_timeout is not None:
            t = min(t, self.per_attempt_timeout)
        dl = _deadline.current()
        if dl is not None:
            t = min(t, max(dl.remaining(), 0.05))
        return t

    def call(self, fn: Callable[[], object], *,
             breaker: Optional["CircuitBreaker"] = None,
             on_retry: Optional[Callable[[BaseException, int], None]] = None,
             retryable: Optional[Callable[[BaseException], bool]] = None):
        """Run ``fn`` under this policy, optionally through ``breaker``
        (checked before every attempt, outcome recorded after).
        ``retryable`` overrides the policy's classifier for THIS call
        (e.g. "never retry" for non-idempotent requests).

        Breaker accounting is always the CONNECTIVITY classification
        (:func:`is_retryable`), independent of the retry decision: a
        fatal application error from an endpoint that answered records
        a breaker SUCCESS (the endpoint is healthy), and a connectivity
        failure records a breaker failure even when the caller chose
        not to retry it."""
        classify = retryable or self.retryable
        started = time.monotonic()
        # Request-scoped deadline (serving a query): the retry budget
        # is capped to the request's remaining balance, and an already-
        # spent budget refuses to start at all — a dead store must not
        # hold a query thread for this policy's full 15 s default when
        # the client's 504 fires in 200 ms.
        dl = _deadline.current()
        budget = self.deadline if dl is None \
            else min(self.deadline, dl.remaining())
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if dl is not None:
                dl.check("storage egress")
            if breaker is not None:
                breaker.check()
            try:
                result = fn()
            except BaseException as e:  # noqa: BLE001 — reclassified below
                if breaker is not None and not isinstance(e, CircuitOpenError):
                    if is_retryable(e):
                        breaker.record_failure()
                    else:
                        breaker.record_success()
                if not classify(e) or attempt == self.max_attempts - 1:
                    raise
                last = e
                delay = self.backoff(attempt)
                if time.monotonic() - started + delay > budget:
                    raise RetryBudgetExceeded(
                        f"retry deadline budget ({budget:.3g}s) "
                        f"exhausted after {attempt + 1} attempt(s): {e}"
                    ) from e
                if on_retry is not None:
                    on_retry(e, attempt)
                if isinstance(e, urllib.error.HTTPError):
                    # drain the abandoned response so retried 429/5xx
                    # answers don't pin sockets until cyclic GC
                    try:
                        e.close()
                    except Exception:
                        pass
                if delay > 0:
                    self._sleep(delay)
                continue
            if breaker is not None:
                breaker.record_success()
            return result
        raise last  # pragma: no cover — loop always raises or returns


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitOpenError(ConnectionError):
    """Fail-fast refusal: the endpoint's circuit is open.

    Subclasses ``ConnectionError`` so existing ``except OSError``
    transport plumbing treats it as a connectivity failure, while
    servers can still catch the specific type to shed load (503 +
    ``Retry-After: retry_after``).
    """

    def __init__(self, name: str, retry_after: float):
        super().__init__(
            f"circuit breaker open for {name}; service unreachable — "
            f"retry after {retry_after:.1f}s")
        self.breaker_name = name
        self.retry_after = max(0.0, retry_after)


class CircuitBreaker:
    """Per-endpoint closed → open → half-open breaker.

    ``failure_threshold`` consecutive failures trip the circuit OPEN;
    calls then fail fast (no socket work) until ``reset_timeout``
    elapses, after which ONE probe call is let through HALF-OPEN — its
    success re-closes the circuit, its failure re-opens it for another
    ``reset_timeout``. Counters track every transition for operability
    (`pio status`, /readyz, the storage registry report them).
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout = float(reset_timeout)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started_at = 0.0
        self.counters = {"success": 0, "failure": 0, "rejected": 0,
                         "opened": 0, "half_opened": 0, "closed": 0}
        _register_breaker(self)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        # caller holds the lock
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout):
            self._state = self.HALF_OPEN
            self._probe_inflight = False
            self.counters["half_opened"] += 1

    def check(self) -> bool:
        """Gate an attempt: raises :class:`CircuitOpenError` when open
        (or when half-open and the single probe slot is taken). Returns
        True when THIS caller took the half-open probe slot (so it can
        release it if it ends with no verdict), False for a plain
        closed-state pass. A probe whose owner never reported an
        outcome (died mid-call, abandoned generator) expires after
        ``reset_timeout`` so the circuit can never wedge permanently
        half-open."""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return False
            if self._state == self.HALF_OPEN:
                stale = (self._probe_inflight
                         and self._clock() - self._probe_started_at
                         >= self.reset_timeout)
                if not self._probe_inflight or stale:
                    self._probe_inflight = True
                    self._probe_started_at = self._clock()
                    return True
            self.counters["rejected"] += 1
            remaining = self.reset_timeout - (self._clock() - self._opened_at)
            raise CircuitOpenError(self.name, remaining)

    def release_probe(self) -> None:
        """Release an unreported probe slot without biasing the state —
        for attempts that ended with no verdict (e.g. a scan generator
        dropped mid-iteration by its consumer)."""
        with self._lock:
            self._probe_inflight = False

    def record_success(self) -> None:
        with self._lock:
            self.counters["success"] += 1
            self._consecutive_failures = 0
            if self._state != self.CLOSED:
                self._state = self.CLOSED
                self.counters["closed"] += 1
            self._probe_inflight = False

    def record_failure(self) -> None:
        with self._lock:
            self.counters["failure"] += 1
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN:
                # the probe failed: straight back to open
                self._state = self.OPEN
                self._opened_at = self._clock()
                self.counters["opened"] += 1
                self._probe_inflight = False
            elif (self._state == self.CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self._clock()
                self.counters["opened"] += 1

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open()
            return {
                "name": self.name,
                "state": self._state,
                "consecutiveFailures": self._consecutive_failures,
                **{k: v for k, v in self.counters.items()},
            }


# -- process-wide breaker registry (reporting only: weakly held, so a
# closed storage client's breakers vanish with it) --------------------------
import weakref as _weakref

_BREAKERS: "_weakref.WeakSet[CircuitBreaker]" = _weakref.WeakSet()
_BREAKERS_LOCK = threading.Lock()


def _register_breaker(b: CircuitBreaker) -> None:
    with _BREAKERS_LOCK:
        _BREAKERS.add(b)


def all_breakers() -> list[CircuitBreaker]:
    with _BREAKERS_LOCK:
        return sorted(_BREAKERS, key=lambda b: b.name)


def breaker_snapshots() -> list[dict]:
    """State of every live breaker in the process (``pio status``)."""
    return [b.snapshot() for b in all_breakers()]


# ---------------------------------------------------------------------------
# resilient urlopen — the storage backends' single HTTP egress point
# ---------------------------------------------------------------------------

#: Idempotent HTTP methods that are always safe to retry. Other methods
#: are retried only when the caller opts in (e.g. the HTTP storage
#:  backend's RPC POSTs, whose fault classification guarantees the
#: request never reached the application layer or is a wire-level POST
#: of an idempotent DAO read).
IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE", "OPTIONS"})


def resilient_urlopen(req: "urllib.request.Request | str", *,
                      timeout: float,
                      policy: Optional[RetryPolicy] = None,
                      breaker: Optional[CircuitBreaker] = None,
                      point: str = "http",
                      retry_non_idempotent: bool = False,
                      context=None):
    """``urllib.request.urlopen`` with fault injection, retry and breaker.

    This is the only place modules under ``data/storage/`` may reach
    urlopen (guard-tested), so every backend inherits the same behavior:
    ``faultinject.fault_point(point)`` fires before each attempt
    (deterministic chaos testing), retryable failures back off per
    ``policy``, and ``breaker`` accounts every outcome. Responses are
    returned open — the caller reads/closes them; ``HTTPError`` with a
    non-retryable status propagates to the caller unchanged.
    """
    if isinstance(req, str):
        req = urllib.request.Request(req)
    method = (req.get_method() or "GET").upper()
    retryable: Optional[Callable[[BaseException], bool]] = None
    if method not in IDEMPOTENT_METHODS and not retry_non_idempotent:
        def retryable(_e: BaseException) -> bool:
            return False
    op_lat = STORAGE_OP_SECONDS.labels(point)
    op_err = STORAGE_OP_ERRORS.labels(point)

    def attempt():
        faultinject.fault_point(point)
        t = (policy.attempt_timeout(timeout)
             if policy is not None else timeout)
        t0 = telemetry.timer_start()
        try:
            return urllib.request.urlopen(req, timeout=t, context=context)
        except BaseException:
            op_err.inc()
            raise
        finally:
            op_lat.observe_since(t0)

    if policy is None:
        # single attempt, but with the SAME breaker accounting as the
        # retried path (RetryPolicy.call owns that logic in one place)
        policy = _SINGLE_ATTEMPT
    return policy.call(attempt, breaker=breaker, retryable=retryable)


#: Degenerate policy for "no retries, still account the breaker".
_SINGLE_ATTEMPT = RetryPolicy(max_attempts=1)


def prop_float(props: dict, key: str, fallback: float) -> float:
    """Tolerant numeric property: unset or unparsable values fall back
    (a typo'd knob must degrade to the default, not crash a deploy)."""
    raw = props.get(key)
    if raw is None:
        return fallback
    try:
        return float(raw)
    except (TypeError, ValueError):
        return fallback


def policy_from_props(props: dict, prefix: str = "RETRY_",
                      **defaults) -> RetryPolicy:
    """Build a RetryPolicy from PIO_STORAGE_SOURCES_<N>_* properties:
    ``RETRY_ATTEMPTS``, ``RETRY_BASE`` (s), ``RETRY_MAX`` (s),
    ``RETRY_DEADLINE`` (s). Unset values fall back to ``defaults`` then
    the RetryPolicy constructor defaults."""
    def num(key, fallback):
        return prop_float(props, prefix + key, fallback)
    return RetryPolicy(
        max_attempts=int(num("ATTEMPTS", defaults.get("max_attempts", 4))),
        base_delay=num("BASE", defaults.get("base_delay", 0.05)),
        max_delay=num("MAX", defaults.get("max_delay", 2.0)),
        deadline=num("DEADLINE", defaults.get("deadline", 15.0)),
    )


def breaker_from_props(props: dict, name: str,
                       prefix: str = "BREAKER_") -> CircuitBreaker:
    """Build a CircuitBreaker from source properties:
    ``BREAKER_THRESHOLD`` (consecutive failures), ``BREAKER_RESET`` (s)."""
    return CircuitBreaker(
        name,
        failure_threshold=int(prop_float(props, prefix + "THRESHOLD", 5)),
        reset_timeout=prop_float(props, prefix + "RESET", 30.0),
    )
