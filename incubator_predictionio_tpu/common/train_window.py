"""Event-time training windows: one resolver for "train on the last
N days".

Production training is windowed — "last 90 days" — and the whole point
of time-bounded log generations (``data/api/event_log.py``) is that a
windowed read can skip cold generations without decoding them. This
module is the single place the window is *decided*, so every consumer
(``PEventStore.find_ratings`` / ``find_batches``, the partition-local
train feed, the manifest-chain loader) cuts the SAME window:

- ``PIO_TRAIN_WINDOW`` — a duration (``90d``, ``12h``, ``30m``,
  ``45s``), resolved against "now" at read time.
- ``PIO_TRAIN_WINDOW_START_US`` / ``PIO_TRAIN_WINDOW_UNTIL_US`` —
  absolute microsecond bounds; they OVERRIDE the duration form.

Gang determinism: ``pio train --window 90d`` resolves the duration to
an absolute start ONCE in the launching process and exports
``PIO_TRAIN_WINDOW_START_US`` before the gang spawns — each worker
inherits the absolute bound instead of re-reading its own clock, so
every partition cuts the log at the identical microsecond.

Explicit beats ambient: a caller that passes its own
``start_time``/``until_time`` is never second-guessed — the env window
only fills bounds the caller left as ``None`` (and only when it left
BOTH as None, so a deliberate open-ended query stays open-ended).
"""

from __future__ import annotations

import datetime as _dt
import re
from typing import Optional

from . import envknobs

__all__ = [
    "apply_window", "parse_duration_us", "resolve_us", "window_datetimes",
]

#: duration spellings accepted by PIO_TRAIN_WINDOW / PIO_EVENT_RETENTION
_DURATION = re.compile(r"^(?P<n>\d+(?:\.\d+)?)(?P<unit>[dhms])$")
_UNIT_US = {
    "d": 86_400_000_000,
    "h": 3_600_000_000,
    "m": 60_000_000,
    "s": 1_000_000,
}


def now_us() -> int:
    """Current wall-clock time in epoch microseconds (UTC)."""
    return int(_dt.datetime.now(_dt.timezone.utc).timestamp() * 1_000_000)


def parse_duration_us(raw: Optional[str]) -> Optional[int]:
    """``"90d"``/``"12h"``/``"30m"``/``"45s"`` → microseconds, or None
    for unset/malformed input (a typo'd window must degrade to the full
    scan, never crash a train or drop data on the floor)."""
    if not raw:
        return None
    m = _DURATION.match(raw.strip().lower())
    if m is None:
        return None
    try:
        us = int(float(m.group("n")) * _UNIT_US[m.group("unit")])
    except (ValueError, OverflowError):
        return None
    return us if us > 0 else None


def _env_us(name: str) -> Optional[int]:
    # -1 is the "unset" sentinel: epoch bounds are non-negative
    v = envknobs.env_int(name, -1, lo=-1)
    return None if v < 0 else v


def resolve_us(now: Optional[int] = None) -> tuple[Optional[int],
                                                   Optional[int]]:
    """The ambient training window as absolute microsecond bounds
    ``(start_us, until_us)`` — each None when unbounded on that side.

    Absolute knobs win over the duration knob; the duration is anchored
    at ``now`` (injectable for tests and for the one-shot CLI
    resolution that pins the gang's shared window)."""
    start = _env_us("PIO_TRAIN_WINDOW_START_US")
    until = _env_us("PIO_TRAIN_WINDOW_UNTIL_US")
    if start is None and until is None:
        dur = parse_duration_us(envknobs.env_str("PIO_TRAIN_WINDOW", ""))
        if dur is not None:
            start = (now if now is not None else now_us()) - dur
    return start, until


def _to_datetime(us: Optional[int]) -> Optional[_dt.datetime]:
    if us is None:
        return None
    return _dt.datetime.fromtimestamp(us / 1_000_000, _dt.timezone.utc)


def window_datetimes() -> tuple[Optional[_dt.datetime],
                                Optional[_dt.datetime]]:
    """:func:`resolve_us` as tz-aware datetimes — the type the event
    store's ``start_time``/``until_time`` parameters take."""
    start, until = resolve_us()
    return _to_datetime(start), _to_datetime(until)


def apply_window(start_time: Optional[_dt.datetime],
                 until_time: Optional[_dt.datetime]) -> tuple:
    """Fill an all-``None`` time range from the ambient window; any
    explicitly passed bound disables the ambient window entirely."""
    if start_time is not None or until_time is not None:
        return start_time, until_time
    return window_datetimes()
