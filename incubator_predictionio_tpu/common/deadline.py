"""Per-request deadline budgets that ride a contextvar through the stack.

A serving request gets ONE wall-clock budget at admission
(``PIO_QUERY_DEADLINE_MS`` default, ``X-Pio-Deadline-Ms`` header
override) and every layer underneath spends from it: the serving
stages (``Deployment.query`` checks between featurize/predict/serve),
and any storage egress mid-query (``resilience.RetryPolicy`` caps its
retry budget and per-attempt timeouts to the remaining balance, so a
retrying DAO call cannot outlive the request that issued it).

The budget travels as a :mod:`contextvars` value, so it crosses
``asyncio.to_thread`` / ``Context.run`` into worker threads exactly
like the trace context does, with zero plumbing through call
signatures. Threads can't be killed: an expired deadline makes the
NEXT spend-point raise :class:`DeadlineExceeded` — the worker frees
itself at the next stage boundary instead of running the query to
completion for a client that already got its 504.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import time
from typing import Iterator, Optional

__all__ = ["Deadline", "DeadlineExceeded", "current", "remaining",
           "running"]


class DeadlineExceeded(Exception):
    """The request's deadline budget is spent. Servers map this to
    HTTP 504 (the request was accepted but could not finish in time —
    distinct from the 503 admission shed, which never started work)."""

    def __init__(self, budget_ms: float, overrun_ms: float,
                 stage: str = ""):
        at = f" at {stage}" if stage else ""
        super().__init__(
            f"query deadline of {budget_ms:.0f}ms exceeded{at} "
            f"(overran by {overrun_ms:.0f}ms)")
        self.budget_ms = budget_ms
        self.overrun_ms = overrun_ms
        self.stage = stage


class Deadline:
    """Monotonic-clock budget: ``budget_ms`` from the moment of
    construction (admission time, NOT first-stage time — queue wait
    spends the budget too, which is what keeps a backed-up executor
    from serving answers nobody is waiting for)."""

    __slots__ = ("budget_ms", "_expires_at")

    def __init__(self, budget_ms: float):
        self.budget_ms = float(budget_ms)
        if not math.isfinite(self.budget_ms):
            # nan poisons every comparison below (expired would be
            # False forever) — refuse rather than mint a budget that
            # can never be spent
            raise ValueError(f"deadline budget must be finite, "
                             f"got {budget_ms!r}")
        self._expires_at = time.monotonic() + self.budget_ms / 1000.0

    def remaining(self) -> float:
        """Seconds left; clamped at 0.0 once spent."""
        return max(0.0, self._expires_at - time.monotonic())

    def remaining_ms(self) -> float:
        return self.remaining() * 1000.0

    def overrun_ms(self) -> float:
        """How far past the deadline we are (0.0 while still inside)."""
        return max(0.0, (time.monotonic() - self._expires_at) * 1000.0)

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self._expires_at

    def check(self, stage: str = "") -> None:
        """Spend-point: raise :class:`DeadlineExceeded` once expired."""
        if self.expired:
            raise DeadlineExceeded(self.budget_ms, self.overrun_ms(), stage)


_current: contextvars.ContextVar[Optional[Deadline]] = \
    contextvars.ContextVar("pio_query_deadline", default=None)


def current() -> Optional[Deadline]:
    """The deadline governing this context (None = unbounded)."""
    return _current.get()


def remaining() -> Optional[float]:
    """Seconds left in this context's budget, or None when unbounded."""
    dl = _current.get()
    return None if dl is None else dl.remaining()


@contextlib.contextmanager
def running(dl: Optional[Deadline]) -> Iterator[Optional[Deadline]]:
    """Install ``dl`` as the context's deadline for the duration.
    ``None`` is allowed (explicitly unbounded — shadows any outer
    deadline), which keeps call sites branch-free."""
    token = _current.set(dl)
    try:
        yield dl
    finally:
        _current.reset(token)
