"""One tolerant parser for ``PIO_*`` environment knobs.

Three subsystems grew three divergent copies of ``_env_int`` (the engine
server's accepted ``"1e3"`` and degraded on overflow, the ingest buffer's
rejected floats silently, the input pipeline's warned and clamped). They
are consolidated here with the semantics spelled out as flags, so every
caller states — and tests can assert — exactly what a malformed value
does:

- unset / empty         → ``default`` (always)
- unparsable / overflow → ``default``; ``warn=True`` additionally emits a
  ``UserWarning`` naming the variable and the value it fell back to
  (an operator typo must never crash a deploy or a train)
- ``float_ok=True``     → accept float spellings for integer knobs
  (``"1e3"`` → 1000); off by default, so ``PIO_FOO=3.5`` falls back
  rather than silently truncating
- ``lo``/``hi``         → clamp the PARSED value into a sane range
  (clamping is not an error: an operator asking for depth 10**9 gets the
  ceiling, not the default)

New knobs should come here instead of growing a fourth copy.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

__all__ = ["env_int", "env_float", "env_ms", "env_flag", "env_str"]


def _warn(name: str, raw: str, default) -> None:
    warnings.warn(
        f"{name}={raw!r} is not a valid value; using {default}",
        stacklevel=4)


def env_int(name: str, default: int, *, lo: Optional[int] = None,
            hi: Optional[int] = None, float_ok: bool = False,
            warn: bool = False) -> int:
    """Integer knob. See module docstring for the malformed/overflow
    semantics each flag selects."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    raw = raw.strip()
    try:
        v = int(raw)
    except ValueError:
        if not float_ok:
            if warn:
                _warn(name, raw, default)
            return default
        try:
            f = float(raw)
            if f != f or f in (float("inf"), float("-inf")):
                raise ValueError(raw)
            v = int(f)
        except (ValueError, OverflowError):
            if warn:
                _warn(name, raw, default)
            return default
    except OverflowError:  # pragma: no cover - int() doesn't overflow
        if warn:
            _warn(name, raw, default)
        return default
    if lo is not None:
        v = max(lo, v)
    if hi is not None:
        v = min(hi, v)
    return v


def env_float(name: str, default: float, *, lo: Optional[float] = None,
              hi: Optional[float] = None, finite: bool = True,
              warn: bool = False) -> float:
    """Float knob. ``finite=True`` (default) treats nan/inf spellings as
    malformed — a budget of ``inf`` is nearly always an operator error."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    raw = raw.strip()
    try:
        v = float(raw)
    except (ValueError, OverflowError):
        if warn:
            _warn(name, raw, default)
        return default
    if finite and (v != v or v in (float("inf"), float("-inf"))):
        if warn:
            _warn(name, raw, default)
        return default
    if lo is not None:
        v = max(lo, v)
    if hi is not None:
        v = min(hi, v)
    return v


def env_ms(name: str, default_ms: float, *, lo_ms: float = 0.0) -> float:
    """Millisecond knob returned in SECONDS (what time.monotonic math
    wants); malformed/non-finite → default, clamped at ``lo_ms``."""
    ms = env_float(name, default_ms, lo=lo_ms)
    return ms / 1000.0


def env_flag(name: str, default: bool) -> bool:
    """Boolean knob: 1/true/yes/on vs 0/false/no/off (case-insensitive);
    anything else → default."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    v = raw.strip().lower()
    if v in ("1", "true", "yes", "on"):
        return True
    if v in ("0", "false", "no", "off"):
        return False
    return default


def env_str(name: str, default: str, *, choices: Optional[tuple] = None,
            lower: bool = True) -> str:
    """String knob; with ``choices``, values outside the set → default."""
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return default
    v = raw.strip()
    if lower:
        v = v.lower()
    if choices is not None and v not in choices:
        return default
    return v
