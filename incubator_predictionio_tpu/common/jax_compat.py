"""JAX API compatibility shims.

The sharded ops target the modern ``jax.shard_map`` entry point
(``check_vma`` spelling); older installs (< 0.5) only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep``
spelling. Import :func:`shard_map` from here instead of from ``jax`` so
the whole training/serving stack degrades gracefully across the JAX
versions the container may carry instead of dying at import time.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # jax >= 0.5

    _MODERN = True
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN = False

__all__ = ["pcast", "shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern keyword surface on any JAX.

    On older JAX the ``check_vma`` flag maps onto ``check_rep=False``
    unconditionally: the old replication checker predates several
    collective patterns these kernels emit and rejects valid programs
    the new varying-manual-axes checker accepts, and the flag only
    controls validation strictness, never numerics.
    """
    if _MODERN:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def pcast(x, axes, to: str = "varying"):
    """``jax.lax.pcast`` where it exists, identity elsewhere.

    The varying-manual-axes type system (jax >= 0.5 shard_map with
    ``check_vma``) requires a replicated scan carry to be explicitly
    cast varying before a body whose output varies over the mesh axis.
    Old jax (0.4.x) has no ``lax.pcast`` — but this shim's
    :func:`shard_map` always runs those installs with
    ``check_rep=False``, where no replication typing is enforced and
    every value is already treated as varying, so the cast is a
    semantic no-op there: drop it. The flag only controls validation,
    never numerics, on both paths.
    """
    import jax

    cast = getattr(jax.lax, "pcast", None)
    if cast is None:
        return x
    return cast(x, axes, to=to)
