"""JAX API compatibility shims.

The sharded ops target the modern ``jax.shard_map`` entry point
(``check_vma`` spelling); older installs (< 0.5) only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep``
spelling. Import :func:`shard_map` from here instead of from ``jax`` so
the whole training/serving stack degrades gracefully across the JAX
versions the container may carry instead of dying at import time.
"""

from __future__ import annotations

try:
    from jax import shard_map as _shard_map  # jax >= 0.5

    _MODERN = True
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN = False

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the modern keyword surface on any JAX.

    On older JAX the ``check_vma`` flag maps onto ``check_rep=False``
    unconditionally: the old replication checker predates several
    collective patterns these kernels emit and rejects valid programs
    the new varying-manual-axes checker accepts, and the flag only
    controls validation strictness, never numerics.
    """
    if _MODERN:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
