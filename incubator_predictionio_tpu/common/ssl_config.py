"""TLS for the ops servers.

Reference: common/.../SSLConfiguration.scala — a JKS keystore configured via
`pio-env.sh` turns every spray server (event server, engine server, dashboard,
admin) HTTPS. The TPU-native analog uses PEM files from the environment:

  PIO_SSL_CERTFILE  path to a PEM certificate chain
  PIO_SSL_KEYFILE   path to the PEM private key
  PIO_SSL_KEY_PASSWORD  optional key passphrase

When both files are set, every `run_*` server entry point serves HTTPS;
otherwise plain HTTP (the reference's default is also off unless a keystore
is configured).
"""

from __future__ import annotations

import os
import ssl
from typing import Optional


def ssl_context_from_env(env: Optional[dict] = None) -> Optional[ssl.SSLContext]:
    e = os.environ if env is None else env
    cert = e.get("PIO_SSL_CERTFILE")
    key = e.get("PIO_SSL_KEYFILE")
    if not cert or not key:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key, password=e.get("PIO_SSL_KEY_PASSWORD"))
    return ctx
