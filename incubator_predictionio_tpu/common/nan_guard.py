"""NaN-guard tier — the sanitizer analog (SURVEY.md §5.2).

Reference behaviour: the reference's native sanitizer builds catch memory
bugs at the point of corruption; the moral equivalent for a numeric
framework is catching non-finite values at the STAGE that produced them
instead of persisting a garbage model. Enabled via `pio train
--nan-guard` (WorkflowParams.nan_guard): every DASE stage output is
checked, and iterative trainers (ALS) switch to per-iteration dispatch so
the failure names the iteration — the same speed-for-attribution trade
``jax_debug_nans``' op-by-op replay makes.
"""

from __future__ import annotations

import dataclasses

import numpy as np


class NaNGuardError(RuntimeError):
    """A stage produced non-finite values (message carries the stage)."""


class _TooDeep(Exception):
    pass


def _iter_arrays(obj, _depth: int = 0):
    """Yield (path, array) for every float array reachable from obj —
    dataclasses, dicts, lists/tuples, numpy and jax arrays. A container
    nested deeper than the cap raises instead of being silently skipped:
    an unverified subtree must not report as clean."""
    if obj is None:
        return
    if _depth > 6:
        # Anything this walker WOULD traverse must raise, not silently
        # pass as clean: arrays (incl. jax), scalars, containers,
        # dataclasses.
        if (isinstance(obj, (np.ndarray, np.generic, dict, list, tuple))
                or (dataclasses.is_dataclass(obj) and not isinstance(obj, type))
                or (type(obj).__module__.startswith("jax")
                    and hasattr(obj, "dtype"))):
            raise _TooDeep
        return
    if isinstance(obj, (np.ndarray, np.generic)):
        # bare numpy scalars (np.float32(nan) etc.) check as 0-d arrays —
        # a non-finite scalar in model state must be caught, not
        # silently reported clean
        yield "", np.asarray(obj)
        return
    # jax.Array without importing jax eagerly
    if type(obj).__module__.startswith("jax") and hasattr(obj, "dtype"):
        yield "", np.asarray(obj)
        return
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            if f.name.startswith("_"):
                continue  # caches (device buffers, indexes) — not model state
            for path, arr in _iter_arrays(getattr(obj, f.name), _depth + 1):
                yield f"{f.name}.{path}".rstrip("."), arr
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            for path, arr in _iter_arrays(v, _depth + 1):
                yield f"{k}.{path}".rstrip("."), arr
        return
    if isinstance(obj, (list, tuple)):
        for j, v in enumerate(obj):
            for path, arr in _iter_arrays(v, _depth + 1):
                yield f"[{j}].{path}".rstrip("."), arr


def check_finite(obj, stage: str) -> None:
    """Raise NaNGuardError naming ``stage`` and the offending field if any
    float array reachable from ``obj`` contains NaN/Inf."""
    try:
        for path, arr in _iter_arrays(obj):
            if arr.dtype.kind == "f" and arr.size and not np.isfinite(arr).all():
                bad = int(np.size(arr) - np.isfinite(arr).sum())
                raise NaNGuardError(
                    f"stage: {stage}: non-finite values in "
                    f"{path or 'array'} ({bad}/{arr.size} elements); "
                    "rerun with --nan-guard off to persist anyway, or fix the "
                    "input data / regularization")
    except _TooDeep:
        raise NaNGuardError(
            f"stage: {stage}: object nests containers deeper than the "
            "guard traverses (6 levels) — cannot verify finiteness; "
            "flatten the model state or disable --nan-guard") from None
