"""Unified telemetry: metric registry, Prometheus exposition, tracing.

After three PRs every layer reported health its own way — `stats.py`
counters, ad-hoc `/status` fields, `PipelineStats`, breaker snapshots —
none of it scrapable or correlated per request. Both TensorFlow (Abadi
et al., 2016) and the Spark-ML performance study (PAPERS.md 1605.08695,
Awan et al.) land on the same operational lesson: a distributed ML
system you cannot measure is a system you cannot optimize or operate.
This module is the one measurement substrate every layer records into:

- **Metric registry** — process-wide :class:`Registry` of counter /
  gauge / histogram families with Prometheus-style label sets.
  Counters are lock-*sharded* (per-thread-bucket locks, summed on
  read) so the ingest hot path never serializes on one metric lock;
  histograms use fixed log2 buckets whose index is a ``bit_length``,
  not a ``log``/bisect, and latency is fed from
  ``time.perf_counter_ns`` integers. With ``PIO_METRICS=0`` every
  record call returns before touching state — and the paired
  :func:`timer_start` returns the cached small int 0, so a disabled
  hot path adds **no allocations per request** (guard-tested).
- **Prometheus exposition** — :meth:`Registry.render` produces the
  text format (``# HELP``/``# TYPE``, escaped labels, cumulative
  ``_bucket``/``_sum``/``_count``) served by the event server, the
  engine server, and the dashboard at ``GET /metrics``.
- **Sampled request tracing** — ``PIO_TRACE`` sets a sample rate;
  sampled requests get a trace id (honoring an incoming
  ``X-Pio-Trace-Id``, which — whenever tracing is enabled at all —
  bypasses the probability roll so a caller can follow one request
  through every tier; ``PIO_TRACE`` unset/0 stays fully off), the
  id rides a
  ``contextvars`` slot across ``asyncio.to_thread`` into the serving
  stages, and finished spans are written as JSON lines to
  ``PIO_TRACE_SINK`` (a path, or ``stderr``).

Per-instance JSON views (ingest ``snapshot()``, ``stats.json``) remain
per-server-instance; the registry is process-cumulative, which is what
a scraper expects.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import sys
import threading
import time
import uuid
from typing import Callable, Iterable, Optional

from . import envknobs

__all__ = [
    "CounterFamily", "GaugeFamily", "HistogramFamily", "Registry",
    "Trace", "TraceRecorder", "TRACE_HEADER",
    "current_trace", "activate_trace", "deactivate_trace",
    "metrics_enabled", "set_metrics_enabled", "timer_start",
    "registry", "render_all", "sample_trace", "configure_tracer",
    "trace_middleware",
]

TRACE_HEADER = "X-Pio-Trace-Id"


# ---------------------------------------------------------------------------
# enablement
# ---------------------------------------------------------------------------

def _env_flag(name: str, default: bool) -> bool:
    return envknobs.env_flag(name, default)


class _State:
    """Mutable module state behind one attribute load (the hot-path
    check is ``if not _STATE.metrics_on: return``)."""

    __slots__ = ("metrics_on",)


_STATE = _State()
_STATE.metrics_on = _env_flag("PIO_METRICS", True)


def metrics_enabled() -> bool:
    return _STATE.metrics_on


def set_metrics_enabled(on: bool) -> None:
    """Flip metric recording at runtime (bench A/B, tests)."""
    _STATE.metrics_on = bool(on)


def timer_start() -> int:
    """Start a latency timer: ``perf_counter_ns`` when metrics are on,
    the cached small int ``0`` when off. The 0 sentinel makes the
    paired ``Histogram.observe_since`` a no-op, and — critically for
    the disabled-path guarantee — allocates nothing."""
    if _STATE.metrics_on:
        return time.perf_counter_ns()
    return 0


# ---------------------------------------------------------------------------
# metric children
# ---------------------------------------------------------------------------

_N_SHARDS = 8  # power of two; see _shard_index


def _shard_index() -> int:
    # thread idents are pointer-ish (low bits aligned-zero), so shift
    # before masking or every thread lands in shard 0
    return (threading.get_ident() >> 6) & (_N_SHARDS - 1)


class Counter:
    """Monotonic counter, lock-sharded: each thread bucket has its own
    (lock, value) cell, reads sum the shards. Concurrent writers on
    different shards never contend; same-shard writers serialize only
    against each other, not against every metric in the process."""

    __slots__ = ("_shards",)

    def __init__(self):
        self._shards = tuple(
            (threading.Lock(), [0]) for _ in range(_N_SHARDS))

    def inc(self, n: int = 1) -> None:
        if not _STATE.metrics_on:
            return
        lock, box = self._shards[_shard_index()]
        with lock:
            box[0] += n

    def value(self) -> int:
        total = 0
        for lock, box in self._shards:
            with lock:
                total += box[0]
        return total


class Gauge:
    """Last-write-wins gauge. Not gated on ``metrics_enabled`` — gauges
    are set from cold paths (pipeline end, breaker snapshots, compile
    accounting), never per-request."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed log2-bucket histogram over integer raw units.

    Bucket ``j`` has upper bound ``2**(lo_exp + j)`` raw units; the
    index is ``(v - 1).bit_length() - lo_exp`` — the smallest bound
    that is ``>= v``, computed without logs, division, or a bisect
    (bucket-boundary math is golden-tested). Values past the top
    bucket land in ``+Inf``. ``scale`` converts raw units to the
    exposition unit (1e-9 for ns→seconds histograms, 1 for sizes).
    """

    __slots__ = ("_lock", "lo_exp", "n_buckets", "scale", "counts",
                 "sum_raw")

    def __init__(self, lo_exp: int, n_buckets: int, scale: float):
        self._lock = threading.Lock()
        self.lo_exp = lo_exp
        self.n_buckets = n_buckets
        self.scale = scale
        self.counts = [0] * (n_buckets + 1)  # [+Inf] is the last slot
        self.sum_raw = 0

    def bucket_index(self, v: int) -> int:
        if v <= 1:
            return 0 if self.lo_exp >= 0 else max(0, -self.lo_exp)
        i = (v - 1).bit_length() - self.lo_exp
        if i < 0:
            return 0
        return min(i, self.n_buckets)

    def observe_raw(self, v: int) -> None:
        """Record one observation of ``v`` raw units (ns for latency
        histograms, a plain count for size histograms)."""
        if not _STATE.metrics_on:
            return
        i = self.bucket_index(v)
        with self._lock:
            self.counts[i] += 1
            self.sum_raw += v

    def observe_since(self, t0: int) -> None:
        """Record the elapsed ns since a :func:`timer_start` result;
        a 0 start (metrics were off at timer creation) is a no-op."""
        if t0:
            self.observe_raw(time.perf_counter_ns() - t0)

    def snapshot(self) -> tuple[list[int], int, int]:
        """(bucket counts, total count, raw sum) under the lock."""
        with self._lock:
            counts = list(self.counts)
            return counts, sum(counts), self.sum_raw

    def upper_bound(self, j: int) -> float:
        """Exposition-unit upper bound of bucket ``j``."""
        return (2.0 ** (self.lo_exp + j)) * self.scale


# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------

class _Family:
    """Named metric with a label schema; children cached per label
    values. The children dict is read lock-free (GIL-safe ``get``) and
    written under a lock — the hot path after warm-up is one dict get."""

    kind = "untyped"

    def __init__(self, name: str, help_: str, labelnames: tuple = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._children: dict = {}
        self._lock = threading.Lock()

    def _new_child(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def labels(self, *values) -> object:
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {values!r}")
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    self._children[key] = child
        return child

    def samples(self) -> Iterable[tuple[tuple, object]]:
        """(label values, child) pairs, stable-sorted for exposition."""
        return sorted(self._children.items())


class CounterFamily(_Family):
    kind = "counter"

    def _new_child(self) -> Counter:
        return Counter()


class GaugeFamily(_Family):
    kind = "gauge"

    def _new_child(self) -> Gauge:
        return Gauge()


class HistogramFamily(_Family):
    kind = "histogram"

    #: default latency shape: 2**10 ns (~1 us) .. 2**35 ns (~34 s)
    DEFAULT_LO_EXP = 10
    DEFAULT_N_BUCKETS = 26

    def __init__(self, name: str, help_: str, labelnames: tuple = (),
                 lo_exp: int = DEFAULT_LO_EXP,
                 n_buckets: int = DEFAULT_N_BUCKETS,
                 scale: float = 1e-9):
        super().__init__(name, help_, labelnames)
        self._shape = (lo_exp, n_buckets, scale)

    def _new_child(self) -> Histogram:
        return Histogram(*self._shape)


# ---------------------------------------------------------------------------
# registry + exposition
# ---------------------------------------------------------------------------

def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v) -> str:
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_text(names: tuple, values: tuple, extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


def render_families(families: Iterable[_Family]) -> str:
    """Prometheus text exposition format 0.0.4 for ``families``."""
    out: list[str] = []
    for fam in families:
        out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.kind}")
        for values, child in fam.samples():
            if fam.kind == "histogram":
                counts, total, sum_raw = child.snapshot()
                cum = 0
                for j in range(child.n_buckets):
                    cum += counts[j]
                    le = f'le="{_fmt(child.upper_bound(j))}"'
                    out.append(
                        f"{fam.name}_bucket"
                        f"{_labels_text(fam.labelnames, values, le)} {cum}")
                inf = 'le="+Inf"'
                out.append(
                    f"{fam.name}_bucket"
                    f"{_labels_text(fam.labelnames, values, inf)} {total}")
                out.append(
                    f"{fam.name}_sum"
                    f"{_labels_text(fam.labelnames, values)} "
                    f"{_fmt(sum_raw * child.scale)}")
                out.append(
                    f"{fam.name}_count"
                    f"{_labels_text(fam.labelnames, values)} {total}")
            else:
                out.append(
                    f"{fam.name}{_labels_text(fam.labelnames, values)} "
                    f"{_fmt(child.value())}")
    return "\n".join(out) + "\n" if out else ""


class Registry:
    """Named family registry plus render-time collectors.

    Families are process-cumulative objects created once
    (``counter``/``gauge``/``histogram`` are get-or-create, so module
    A and module B asking for the same name share the family).
    *Collectors* are callables returning families built at render time
    — for state owned elsewhere (circuit breakers, a server instance's
    per-instance stats). Collectors register under a key and REPLACE
    any previous registrant of that key, so a test spinning up a fresh
    server replaces the old server's collector instead of duplicating
    metric names in the exposition.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self._collectors: dict[str, Callable[[], Iterable[_Family]]] = {}

    def _family(self, cls, name: str, help_: str, labelnames: tuple,
                **kwargs) -> _Family:
        # histogram() always passes the full shape; None for other kinds
        shape = ((kwargs["lo_exp"], kwargs["n_buckets"], kwargs["scale"])
                 if cls is HistogramFamily else None)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help_, labelnames, **kwargs)
                self._families[name] = fam
            elif (not isinstance(fam, cls)
                  or fam.labelnames != tuple(labelnames)
                  or getattr(fam, "_shape", None) != shape):
                raise ValueError(
                    f"metric {name!r} re-registered with a different "
                    f"type/labels/shape")
            return fam

    def counter(self, name: str, help_: str,
                labelnames: tuple = ()) -> CounterFamily:
        return self._family(CounterFamily, name, help_, labelnames)

    def gauge(self, name: str, help_: str,
              labelnames: tuple = ()) -> GaugeFamily:
        return self._family(GaugeFamily, name, help_, labelnames)

    def histogram(self, name: str, help_: str, labelnames: tuple = (),
                  lo_exp: int = HistogramFamily.DEFAULT_LO_EXP,
                  n_buckets: int = HistogramFamily.DEFAULT_N_BUCKETS,
                  scale: float = 1e-9) -> HistogramFamily:
        return self._family(HistogramFamily, name, help_, labelnames,
                            lo_exp=lo_exp, n_buckets=n_buckets, scale=scale)

    def register_collector(self, key: str,
                           fn: Callable[[], Iterable[_Family]]) -> None:
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    def collect(self) -> list[_Family]:
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
            collectors = list(self._collectors.values())
        seen = {f.name for f in families}
        for fn in collectors:
            try:
                extra = list(fn())
            except Exception:  # noqa: BLE001 - exposition must not 500
                continue
            for fam in extra:
                if fam.name not in seen:
                    seen.add(fam.name)
                    families.append(fam)
        return families

    def render(self) -> str:
        """The full Prometheus text page for this registry."""
        return render_families(self.collect())


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-wide default registry every layer records into."""
    return _REGISTRY


def render_all() -> str:
    return _REGISTRY.render()


# ---------------------------------------------------------------------------
# sampled request tracing
# ---------------------------------------------------------------------------

_TRACE_VAR: "contextvars.ContextVar[Optional[Trace]]" = \
    contextvars.ContextVar("pio_trace", default=None)


class Trace:
    """One sampled request: collects spans, flushed once at the end.

    Spans are buffered in-process and written as JSON lines in one
    flush so a trace's spans land contiguously in the sink even under
    concurrent requests."""

    __slots__ = ("trace_id", "_recorder", "_spans", "_lock")

    def __init__(self, trace_id: str, recorder: "TraceRecorder"):
        self.trace_id = trace_id
        self._recorder = recorder
        self._spans: list[dict] = []
        self._lock = threading.Lock()

    def add_span(self, name: str, dur_ns: int, **tags) -> None:
        span = {
            "traceId": self.trace_id,
            "span": name,
            "startUs": (time.time_ns() - dur_ns) // 1000,
            "durUs": dur_ns // 1000,
        }
        if tags:
            span["tags"] = tags
        with self._lock:
            self._spans.append(span)

    @contextlib.contextmanager
    def span(self, name: str, **tags):
        t0 = time.perf_counter_ns()
        try:
            yield self
        finally:
            self.add_span(name, time.perf_counter_ns() - t0, **tags)

    def flush(self) -> None:
        with self._lock:
            spans, self._spans = self._spans, []
        if spans:
            self._recorder.emit(spans)


class TraceRecorder:
    """``PIO_TRACE``-rate span sampler writing JSON lines to a sink.

    ``PIO_TRACE``: unset/0 → off; ``1``/``on`` → every request; a
    float in (0, 1) → that sampling probability. ``PIO_TRACE_SINK``:
    a file path (lines appended under a lock) or ``stderr`` (default).
    With tracing enabled, an incoming ``X-Pio-Trace-Id`` skips the
    probability roll — the upstream tier already decided this request
    is worth following. With ``PIO_TRACE`` unset/0 the header is
    ignored: off means off, clients cannot force span writes."""

    def __init__(self, rate: Optional[float] = None,
                 sink: Optional[str] = None):
        if rate is None:
            raw = envknobs.env_str("PIO_TRACE", "")
            if raw in ("", "0", "off", "false", "no"):
                rate = 0.0
            elif raw in ("1", "on", "true", "yes"):
                rate = 1.0
            else:
                rate = envknobs.env_float("PIO_TRACE", 0.0)
        self.rate = max(0.0, min(1.0, float(rate)))
        self.sink = (sink
                     or envknobs.env_str("PIO_TRACE_SINK", "", lower=False)
                     or "stderr")
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.rate > 0.0

    def sample(self, incoming_id: Optional[str] = None) -> Optional[Trace]:
        if not self.rate:
            return None
        if incoming_id:
            return Trace(incoming_id[:64], self)
        if self.rate < 1.0 and random.random() >= self.rate:
            return None
        return Trace(uuid.uuid4().hex[:16], self)

    def emit(self, spans: list[dict]) -> None:
        data = "".join(json.dumps(s, separators=(",", ":")) + "\n"
                       for s in spans)
        try:
            with self._lock:
                if self.sink == "stderr":
                    sys.stderr.write(data)
                else:
                    with open(self.sink, "a", encoding="utf-8") as f:
                        f.write(data)
        except OSError:  # noqa: PERF203 - a dead sink must not fail requests
            pass


_TRACER: Optional[TraceRecorder] = None
_TRACER_LOCK = threading.Lock()


def _tracer() -> TraceRecorder:
    global _TRACER
    if _TRACER is None:
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = TraceRecorder()
    return _TRACER


def configure_tracer(rate: Optional[float] = None,
                     sink: Optional[str] = None) -> TraceRecorder:
    """(Re)build the process tracer — re-reads PIO_TRACE / PIO_TRACE_SINK
    for arguments left None. Tests and `pio` verbs use this after
    changing the environment."""
    global _TRACER
    with _TRACER_LOCK:
        _TRACER = TraceRecorder(rate, sink)
        return _TRACER


def sample_trace(incoming_id: Optional[str] = None) -> Optional[Trace]:
    """Sampling decision for one request (None → not traced)."""
    return _tracer().sample(incoming_id)


def current_trace() -> Optional[Trace]:
    """The active request's Trace, if sampled. Propagates across
    ``asyncio.to_thread`` (contextvars are copied into the executor),
    which is how the serving stages inside ``Deployment.query`` see
    the trace the HTTP layer started."""
    return _TRACE_VAR.get()


def activate_trace(tr: Trace):
    return _TRACE_VAR.set(tr)


def deactivate_trace(token) -> None:
    _TRACE_VAR.reset(token)


def trace_middleware():
    """aiohttp middleware: sample each request, bind the trace into the
    handler's context, stamp ``X-Pio-Trace-Id`` on the response, and
    flush the root span. Servers append this to their middleware list;
    with tracing off it forwards with one None check."""
    from aiohttp import web

    @web.middleware
    async def _trace_mw(request, handler):
        tr = sample_trace(request.headers.get(TRACE_HEADER))
        if tr is None:
            return await handler(request)
        token = activate_trace(tr)
        t0 = time.perf_counter_ns()
        status = 500
        try:
            resp = await handler(request)
            status = resp.status
            resp.headers[TRACE_HEADER] = tr.trace_id
            return resp
        except web.HTTPException as e:
            status = e.status
            e.headers[TRACE_HEADER] = tr.trace_id
            raise
        finally:
            deactivate_trace(token)
            tr.add_span(f"http {request.method} {request.path}",
                        time.perf_counter_ns() - t0, status=status)
            tr.flush()

    return _trace_mw
