"""Shared L4 splice front: connection-level round-robin proxying for
multi-worker services.

One listener accepts client connections and splices each to ONE backend
worker (chosen round-robin among backends that accept a connect), so no
HTTP parsing sits on the hot path — keep-alive clients naturally spread
across workers and a worker mid-restart is skipped (its connections land
on the survivors). Extracted from the PR 8 partitioned event server so
the engine replica fleet (``workflow/fleet.py``) rides the same front.

Hardening on top of the original event-server front (all opt-in, the
event server keeps its exact original behavior):

- **Readiness-aware routing.** ``FrontProxy.set_ready(i, bool)`` marks a
  backend not-ready; new connections prefer ready backends and fall back
  to the full list only when nothing is ready (serving a maybe-draining
  replica beats refusing outright). :func:`probe_ready` is a minimal
  asyncio HTTP ``GET /readyz`` prober the owner can poll with — a
  draining replica (readyz 503) stops receiving NEW connections while
  its in-flight work finishes.
- **Connect-refused retry.** A backend that refuses the connect (worker
  mid-relaunch) is skipped within the same accept — the client pays
  nothing for a replica that is between death and respawn, as long as
  any backend answers. With ``connect_retry_s`` > 0 a pass where EVERY
  backend refuses is retried within that time budget before the client
  is dropped: a starved worker stops accept()ing and its full accept
  queue refuses connects while the process is alive, so a sub-second
  stall costs the client a short wait instead of an RST.
- **Front-served /healthz.** With ``healthz_provider`` set, the front
  peeks at the FIRST bytes of each client connection; a connection whose
  first request line starts with ``GET /healthz`` is answered directly
  by the front with the provider's JSON (aggregated backend liveness)
  and closed — everything else is spliced untouched, with the peeked
  bytes forwarded verbatim. The cost on the hot path is one prefix
  compare per connection, not an HTTP parse; on a kept-alive spliced
  connection only the first request is inspected (a later ``/healthz``
  rides through to a backend, which serves its own).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Optional

log = logging.getLogger("pio.splice")

__all__ = ["FrontProxy", "pipe", "probe_ready"]

_HEALTHZ_PREFIX = b"GET /healthz"


async def pipe(reader: asyncio.StreamReader,
               writer: asyncio.StreamWriter) -> None:
    """One splice direction. EOF half-closes the peer (write_eof) —
    a client that shuts down its write side after the request must
    still receive the response on the other direction; the full close
    happens in the connection handler once BOTH directions are done."""
    try:
        while True:
            chunk = await reader.read(65536)
            if not chunk:
                break
            writer.write(chunk)
            await writer.drain()
        if writer.can_write_eof():
            writer.write_eof()
    except (ConnectionError, asyncio.IncompleteReadError, OSError):
        try:
            writer.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


async def probe_ready(host: str, port: int, timeout: float = 2.0) -> bool:
    """Minimal readiness probe: ``GET /readyz`` against one backend,
    True iff it answers 200. Hand-rolled over asyncio streams so the
    front needs no HTTP client stack; any connect/read failure is
    simply not-ready."""
    try:
        r, w = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout)
    except (OSError, asyncio.TimeoutError):
        return False
    try:
        w.write(b"GET /readyz HTTP/1.1\r\nHost: front\r\n"
                b"Connection: close\r\n\r\n")
        await w.drain()
        line = await asyncio.wait_for(r.readline(), timeout)
        return b" 200" in line
    except (ConnectionError, OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError):
        return False
    finally:
        try:
            w.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


class FrontProxy:
    """Connection-level (L4) front listener: each accepted client
    connection is spliced to one worker, chosen round-robin among the
    backends that accept a connect. See the module docstring for the
    optional readiness/healthz hardening."""

    def __init__(self, worker_ports: list[int], host: str = "127.0.0.1",
                 healthz_provider: Optional[Callable[[], dict]] = None,
                 connect_retry_s: float = 0.0):
        self.worker_ports = worker_ports
        self.worker_host = host
        self.healthz_provider = healthz_provider
        # > 0: a pass where EVERY backend refuses the connect is
        # retried (50 ms pacing) within this time budget before the
        # client is dropped. A starved worker stops accept()ing and its
        # full accept queue refuses connects while the process is
        # perfectly alive — a sub-second stall must cost the client a
        # short wait, not an RST. 0 keeps the original one-pass drop
        # (the event-server front's exact behavior).
        self.connect_retry_s = float(connect_retry_s)
        # readiness marks (index-aligned with worker_ports); absent =
        # assumed ready, so fronts that never probe behave exactly as
        # before the hardening
        self._ready: dict[int, bool] = {}
        # draining marks: a backend the owner is INTENTIONALLY taking
        # out of rotation (elastic scale-down). Unlike not-ready — which
        # still admits the backend on the all-else-refused fallback
        # pass — a draining backend gets NO new connections at all: its
        # in-flight splices finish, and clients reconnect to survivors.
        # A freed slot keeps its index with ``worker_ports[idx] = None``
        # so slot identity stays stable across scale cycles.
        self._draining: dict[int, bool] = {}
        self._rr = 0
        self._server: Optional[asyncio.AbstractServer] = None
        # live connection tasks: stop() must be able to cut idle
        # keep-alive splices — on 3.10 Server.wait_closed() waits for
        # every active connection, so ONE parked splice would otherwise
        # wedge shutdown forever
        self._conns: set = set()

    def set_ready(self, idx: int, ready: bool) -> None:
        self._ready[idx] = bool(ready)

    def is_ready(self, idx: int) -> bool:
        return self._ready.get(idx, True)

    def set_draining(self, idx: int, draining: bool) -> None:
        self._draining[idx] = bool(draining)

    def is_draining(self, idx: int) -> bool:
        return self._draining.get(idx, False)

    def set_backend(self, idx: int, port: Optional[int]) -> None:
        """Assign (or free, with ``None``) the backend slot ``idx``,
        extending the slot list as needed — the elastic owner's hook.
        Freeing a slot clears its readiness/draining marks so a later
        occupant starts with the unprobed defaults."""
        while len(self.worker_ports) <= idx:
            self.worker_ports.append(None)
        self.worker_ports[idx] = port
        if port is None:
            self._ready.pop(idx, None)
            self._draining.pop(idx, None)

    def _routable(self, idx: int) -> bool:
        return (self.worker_ports[idx] is not None
                and not self._draining.get(idx, False))

    def active_count(self) -> int:
        """Slots holding a routable (assigned, not draining) backend."""
        return sum(1 for i in range(len(self.worker_ports))
                   if self._routable(i))

    def ready_count(self) -> int:
        return sum(1 for i in range(len(self.worker_ports))
                   if self._routable(i) and self._ready.get(i, True))

    async def _connect_backend(self):
        loop = asyncio.get_running_loop()
        deadline = (loop.time() + self.connect_retry_s
                    if self.connect_retry_s > 0 else None)
        while True:
            n = len(self.worker_ports)
            # two passes: ready backends first, then every ROUTABLE one
            # — a fleet with zero ready replicas still routes (a
            # not-ready-but-alive replica answering 503s beats a
            # refused connect). Draining and freed slots are excluded
            # from BOTH passes: drain means no new connections, period.
            for ready_only in (True, False):
                for i in range(n):
                    j = (self._rr + i) % n
                    if not self._routable(j):
                        continue
                    if ready_only and not self._ready.get(j, True):
                        continue
                    try:
                        r, w = await asyncio.open_connection(
                            self.worker_host, self.worker_ports[j])
                    except OSError:
                        continue
                    self._rr = (j + 1) % n
                    return r, w
                if all(self._ready.get(i, True) for i in range(n)
                       if self._routable(i)):
                    break  # second pass would retry the identical set
            if deadline is None or loop.time() >= deadline:
                return None
            await asyncio.sleep(0.05)

    async def _serve_healthz(self, cwriter) -> None:
        try:
            doc = self.healthz_provider()
        except Exception:  # noqa: BLE001 — health must not kill the front
            doc = {"status": "error"}
        body = json.dumps(doc).encode("utf-8")
        cwriter.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode()
            + b"\r\nConnection: close\r\n\r\n" + body)
        try:
            await cwriter.drain()
        except (ConnectionError, OSError):
            pass

    async def _handle(self, creader, cwriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conns.add(task)
            task.add_done_callback(self._conns.discard)
        first = b""
        if self.healthz_provider is not None:
            try:
                first = await creader.read(65536)
                # a request line split across TCP segments ("GET /hea" +
                # "lthz ...") must not be misrouted to a backend's own
                # /healthz: keep reading while the bytes so far are
                # still a proper prefix of the marker (bounded — at
                # most len(marker) bytes before the loop settles)
                while (0 < len(first) < len(_HEALTHZ_PREFIX)
                       and _HEALTHZ_PREFIX.startswith(first)):
                    more = await creader.read(65536)
                    if not more:
                        break
                    first += more
            except (ConnectionError, OSError):
                first = b""
            if not first:
                cwriter.close()
                return
            if first.startswith(_HEALTHZ_PREFIX):
                await self._serve_healthz(cwriter)
                cwriter.close()
                return
        backend = await self._connect_backend()
        if backend is None:
            log.warning("front: no backend accepted a connection "
                        "(ports %s, ready %s); dropping the client",
                        self.worker_ports, dict(self._ready))
            cwriter.close()
            return
        breader, bwriter = backend
        if first:
            try:
                bwriter.write(first)
                await bwriter.drain()
            except (ConnectionError, OSError):
                for w in (bwriter, cwriter):
                    try:
                        w.close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass
                return
        try:
            await asyncio.gather(pipe(creader, bwriter),
                                 pipe(breader, cwriter))
        finally:
            # runs on cancellation too (stop() cutting stragglers):
            # transports must close or wait_closed() never completes
            for w in (bwriter, cwriter):
                try:
                    w.close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    async def start(self, host: str, port: int) -> None:
        self._server = await asyncio.start_server(
            self._handle, host, port, reuse_address=True)

    async def stop(self, drain_s: float = 5.0) -> None:
        """Stop accepting, give in-flight splices ``drain_s`` to finish
        naturally (their backends are still up — requests already
        spliced get their response), then cut stragglers: an idle
        keep-alive splice never ends on its own, and on Python < 3.12
        ``Server.wait_closed()`` waits for every active connection, so
        without the cut a single parked client would wedge shutdown."""
        if self._server is None:
            return
        self._server.close()
        if self._conns:
            _done, pending = await asyncio.wait(set(self._conns),
                                                timeout=drain_s)
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
        await self._server.wait_closed()
        self._server = None
