"""Native event-log codec bindings (ctypes over native/src/event_codec.cc).

The C++ library is the scan path of the JSONL event store — the role the
HBase client + TableInputFormat scan play in the reference (storage/hbase/
.../HBPEvents.scala). ``parse_events_jsonl`` decodes a JSONL buffer into
``ColumnarEvents``: interned id codes + timestamps + ratings as numpy
arrays, the exact host-side layout the input pipeline uploads to device.

Build strategy: the .so is compiled lazily on first use (one translation
unit, ~1s with g++ -O3) into ``_lib/`` next to this file, keyed by an ABI
version exported by the library; `make -C native` does the same for
packaging. When no C++ toolchain is available ``parse_events_jsonl``
raises ``NativeUnavailable`` and callers fall back to the pure-Python
scan — behavior is identical, only slower (tests assert equality).
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

_EXPECTED_VERSION = 18

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


class NativeUnavailable(RuntimeError):
    pass


class EventParseError(ValueError):
    pass


def _src_path() -> str:
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, "native", "src", "event_codec.cc")


def _lib_path() -> str:
    # ABI version in the filename: glibc dlopen dedups by pathname, so a
    # same-path rebuild inside a live process would silently resolve to
    # the stale mapped library (its symbols, not the new ones).
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib",
                        f"libpioevent.v{_EXPECTED_VERSION}.so")


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.pio_codec_version.restype = ctypes.c_int32
    lib.pio_parse_events_jsonl.restype = ctypes.c_void_p
    lib.pio_parse_events_jsonl.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.pio_col_count.restype = ctypes.c_int64
    lib.pio_col_count.argtypes = [ctypes.c_void_p]
    for name in ("pio_col_event", "pio_col_etype", "pio_col_eid",
                 "pio_col_tetype", "pio_col_teid", "pio_col_event_id"):
        fn = getattr(lib, name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p]
    for name in ("pio_col_time_us", "pio_col_props", "pio_col_span"):
        fn = getattr(lib, name)
        fn.restype = ctypes.POINTER(ctypes.c_int64)
        fn.argtypes = [ctypes.c_void_p]
    lib.pio_col_rating.restype = ctypes.POINTER(ctypes.c_float)
    lib.pio_col_rating.argtypes = [ctypes.c_void_p]
    lib.pio_table_size.restype = ctypes.c_int32
    lib.pio_table_size.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.pio_table_get.restype = ctypes.POINTER(ctypes.c_char)
    lib.pio_table_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.pio_table_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.pio_table_blob.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.pio_table_offsets.restype = ctypes.POINTER(ctypes.c_int64)
    lib.pio_table_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.pio_tombstone_count.restype = ctypes.c_int64
    lib.pio_tombstone_count.argtypes = [ctypes.c_void_p]
    lib.pio_tombstone_pos.restype = ctypes.POINTER(ctypes.c_int64)
    lib.pio_tombstone_pos.argtypes = [ctypes.c_void_p]
    lib.pio_tombstone_get.restype = ctypes.POINTER(ctypes.c_char)
    lib.pio_tombstone_get.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
    ]
    lib.pio_free.restype = None
    lib.pio_free.argtypes = [ctypes.c_void_p]
    lib.pio_ingest_batch.restype = ctypes.c_void_p
    lib.pio_ingest_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.pio_ingest_count.restype = ctypes.c_int64
    lib.pio_ingest_count.argtypes = [ctypes.c_void_p]
    lib.pio_ingest_all_ok.restype = ctypes.c_int32
    lib.pio_ingest_all_ok.argtypes = [ctypes.c_void_p]
    lib.pio_ingest_lines.restype = ctypes.POINTER(ctypes.c_char)
    lib.pio_ingest_lines.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
    lib.pio_ingest_free.restype = None
    lib.pio_ingest_free.argtypes = [ctypes.c_void_p]
    lib.pio_cco_partition.restype = ctypes.c_void_p
    lib.pio_cco_partition.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int64,
    ]
    lib.pio_ccop_dim.restype = ctypes.c_int64
    lib.pio_ccop_dim.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.pio_ccop_slab.restype = ctypes.POINTER(ctypes.c_uint16)
    lib.pio_ccop_slab.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.pio_ccop_item_counts.restype = ctypes.POINTER(ctypes.c_int64)
    lib.pio_ccop_item_counts.argtypes = [ctypes.c_void_p]
    lib.pio_ccop_free.restype = None
    lib.pio_ccop_free.argtypes = [ctypes.c_void_p]
    lib.pio_pair_dedupe.restype = ctypes.c_void_p
    lib.pio_pair_dedupe.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.pio_pdd_count.restype = ctypes.c_int64
    lib.pio_pdd_count.argtypes = [ctypes.c_void_p]
    for name in ("pio_pdd_users", "pio_pdd_items"):
        fn = getattr(lib, name)
        fn.restype = ctypes.POINTER(ctypes.c_int32)
        fn.argtypes = [ctypes.c_void_p]
    lib.pio_pdd_per_user.restype = ctypes.POINTER(ctypes.c_int64)
    lib.pio_pdd_per_user.argtypes = [ctypes.c_void_p]
    lib.pio_pdd_free.restype = None
    lib.pio_pdd_free.argtypes = [ctypes.c_void_p]
    lib.pio_fill_entries.restype = ctypes.c_int32
    lib.pio_fill_entries.argtypes = [
        ctypes.POINTER(ctypes.c_int64),   # row
        ctypes.POINTER(ctypes.c_int64),   # col
        ctypes.POINTER(ctypes.c_float),   # val
        ctypes.c_int64,                   # nnz
        ctypes.POINTER(ctypes.c_int64),   # col_slot_map
        ctypes.c_int64,                   # n_cols
        ctypes.POINTER(ctypes.c_int64),   # prim_base
        ctypes.POINTER(ctypes.c_int64),   # v_base
        ctypes.POINTER(ctypes.c_int64),   # vc_e
        ctypes.POINTER(ctypes.c_int64),   # cursor scratch
        ctypes.c_int64,                   # n_rows
        ctypes.POINTER(ctypes.c_int32),   # flat_cols
        ctypes.POINTER(ctypes.c_float),   # flat_vals
        ctypes.c_int64,                   # total
    ]
    lib.pio_tfidf_tf.restype = ctypes.c_int32
    lib.pio_tfidf_tf.argtypes = [
        ctypes.c_char_p,                  # concatenated utf-8 docs
        ctypes.POINTER(ctypes.c_int64),   # offsets [n_docs + 1]
        ctypes.c_int64,                   # n_docs
        ctypes.c_int32,                   # n_features
        ctypes.c_int32,                   # ngram
        ctypes.POINTER(ctypes.c_float),   # out [n_docs, n_features]
        ctypes.POINTER(ctypes.c_int64),   # df [n_features] or NULL
    ]
    lib.pio_tfidf_tf_coo.restype = ctypes.c_int64
    lib.pio_tfidf_tf_coo.argtypes = [
        ctypes.c_char_p,                  # concatenated utf-8 docs
        ctypes.POINTER(ctypes.c_int64),   # offsets [n_docs + 1]
        ctypes.c_int64,                   # n_docs
        ctypes.c_int32,                   # n_features
        ctypes.c_int32,                   # ngram
        ctypes.c_int64,                   # cap
        ctypes.POINTER(ctypes.c_int64),   # doc_ptr [n_docs + 1]
        ctypes.POINTER(ctypes.c_int32),   # feat_out [cap]
        ctypes.POINTER(ctypes.c_float),   # cnt_out [cap]
        ctypes.POINTER(ctypes.c_int64),   # df [n_features] or NULL
    ]
    return lib


def _build() -> str:
    out = _lib_path()
    os.makedirs(os.path.dirname(out), exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-shared",
           "-o", tmp, _src_path()]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        raise NativeUnavailable(f"g++ build failed: {proc.stderr[-2000:]}")
    os.replace(tmp, out)
    # drop superseded ABI versions (and the pre-v7 unversioned file)
    import glob

    for stale in glob.glob(os.path.join(os.path.dirname(out), "libpioevent*.so")):
        if stale != out:
            try:
                os.unlink(stale)
            except OSError:
                pass
    return out


def _load() -> ctypes.CDLL:
    global _lib, _lib_error
    from ..common import envknobs

    if envknobs.env_flag("PIO_DISABLE_NATIVE", False):
        # operational kill-switch: force every caller onto the pure-
        # Python fallbacks (e.g. a miscompiling toolchain in the field)
        raise NativeUnavailable("disabled by PIO_DISABLE_NATIVE=1")
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_error is not None:
            raise NativeUnavailable(_lib_error)
        try:
            path = _lib_path()
            lib = None
            if os.path.exists(path):
                try:
                    candidate = _bind(ctypes.CDLL(path))
                    if candidate.pio_codec_version() == _EXPECTED_VERSION:
                        lib = candidate
                except (OSError, AttributeError):
                    pass  # stale/corrupt cache → rebuild below
            if lib is None:
                lib = _bind(ctypes.CDLL(_build()))
                if lib.pio_codec_version() != _EXPECTED_VERSION:
                    raise NativeUnavailable(
                        "built library ABI version mismatch — source/wrapper skew"
                    )
            _lib = lib
            return _lib
        except NativeUnavailable as e:
            _lib_error = str(e)
            raise
        except Exception as e:  # toolchain/loader failures degrade cleanly
            _lib_error = f"native codec unavailable: {e}"
            raise NativeUnavailable(_lib_error) from e


def loaded() -> Optional[ctypes.CDLL]:
    """The already-loaded library, or None — NEVER loads or builds.
    Hot paths that may run ON an event loop (the ingest fast paths) use
    this so a cold cache can't turn into a g++ build stalling every
    connection; a sync context (server construction) pays the build via
    :func:`available`. Honours the PIO_DISABLE_NATIVE kill-switch
    per-call exactly like :func:`_load` — the operational escape hatch
    must cover the hot path too, resident library or not."""
    from ..common import envknobs

    if envknobs.env_flag("PIO_DISABLE_NATIVE", False):
        return None
    return _lib


def available() -> bool:
    try:
        _load()
        return True
    except NativeUnavailable:
        return False


@dataclass
class ColumnarEvents:
    """Interned columnar view of an event log scan.

    Code -1 in ``tetype``/``teid``/``event_id`` = field absent;
    ``time_us`` INT64_MIN = absent; ``rating`` NaN = key absent, -inf =
    key present but not coercible to a finite number (the two fill
    differently in find_ratings). ``props`` and ``span`` are [start, end)
    byte offsets into ``raw`` (-1 = absent) for lazy per-event reparse of
    the full JSON. ``tombstone_pos[i]`` = how many event records precede
    tombstone i (deletes are positional: later re-inserts are live).

    String tables are materialized lazily per table via ``table(which)`` —
    the eventId table of a big scan is as large as the scan itself, and the
    training fast path never touches it.
    """

    raw: bytes
    event: np.ndarray
    etype: np.ndarray
    eid: np.ndarray
    tetype: np.ndarray
    teid: np.ndarray
    event_id: np.ndarray
    time_us: np.ndarray
    rating: np.ndarray
    props: np.ndarray  # (n, 2) int64
    span: np.ndarray  # (n, 2) int64
    # per table: (concatenated utf-8 blob, size+1 end-offsets) or the
    # already-built list
    _tables: list
    tombstones: list[str]
    tombstone_pos: np.ndarray  # int64, record count before each tombstone

    def __len__(self) -> int:
        return int(self.event.shape[0])

    TABLE_EVENT, TABLE_ETYPE, TABLE_EID = 0, 1, 2
    TABLE_TETYPE, TABLE_TEID, TABLE_EVENT_ID = 3, 4, 5

    def table(self, which: int) -> list[str]:
        t = self._tables[which]
        if isinstance(t, list):
            return t
        blob, offs = t
        size = len(offs) - 1
        text = blob.decode("utf-8")
        if len(text) == len(blob):  # pure ASCII: str slicing == byte slicing
            out = [text[offs[k]:offs[k + 1]] for k in range(size)]
        else:
            out = [blob[offs[k]:offs[k + 1]].decode("utf-8") for k in range(size)]
        self._tables[which] = out
        return out

    @property
    def tables(self) -> list[list[str]]:
        return [self.table(w) for w in range(6)]

    def properties_dict(self, i: int) -> dict:
        s, e = self.props[i]
        if s < 0:
            return {}
        return json.loads(self.raw[s:e])

    def record_dict(self, i: int) -> dict:
        s, e = self.span[i]
        return json.loads(self.raw[s:e])


def _np_copy(ptr, n, dtype):
    if n == 0:
        return np.empty(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def parse_events_jsonl(buf: bytes) -> ColumnarEvents:
    """Parse a JSONL buffer of event objects (native fast path).

    Raises NativeUnavailable when no toolchain/library, EventParseError on
    malformed input. Pure-Python equivalent: ``parse_events_jsonl_py``.
    """
    lib = _load()
    err = ctypes.create_string_buffer(512)
    handle = lib.pio_parse_events_jsonl(buf, len(buf), err, len(err))
    if not handle:
        raise EventParseError(err.value.decode(errors="replace") or "parse failed")
    try:
        n = lib.pio_col_count(handle)
        tables = []
        for which in range(6):
            size = lib.pio_table_size(handle, which)
            if size == 0:
                tables.append([])
                continue
            blob_len = ctypes.c_int64(0)
            blob_ptr = lib.pio_table_blob(handle, which, ctypes.byref(blob_len))
            blob = ctypes.string_at(blob_ptr, blob_len.value)
            offs = _np_copy(lib.pio_table_offsets(handle, which), size + 1, np.int64)
            tables.append((blob, offs))
        tombstones = []
        ln = ctypes.c_int32(0)
        n_tomb = lib.pio_tombstone_count(handle)
        for idx in range(n_tomb):
            ptr = lib.pio_tombstone_get(handle, idx, ctypes.byref(ln))
            tombstones.append(ctypes.string_at(ptr, ln.value).decode("utf-8"))
        tombstone_pos = _np_copy(lib.pio_tombstone_pos(handle), n_tomb, np.int64)
        return ColumnarEvents(
            raw=buf,
            event=_np_copy(lib.pio_col_event(handle), n, np.int32),
            etype=_np_copy(lib.pio_col_etype(handle), n, np.int32),
            eid=_np_copy(lib.pio_col_eid(handle), n, np.int32),
            tetype=_np_copy(lib.pio_col_tetype(handle), n, np.int32),
            teid=_np_copy(lib.pio_col_teid(handle), n, np.int32),
            event_id=_np_copy(lib.pio_col_event_id(handle), n, np.int32),
            time_us=_np_copy(lib.pio_col_time_us(handle), n, np.int64),
            rating=_np_copy(lib.pio_col_rating(handle), n, np.float32),
            props=_np_copy(lib.pio_col_props(handle), 2 * n, np.int64).reshape(n, 2),
            span=_np_copy(lib.pio_col_span(handle), 2 * n, np.int64).reshape(n, 2),
            _tables=tables,
            tombstones=tombstones,
            tombstone_pos=tombstone_pos,
        )
    finally:
        lib.pio_free(handle)


_FILL_ERRORS = {
    -1: "column id outside the counterpart slot map",
    -2: "computed destination outside the flat buffer (inconsistent plan)",
    -3: "row id outside [0, n_rows)",
}


def fill_entries(row: np.ndarray, col: np.ndarray, val, col_slot_map,
                 prim_base: np.ndarray, v_base: np.ndarray,
                 vc_e: np.ndarray, flat_cols: np.ndarray,
                 flat_vals) -> None:
    """Native scatter for ops/rowblocks.fill_buckets (see event_codec.cc).

    Mutates ``flat_cols``/``flat_vals`` in place; within-row entry order
    is the original order, bit-identical to the numpy fallback path.
    ``val``/``flat_vals`` may be None together (binary-ratings mode —
    the value slabs are never built). Raises NativeUnavailable when no
    toolchain, ValueError on the contract violations the library
    range-checks.
    """
    lib = _load()
    n_rows = int(prim_base.shape[0])
    row = np.ascontiguousarray(row, np.int64)
    col = np.ascontiguousarray(col, np.int64)
    col_slot_map = np.ascontiguousarray(col_slot_map, np.int64)
    prim_base = np.ascontiguousarray(prim_base, np.int64)
    v_base = np.ascontiguousarray(v_base, np.int64)
    vc_e = np.ascontiguousarray(vc_e, np.int64)
    if flat_cols.dtype != np.int32 or not flat_cols.flags.c_contiguous:
        raise ValueError("fill_entries: flat_cols must be contiguous int32")
    if (flat_vals is None) != (val is None):
        raise ValueError("fill_entries: val and flat_vals must be "
                         "both present or both None")
    if flat_vals is not None:
        val = np.ascontiguousarray(val, np.float32)
        if flat_vals.dtype != np.float32 or not flat_vals.flags.c_contiguous:
            raise ValueError(
                "fill_entries: flat_vals must be contiguous float32")
    cursor = np.empty(n_rows, np.int64)

    def p(a, ct):
        return None if a is None else a.ctypes.data_as(ctypes.POINTER(ct))

    rc = lib.pio_fill_entries(
        p(row, ctypes.c_int64), p(col, ctypes.c_int64),
        p(val, ctypes.c_float), len(row),
        p(col_slot_map, ctypes.c_int64), len(col_slot_map),
        p(prim_base, ctypes.c_int64), p(v_base, ctypes.c_int64),
        p(vc_e, ctypes.c_int64), p(cursor, ctypes.c_int64), n_rows,
        p(flat_cols, ctypes.c_int32), p(flat_vals, ctypes.c_float),
        len(flat_cols),
    )
    if rc != 0:
        raise ValueError(
            f"fill_entries: {_FILL_ERRORS.get(rc, f'error {rc}')}")


def tfidf_tf_coo(docs, n_features: int, ngram: int,
                 want_df: bool = False):
    """Native per-doc (feature, count) pairs — the COO twin of
    ``tfidf_tf`` (see pio_tfidf_tf_coo in event_codec.cc). The dense
    [N, D] matrix never exists: linear trainers reduce over docs, so
    only the ~150 distinct buckets per doc need to leave the tokenizer
    (or cross an accelerator link). Returns
    ``(doc_ptr [N+1] int64, feat [nnz] int32, counts [nnz] float32)``
    (+ ``df`` when requested), entries per doc in ascending bucket id.
    """
    lib = _load()
    enc = [d.encode(errors="replace") for d in docs]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=offs[1:])
    buf = b"".join(enc)
    # nnz is bounded by token occurrences; every token is >=1 byte with
    # >=0 separators, and each of the (ngram-1) extra orders adds at
    # most one occurrence per token position
    cap = (len(buf) // 2 + len(enc) + 1) * ngram + 1
    doc_ptr = np.zeros(len(enc) + 1, np.int64)
    feat = np.empty(cap, np.int32)
    cnt = np.empty(cap, np.float32)
    df = np.zeros(n_features, np.int64) if want_df else None
    nnz = lib.pio_tfidf_tf_coo(
        buf, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(enc), n_features, ngram, cap,
        doc_ptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        feat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cnt.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        (df.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
         if df is not None else None),
    )
    if nnz < 0:
        raise ValueError(f"tfidf_tf_coo: native tokenizer error {nnz}")
    out = (doc_ptr, feat[:nnz].copy(), cnt[:nnz].copy())
    return out + (df,) if want_df else out


def tfidf_tf(docs, n_features: int, ngram: int,
             want_df: bool = False):
    """Native term-frequency rows (see pio_tfidf_tf in event_codec.cc).

    Bit-identical to ops/tfidf.TfIdfVectorizer's Python token loop.
    ``want_df=True`` returns ``(tf, df)`` with the per-bucket document
    frequency accumulated during the same pass (the IDF fit then needs
    no second sweep over the [N,D] matrix). Raises NativeUnavailable
    when no toolchain.
    """
    lib = _load()
    # errors="replace": lone surrogates (legal in Python str, e.g. out
    # of json.loads "\ud800" escapes) can't encode to UTF-8. '?' is not
    # a token byte, and neither is a surrogate under the Python
    # tokenizer's ASCII class — both act as separators, so replacement
    # preserves token boundaries and bit-identity with the fallback.
    enc = [d.encode(errors="replace") for d in docs]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=offs[1:])
    buf = b"".join(enc)
    out = np.zeros((len(enc), n_features), np.float32)
    df = np.zeros(n_features, np.int64) if want_df else None
    rc = lib.pio_tfidf_tf(
        buf, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(enc), n_features, ngram,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        (df.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
         if df is not None else None),
    )
    if rc != 0:
        raise ValueError(f"tfidf_tf: native tokenizer error {rc}")
    return (out, df) if want_df else out


def _scan_object_bytes(rec: bytes, start: int) -> int:
    """End index (exclusive) of the JSON object opening at rec[start] == '{'.
    Structural bytes are ASCII, so scanning raw UTF-8 is safe."""
    depth, j = 0, start
    in_str = esc = False
    while j < len(rec):
        ch = rec[j:j + 1]
        if in_str:
            if esc:
                esc = False
            elif ch == b"\\":
                esc = True
            elif ch == b'"':
                in_str = False
        elif ch == b'"':
            in_str = True
        elif ch == b"{":
            depth += 1
        elif ch == b"}":
            depth -= 1
            if depth == 0:
                return j + 1
        j += 1
    raise EventParseError("unterminated properties object")


def parse_events_jsonl_py(buf: bytes) -> ColumnarEvents:
    """Pure-Python reference implementation (fallback + equality oracle).

    Line-delimited only (one JSON object per line) — the format the JSONL
    backend writes. The native parser additionally accepts arbitrary
    inter-object whitespace.
    """
    import datetime as _dt

    from ..data.storage.event import parse_event_time

    tables: list[list[str]] = [[] for _ in range(6)]
    interns: list[dict[str, int]] = [{} for _ in range(6)]

    def intern(which: int, s: str) -> int:
        m = interns[which]
        code = m.get(s)
        if code is None:
            code = len(m)
            m[s] = code
            tables[which].append(s)
        return code

    cols = {k: [] for k in ("event", "etype", "eid", "tetype", "teid",
                            "event_id", "time_us", "rating")}
    props, span, tombstones, tombstone_pos = [], [], [], []
    epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)

    offset = 0
    for raw_line in buf.split(b"\n"):
        line = raw_line.strip()
        if not line:
            offset += len(raw_line) + 1
            continue
        lead = len(raw_line) - len(raw_line.lstrip())
        start = offset + lead
        stop = start + len(line)
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise EventParseError(f"{e} at byte {start}") from e
        offset += len(raw_line) + 1
        if not isinstance(obj, dict):
            raise EventParseError(f"expected event object at byte {start}")
        if "__tombstone__" in obj:
            tombstones.append(obj["__tombstone__"])
            tombstone_pos.append(len(cols["event"]))
            continue
        cols["event"].append(intern(0, obj["event"]) if "event" in obj else -1)
        cols["etype"].append(intern(1, obj["entityType"]) if "entityType" in obj else -1)
        cols["eid"].append(intern(2, obj["entityId"]) if "entityId" in obj else -1)
        tet, tei = obj.get("targetEntityType"), obj.get("targetEntityId")
        cols["tetype"].append(intern(3, tet) if tet is not None else -1)
        cols["teid"].append(intern(4, tei) if tei is not None else -1)
        eid = obj.get("eventId")
        cols["event_id"].append(intern(5, eid) if eid is not None else -1)
        t = obj.get("eventTime")
        if t is None:
            cols["time_us"].append(np.iinfo(np.int64).min)
        else:
            try:
                dt = parse_event_time(t)
                cols["time_us"].append(
                    int(round((dt - epoch).total_seconds() * 1e6))
                )
            except Exception:
                cols["time_us"].append(np.iinfo(np.int64).min)
        p = obj.get("properties")
        has_rating = isinstance(p, dict) and "rating" in p
        r = p.get("rating") if has_rating else None
        if isinstance(r, (int, float)) and not isinstance(r, bool):
            try:
                f = np.float32(r)  # float32-range finiteness (codec parity)
            except OverflowError:
                f = np.float32(np.inf)
            cols["rating"].append(float(f) if np.isfinite(f) else -np.inf)
        elif isinstance(r, str) and not set(r) - set("0123456789.+-eE \t\r\n"):
            # string-typed numeric rating; charset limited to what both
            # float() and strtod parse identically (no hex/inf/nan/_)
            try:
                f = np.float32(float(r))
                cols["rating"].append(float(f) if np.isfinite(f) else -np.inf)
            except (ValueError, OverflowError):
                cols["rating"].append(-np.inf)
        elif has_rating:
            # bool / null / list / dict / "1_0": present but unusable
            cols["rating"].append(-np.inf)
        else:
            cols["rating"].append(np.nan)
        if isinstance(p, dict):
            # locate the top-level "properties" key: preceding non-ws byte
            # must be '{' or ',' (an occurrence inside a string value is
            # always preceded by a backslash-escaped quote instead)
            rel = -1
            search = 0
            while True:
                cand = line.find(b'"properties"', search)
                if cand < 0:
                    break
                k = cand - 1
                while k >= 0 and line[k:k + 1] in b" \t":
                    k -= 1
                if k >= 0 and line[k:k + 1] in b"{,":
                    rel = cand
                    break
                search = cand + 1
            brace = line.index(b"{", rel) if rel >= 0 else -1
            if brace >= 0:
                pend = _scan_object_bytes(line, brace)
                props.append((start + brace, start + pend))
            else:
                props.append((-1, -1))
        else:
            props.append((-1, -1))
        span.append((start, stop))

    count = len(cols["event"])
    return ColumnarEvents(
        raw=buf,
        event=np.asarray(cols["event"], np.int32),
        etype=np.asarray(cols["etype"], np.int32),
        eid=np.asarray(cols["eid"], np.int32),
        tetype=np.asarray(cols["tetype"], np.int32),
        teid=np.asarray(cols["teid"], np.int32),
        event_id=np.asarray(cols["event_id"], np.int32),
        time_us=np.asarray(cols["time_us"], np.int64),
        rating=np.asarray(cols["rating"], np.float32),
        props=np.asarray(props, np.int64).reshape(count, 2),
        span=np.asarray(span, np.int64).reshape(count, 2),
        _tables=tables,
        tombstones=tombstones,
        tombstone_pos=np.asarray(tombstone_pos, np.int64),
    )


def parse_events(buf: bytes) -> ColumnarEvents:
    """Native when possible, Python otherwise."""
    try:
        return parse_events_jsonl(buf)
    except NativeUnavailable:
        return parse_events_jsonl_py(buf)


def ingest_batch(raw: bytes, max_items: int, creation_iso: str):
    """Validate + canonicalize a /batch/events.json body in ONE native
    pass (the ★ ingestion hot path). Returns (event_ids, jsonl_bytes) on
    the uniform happy case, or None when ANY item needs the Python path
    (validation failure, client-supplied eventId, over-cap count, syntax
    error) — the caller then re-parses in Python for exact error
    semantics. Raises NativeUnavailable when the codec is not RESIDENT:
    unlike every other entry point this one never triggers the lazy
    build — its callers (/batch handler, inline group commit) can run
    on the event loop, where a first-use g++ build would stall every
    connection for seconds. IngestBuffer warms the codec at
    construction; until someone does, callers fall back to the Python
    path exactly as if no toolchain existed."""
    import os as _os2

    lib = loaded()
    if lib is None:
        raise NativeUnavailable(
            "native codec not resident — warm it off the hot path "
            "(native.available() in a sync context) before first use")
    try:
        # Python json.loads decodes the body as strict UTF-8 before any
        # grammar check; the C scanner is byte-oriented, so invalid UTF-8
        # must bounce to the Python path here or it would be persisted.
        raw.decode("utf-8", "strict")
    except UnicodeDecodeError:
        return None
    ids_hex = _os2.urandom(16 * max_items).hex().encode()
    err = ctypes.create_string_buffer(256)
    h = lib.pio_ingest_batch(raw, len(raw), ids_hex, max_items,
                             creation_iso.encode(), err, len(err))
    if not h:
        return None
    try:
        if not lib.pio_ingest_all_ok(h):
            return None
        n = lib.pio_ingest_count(h)
        nbytes = ctypes.c_int64()
        ptr = lib.pio_ingest_lines(h, ctypes.byref(nbytes))
        lines = ctypes.string_at(ptr, nbytes.value)
        ids = [ids_hex[32 * j:32 * (j + 1)].decode() for j in range(n)]
        return ids, lines
    finally:
        lib.pio_ingest_free(h)


def cco_partition(u: np.ndarray, i: np.ndarray, rank, n_users: int,
                  u_chunk: int, n_ranges: int, n_items: int,
                  h_chunk: int, h_ranges: int):
    """One-pass C partition of deduped user-sorted (u, i) pairs into the
    CCO slab layout (ops/llr.py): ((light_eu, light_ei), (heavy_eu,
    heavy_ei) or None, item_counts). The numpy version's fancy-index
    scatter + bincounts measured ~1.0 s at 10M pairs on the 1-core
    host; this is ~10x. Requires the uint16 wire (u_chunk < 0xFFFF,
    n_items <= 0xFFFF); raises NativeUnavailable otherwise or when the
    codec cannot load — callers fall back to numpy (identical layout,
    tested)."""
    if u_chunk >= 0xFFFF or n_items > 0xFFFF or h_chunk >= 0xFFFF:
        raise NativeUnavailable("cco_partition: ids exceed the uint16 wire")
    lib = _load()
    u = np.ascontiguousarray(u, np.int32)
    i = np.ascontiguousarray(i, np.int32)
    rank_ptr = None
    if rank is not None:
        rank = np.ascontiguousarray(rank, np.int32)
        rank_ptr = rank.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    h = lib.pio_cco_partition(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        i.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        u.size, rank_ptr, n_users, u_chunk, n_ranges, n_items,
        h_chunk, h_ranges if rank is not None else 0)
    if not h:
        raise NativeUnavailable("cco_partition failed")
    try:
        le = lib.pio_ccop_dim(h, 0)
        light = tuple(
            np.ctypeslib.as_array(lib.pio_ccop_slab(h, w),
                                  shape=(n_ranges, le)).copy()
            for w in (0, 1))
        heavy = None
        if rank is not None:
            he = lib.pio_ccop_dim(h, 1)
            heavy = tuple(
                np.ctypeslib.as_array(lib.pio_ccop_slab(h, w),
                                      shape=(h_ranges, he)).copy()
                for w in (2, 3))
        counts = np.ctypeslib.as_array(
            lib.pio_ccop_item_counts(h), shape=(n_items,)).copy()
        return light, heavy, counts
    finally:
        lib.pio_ccop_free(h)

def pair_dedupe(u: np.ndarray, i: np.ndarray, n_users: int, n_items: int):
    """Distinct (user, item) pairs sorted by (user, item) + per-user
    distinct counts, via counting-sort by user + small per-user sorts —
    replaces np.unique's global comparison sort (0.39 s at 10M events on
    the 1-core host) with two linear passes. Identical output order to
    the packed-key np.unique (tested). Raises NativeUnavailable when
    the codec cannot load."""
    lib = _load()
    u = np.asarray(u)
    i = np.asarray(i)
    if u.dtype != np.int32 or i.dtype != np.int32:
        # range-check in the WIDE dtype first: an unsafe int64→int32
        # cast would wrap an out-of-range id INTO the valid range and
        # keep a pair the numpy fallback drops
        u64 = u.astype(np.int64)
        i64 = i.astype(np.int64)
        valid = ((u64 >= 0) & (u64 < n_users)
                 & (i64 >= 0) & (i64 < n_items))
        u = u64[valid].astype(np.int32)
        i = i64[valid].astype(np.int32)
    u = np.ascontiguousarray(u, np.int32)
    i = np.ascontiguousarray(i, np.int32)
    h = lib.pio_pair_dedupe(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        i.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        u.size, n_users, n_items)
    if not h:
        raise NativeUnavailable("pair_dedupe failed")
    try:
        n = lib.pio_pdd_count(h)
        if n:  # empty vectors hand back NULL data pointers
            du = np.ctypeslib.as_array(lib.pio_pdd_users(h), shape=(n,)).copy()
            di = np.ctypeslib.as_array(lib.pio_pdd_items(h), shape=(n,)).copy()
        else:
            du = np.zeros(0, np.int32)
            di = np.zeros(0, np.int32)
        per_user = (np.ctypeslib.as_array(
            lib.pio_pdd_per_user(h), shape=(n_users,)).copy()
            if n_users else np.zeros(0, np.int64))
        return du, di, per_user
    finally:
        lib.pio_pdd_free(h)
