"""Engine-facing event read APIs (reference: data/.../data/store/)."""

from .l_event_store import LEventStore
from .p_event_store import EventBatch, PEventStore

__all__ = ["EventBatch", "LEventStore", "PEventStore"]
