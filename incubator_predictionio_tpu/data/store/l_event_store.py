"""LEventStore — serve-time blocking reads of recent entity events.

Reference: data/.../data/store/LEventStore.scala — used inside predict()
for serve-time context (e.g. the e-commerce template filters recently-seen
items). Latency budget is the query hot path's, so calls take explicit
limits and time windows.
"""

from __future__ import annotations

import datetime as _dt
from typing import Optional, Sequence

from ..storage.event import Event
from ..storage.registry import Storage
from .p_event_store import _resolve_app


class LEventStore:
    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        limit: Optional[int] = None,
        latest: bool = True,
        time_window: Optional[_dt.timedelta] = None,
        storage: Optional[Storage] = None,
    ) -> list[Event]:
        s, app_id, channel_id = _resolve_app(app_name, storage, channel_name)
        start_time = None
        if time_window is not None:
            start_time = _dt.datetime.now(_dt.timezone.utc) - time_window
        return list(
            s.get_l_events().find(
                app_id,
                channel_id=channel_id,
                start_time=start_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                limit=limit,
                reversed_order=latest,
            )
        )
