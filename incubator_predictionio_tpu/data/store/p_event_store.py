"""PEventStore — bulk event reads for training DataSources.

Reference: data/.../data/store/PEventStore.scala (find/aggregateProperties
returning RDDs). The TPU-native analog returns *columnar batches*: entity
ids and values as numpy arrays plus BiMaps, ready for device sharding —
the "RDD[Event] → device array" bridge of SURVEY.md §7 step 4.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Iterator, Optional, Sequence

import numpy as np

from ..storage.bimap import BiMap
from ..storage.datamap import PropertyMap
from ..storage.event import Event
from ..storage.registry import Storage


@dataclasses.dataclass
class EventBatch:
    """Columnar view of an event scan (host side)."""

    event: list[str]
    entity_type: list[str]
    entity_id: list[str]
    target_entity_id: list[Optional[str]]
    properties: list[dict]
    event_time_us: np.ndarray  # int64 epoch micros

    def __len__(self) -> int:
        return len(self.event)


_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def _resolve_app(app_name: str, storage: Optional[Storage] = None,
                 channel_name: Optional[str] = None):
    """app name (+channel name) → ids (reference: Common.appNameToId)."""
    s = storage or Storage.instance()
    app = s.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise ValueError(f"App {app_name!r} does not exist; create it with `pio app new`")
    channel_id = None
    if channel_name:
        chans = [c for c in s.get_meta_data_channels().get_by_appid(app.id)
                 if c.name == channel_name]
        if not chans:
            raise ValueError(f"Channel {channel_name!r} not found for app {app_name!r}")
        channel_id = chans[0].id
    return s, app.id, channel_id


class PEventStore:
    """Static facade mirroring the reference object's API."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        storage: Optional[Storage] = None,
    ) -> Iterator[Event]:
        s, app_id, channel_id = _resolve_app(app_name, storage, channel_name)
        return s.get_p_events().find(
            app_id, channel_id, start_time, until_time, entity_type,
            entity_id, event_names, target_entity_type, target_entity_id,
        )

    @staticmethod
    def find_batches(
        app_name: str,
        event_names: Optional[Sequence[str]] = None,
        storage: Optional[Storage] = None,
        chunk_size: int = 65536,
        **kwargs,
    ) -> Iterator[EventBatch]:
        """Chunked columnar scan: yields EventBatch slices of at most
        ``chunk_size`` events in scan order. This is the batch iterator
        the streaming input pipeline's featurize workers pull from
        (workflow/input_pipeline.prefetch) — decode of chunk N+1
        overlaps featurize/upload of chunk N instead of the whole scan
        materializing first. Concatenating the chunks reproduces
        find_batch exactly.

        A training read that passes no time range fills it from the
        ambient training window (``pio train --window`` /
        ``PIO_TRAIN_WINDOW``); explicit bounds are never
        overridden."""
        from ...common import train_window

        start, until = train_window.apply_window(
            kwargs.get("start_time"), kwargs.get("until_time"))
        if start is not None or until is not None:
            kwargs = dict(kwargs, start_time=start, until_time=until)
        events = PEventStore.find(
            app_name, event_names=event_names, storage=storage, **kwargs
        )
        step = max(1, int(chunk_size))
        ev, et, eid, tid, props, times = [], [], [], [], [], []

        def flush() -> EventBatch:
            return EventBatch(
                event=ev, entity_type=et, entity_id=eid,
                target_entity_id=tid, properties=props,
                event_time_us=np.asarray(times, dtype=np.int64),
            )

        for e in events:
            ev.append(e.event)
            et.append(e.entity_type)
            eid.append(e.entity_id)
            tid.append(e.target_entity_id)
            props.append(e.properties.to_dict())
            times.append(
                int((e.event_time - _EPOCH).total_seconds() * 1_000_000)
            )
            if len(ev) >= step:
                yield flush()
                ev, et, eid, tid, props, times = [], [], [], [], [], []
        if ev:
            yield flush()

    @staticmethod
    def find_batch(
        app_name: str,
        event_names: Optional[Sequence[str]] = None,
        storage: Optional[Storage] = None,
        **kwargs,
    ) -> EventBatch:
        """Columnar scan (the hot path for DataSources) — the
        concatenation of find_batches."""
        ev, et, eid, tid, props = [], [], [], [], []
        times: list[np.ndarray] = []
        for b in PEventStore.find_batches(
                app_name, event_names=event_names, storage=storage, **kwargs):
            ev += b.event
            et += b.entity_type
            eid += b.entity_id
            tid += b.target_entity_id
            props += b.properties
            times.append(b.event_time_us)
        return EventBatch(
            event=ev, entity_type=et, entity_id=eid, target_entity_id=tid,
            properties=props,
            event_time_us=(np.concatenate(times) if times
                           else np.asarray([], dtype=np.int64)),
        )

    @staticmethod
    def find_ratings(
        app_name: str,
        event_names: Optional[Sequence[str]] = None,
        rating_from_props: bool = True,
        default_rating: float = 1.0,
        event_default_ratings: Optional[dict] = None,
        storage: Optional[Storage] = None,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, BiMap, BiMap]:
        """(user, item, rating) COO triple + id maps — the shared prep for
        every recommendation-family template.

        Fast path: when the event backend exposes a columnar scan (the
        JSONL log decoded by the native codec — data/storage/jsonl.py),
        the triple is assembled with pure numpy on interned codes, never
        materializing per-event Python objects. Otherwise falls back to
        the row-based scan + ``ratings_matrix``.

        ``event_default_ratings`` assigns a rating to events of a given
        name when properties carry none (e.g. the quickstart template's
        implicit "buy" → 4.0).

        When neither ``start_time`` nor ``until_time`` is given the
        ambient training window (``pio train --window`` /
        ``PIO_TRAIN_WINDOW``) applies; explicit bounds win.
        """
        from ...common import train_window

        start_time, until_time = train_window.apply_window(
            start_time, until_time)
        s, app_id, channel_id = _resolve_app(app_name, storage, channel_name)
        pe = s.get_p_events()
        if hasattr(pe, "scan_columnar"):
            cols, rows = pe.scan_columnar(
                app_id, channel_id, event_names, start_time, until_time
            )
            rows = rows[cols.eid[rows] >= 0]  # malformed records: no entityId
            # The row path iterates events time-sorted (LEvents.find
            # semantics); order the selection the same way so BiMap
            # first-seen index assignment matches bit-for-bit.
            rows = rows[np.argsort(cols.time_us[rows], kind="stable")]
            # BiMap membership and index order must match the row path
            # exactly: users cover ALL scanned events (even target-less
            # ones), items only events with a target; both indexed in
            # first-seen order within the selection (BiMap.string_int).
            keep_mask = cols.teid[rows] >= 0
            keep = rows[keep_mask]
            if rating_from_props:
                r = cols.rating[keep].astype(np.float32, copy=True)
                # Codec sentinel semantics: NaN = "rating" key absent
                # (event-default applies, like the row path injecting into
                # properties), -inf = key present but not coercible
                # (row path's _coerce → plain default_rating).
                missing = np.isnan(r)
                unusable = np.isneginf(r)
                if unusable.any():
                    r[unusable] = np.float32(default_rating)
                if missing.any():
                    fill = np.full(keep.shape, np.float32(default_rating))
                    if event_default_ratings:
                        ev_table = cols.table(cols.TABLE_EVENT)
                        ev = cols.event[keep]
                        for name, val in event_default_ratings.items():
                            if name in ev_table:
                                fill = np.where(
                                    ev == ev_table.index(name),
                                    np.float32(val), fill,
                                )
                    r[missing] = fill[missing]
            else:
                r = np.full(keep.shape, default_rating, np.float32)

            def densify(codes: np.ndarray, table: list[str]):
                uniq, first_pos, inv = np.unique(
                    codes, return_index=True, return_inverse=True
                )
                order = np.argsort(first_pos, kind="stable")
                rank = np.empty(order.shape, np.int64)
                rank[order] = np.arange(order.shape[0])
                bimap = BiMap({table[c]: int(k)
                               for k, c in enumerate(uniq[order])})
                return rank[inv], bimap

            u_all, users = densify(cols.eid[rows], cols.table(cols.TABLE_EID))
            u = u_all[keep_mask]
            i, items = densify(cols.teid[keep], cols.table(cols.TABLE_TEID))
            return u.astype(np.int32), i.astype(np.int32), r, users, items

        batch = PEventStore.find_batch(
            app_name, event_names=event_names, storage=storage,
            channel_name=channel_name, start_time=start_time,
            until_time=until_time,
        )
        if rating_from_props and event_default_ratings:
            for j, ev in enumerate(batch.event):
                dflt = event_default_ratings.get(ev)
                if dflt is not None and "rating" not in batch.properties[j]:
                    batch.properties[j] = {**batch.properties[j], "rating": dflt}
        return ratings_matrix(
            batch, rating_from_props=rating_from_props,
            default_rating=default_rating,
        )

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
        storage: Optional[Storage] = None,
    ) -> dict[str, PropertyMap]:
        s, app_id, channel_id = _resolve_app(app_name, storage, channel_name)
        return s.get_p_events().aggregate_properties(
            app_id, entity_type, channel_id, start_time, until_time, required
        )


def ratings_matrix(
    batch: EventBatch,
    rating_from_props: bool = True,
    default_rating: float = 1.0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, BiMap, BiMap]:
    """(user, item, rating) COO triple + id maps from a columnar batch —
    the shared prep for every recommendation-family template."""
    users = BiMap.string_int(batch.entity_id)
    items = BiMap.string_int(t for t in batch.target_entity_id if t is not None)
    u = users.map_array(batch.entity_id)
    i = np.fromiter(
        (items(t) if t is not None else -1 for t in batch.target_entity_id),
        dtype=np.int32,
        count=len(batch),
    )
    if rating_from_props:
        def _coerce(v) -> float:
            # Must mirror the columnar codec exactly (fast/slow parity):
            # bool/None, strings outside the common float()/strtod charset
            # (hex, inf, nan, "1_0"), and values non-finite after the
            # float32 cast all count as "present but unusable".
            if isinstance(v, bool) or v is None:
                return default_rating
            if isinstance(v, str) and set(v) - set("0123456789.+-eE \t\r\n"):
                return default_rating
            try:
                f = np.float32(float(v))
            except (TypeError, ValueError, OverflowError):
                return default_rating
            return float(f) if np.isfinite(f) else default_rating

        r = np.fromiter(
            (_coerce(p.get("rating", default_rating)) for p in batch.properties),
            dtype=np.float32,
            count=len(batch),
        )
    else:
        r = np.full(len(batch), default_rating, dtype=np.float32)
    keep = i >= 0
    return u[keep], i[keep], r[keep], users, items
