"""PEventStore — bulk event reads for training DataSources.

Reference: data/.../data/store/PEventStore.scala (find/aggregateProperties
returning RDDs). The TPU-native analog returns *columnar batches*: entity
ids and values as numpy arrays plus BiMaps, ready for device sharding —
the "RDD[Event] → device array" bridge of SURVEY.md §7 step 4.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
from typing import Iterator, Optional, Sequence

import numpy as np

from ..storage.bimap import BiMap
from ..storage.datamap import PropertyMap
from ..storage.event import Event
from ..storage.registry import Storage


@dataclasses.dataclass
class EventBatch:
    """Columnar view of an event scan (host side)."""

    event: list[str]
    entity_type: list[str]
    entity_id: list[str]
    target_entity_id: list[Optional[str]]
    properties: list[dict]
    event_time_us: np.ndarray  # int64 epoch micros

    def __len__(self) -> int:
        return len(self.event)


_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)


def _resolve_app(app_name: str, storage: Optional[Storage] = None,
                 channel_name: Optional[str] = None):
    """app name (+channel name) → ids (reference: Common.appNameToId)."""
    s = storage or Storage.instance()
    app = s.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise ValueError(f"App {app_name!r} does not exist; create it with `pio app new`")
    channel_id = None
    if channel_name:
        chans = [c for c in s.get_meta_data_channels().get_by_appid(app.id)
                 if c.name == channel_name]
        if not chans:
            raise ValueError(f"Channel {channel_name!r} not found for app {app_name!r}")
        channel_id = chans[0].id
    return s, app.id, channel_id


class PEventStore:
    """Static facade mirroring the reference object's API."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Optional[str] = None,
        target_entity_id: Optional[str] = None,
        storage: Optional[Storage] = None,
    ) -> Iterator[Event]:
        s, app_id, channel_id = _resolve_app(app_name, storage, channel_name)
        return s.get_p_events().find(
            app_id, channel_id, start_time, until_time, entity_type,
            entity_id, event_names, target_entity_type, target_entity_id,
        )

    @staticmethod
    def find_batch(
        app_name: str,
        event_names: Optional[Sequence[str]] = None,
        storage: Optional[Storage] = None,
        **kwargs,
    ) -> EventBatch:
        """Columnar scan (the hot path for DataSources)."""
        events = PEventStore.find(
            app_name, event_names=event_names, storage=storage, **kwargs
        )
        ev, et, eid, tid, props, times = [], [], [], [], [], []
        for e in events:
            ev.append(e.event)
            et.append(e.entity_type)
            eid.append(e.entity_id)
            tid.append(e.target_entity_id)
            props.append(e.properties.to_dict())
            times.append(
                int((e.event_time - _EPOCH).total_seconds() * 1_000_000)
            )
        return EventBatch(
            event=ev, entity_type=et, entity_id=eid, target_entity_id=tid,
            properties=props,
            event_time_us=np.asarray(times, dtype=np.int64),
        )

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
        storage: Optional[Storage] = None,
    ) -> dict[str, PropertyMap]:
        s, app_id, channel_id = _resolve_app(app_name, storage, channel_name)
        return s.get_p_events().aggregate_properties(
            app_id, entity_type, channel_id, start_time, until_time, required
        )


def ratings_matrix(
    batch: EventBatch,
    rating_from_props: bool = True,
    default_rating: float = 1.0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, BiMap, BiMap]:
    """(user, item, rating) COO triple + id maps from a columnar batch —
    the shared prep for every recommendation-family template."""
    users = BiMap.string_int(batch.entity_id)
    items = BiMap.string_int(t for t in batch.target_entity_id if t is not None)
    u = users.map_array(batch.entity_id)
    i = np.fromiter(
        (items(t) if t is not None else -1 for t in batch.target_entity_id),
        dtype=np.int32,
        count=len(batch),
    )
    if rating_from_props:
        r = np.fromiter(
            (float(p.get("rating", default_rating)) for p in batch.properties),
            dtype=np.float32,
            count=len(batch),
        )
    else:
        r = np.full(len(batch), default_rating, dtype=np.float32)
    keep = i >= 0
    return u[keep], i[keep], r[keep], users, items
